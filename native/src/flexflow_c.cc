// Flat C API implementation: embeds CPython and drives the Python core
// through flexflow_tpu.capi_shim (see native/include/flexflow_c.h for the
// design note; reference: python/flexflow_c.cc — the same surface in the
// opposite direction).

#include "../include/flexflow_c.h"

#include <Python.h>

#include <chrono>
#include <cmath>
#include <cstdio>
#include <vector>

namespace {

PyObject *g_shim = nullptr;  // flexflow_tpu.capi_shim module

void print_error() {
  if (PyErr_Occurred()) PyErr_Print();
}

// Call shim.<fn>(*args); returns a NEW reference or nullptr.
PyObject *shim_call(const char *fn, PyObject *args) {
  if (g_shim == nullptr) {
    std::fprintf(stderr, "flexflow_c: flexflow_init() not called\n");
    Py_XDECREF(args);
    return nullptr;
  }
  if (args == nullptr) {
    // a failed Py_BuildValue (e.g. a NULL handle from an earlier failed
    // call formatted with "O") — surface that instead of calling the shim
    // with no arguments
    std::fprintf(stderr, "flexflow_c: %s called with invalid handle\n", fn);
    print_error();
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(g_shim, fn);
  if (f == nullptr) {
    print_error();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (out == nullptr) print_error();
  return out;
}

// shim call returning int (discarding the Python result); 0 on success
int shim_call_status(const char *fn, PyObject *args) {
  PyObject *out = shim_call(fn, args);
  if (out == nullptr) return 1;
  Py_DECREF(out);
  return 0;
}

long shim_call_long(const char *fn, PyObject *args, long on_error) {
  PyObject *out = shim_call(fn, args);
  if (out == nullptr) return on_error;
  long v = PyLong_AsLong(out);
  Py_DECREF(out);
  if (PyErr_Occurred()) {
    print_error();
    return on_error;
  }
  return v;
}

double shim_call_double(const char *fn, PyObject *args) {
  PyObject *out = shim_call(fn, args);
  if (out == nullptr) return NAN;
  double v = PyFloat_AsDouble(out);
  Py_DECREF(out);
  if (PyErr_Occurred()) {
    print_error();
    return NAN;
  }
  return v;
}

PyObject *int_list(const int *v, int n) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i) PyList_SET_ITEM(l, i, PyLong_FromLong(v[i]));
  return l;
}

PyObject *int64_list(const int64_t *v, int n) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(l, i, PyLong_FromLongLong(v[i]));
  return l;
}

PyObject *none_or(PyObject *h) {
  if (h == nullptr) Py_RETURN_NONE;
  Py_INCREF(h);
  return h;
}

}  // namespace

extern "C" {

int flexflow_init(int argc, char **argv) {
  if (g_shim != nullptr) return 0;
  if (!Py_IsInitialized()) Py_InitializeEx(0);
  // make the working directory importable (the embedded interpreter has no
  // script directory on sys.path)
  PyRun_SimpleString("import sys, os; sys.path.insert(0, os.getcwd())");
  g_shim = PyImport_ImportModule("flexflow_tpu.capi_shim");
  if (g_shim == nullptr) {
    print_error();
    return 1;
  }
  (void)argc;
  (void)argv;
  return 0;
}

void flexflow_finalize(void) {
  Py_XDECREF(g_shim);
  g_shim = nullptr;
  if (Py_IsInitialized()) Py_FinalizeEx();
}

double flexflow_get_current_time(void) {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

/* config ---------------------------------------------------------------- */

flexflow_config_t flexflow_config_create(int argc, char **argv) {
  PyObject *l = PyList_New(argc);
  for (int i = 0; i < argc; ++i)
    PyList_SET_ITEM(l, i, PyUnicode_FromString(argv[i]));
  return shim_call("config_create", Py_BuildValue("(N)", l));
}

#define CONFIG_GET(name)                                            \
  int flexflow_config_get_##name(flexflow_config_t config) {        \
    return (int)shim_call_long("config_get_" #name,                 \
                               Py_BuildValue("(O)", (PyObject *)config), -1); \
  }
CONFIG_GET(batch_size)
CONFIG_GET(epochs)
CONFIG_GET(num_nodes)
CONFIG_GET(workers_per_node)
#undef CONFIG_GET

void flexflow_config_destroy(flexflow_config_t config) {
  Py_XDECREF((PyObject *)config);
}

/* model ----------------------------------------------------------------- */

flexflow_model_t flexflow_model_create(flexflow_config_t config) {
  return shim_call("model_create",
                   Py_BuildValue("(O)", (PyObject *)config));
}

void flexflow_model_destroy(flexflow_model_t model) {
  Py_XDECREF((PyObject *)model);
}

/* tensors --------------------------------------------------------------- */

flexflow_tensor_t flexflow_tensor_create_ex(flexflow_model_t model, int ndims,
                                            const int *dims, int dtype,
                                            const char *name) {
  return shim_call(
      "tensor_create",
      Py_BuildValue("(ONis)", (PyObject *)model, int_list(dims, ndims), dtype,
                    name ? name : ""));
}

flexflow_tensor_t flexflow_tensor_create(flexflow_model_t model, int ndims,
                                         const int *dims, const char *name) {
  return flexflow_tensor_create_ex(model, ndims, dims, 0, name);
}

int flexflow_tensor_get_num_dims(flexflow_tensor_t tensor) {
  return (int)shim_call_long("tensor_num_dims",
                             Py_BuildValue("(O)", (PyObject *)tensor), -1);
}

int flexflow_tensor_get_dims(flexflow_tensor_t tensor, int *dims,
                             int max_dims) {
  PyObject *out =
      shim_call("tensor_dims", Py_BuildValue("(O)", (PyObject *)tensor));
  if (out == nullptr) return -1;
  int n = (int)PyList_Size(out);
  for (int i = 0; i < n && i < max_dims; ++i)
    dims[i] = (int)PyLong_AsLong(PyList_GetItem(out, i));
  Py_DECREF(out);
  return n;
}

int flexflow_tensor_get_data_type(flexflow_tensor_t tensor) {
  return (int)shim_call_long("tensor_dtype",
                             Py_BuildValue("(O)", (PyObject *)tensor), -1);
}

flexflow_op_t flexflow_tensor_get_owner_op(flexflow_tensor_t tensor) {
  return shim_call("tensor_owner_op",
                   Py_BuildValue("(O)", (PyObject *)tensor));
}

void flexflow_tensor_destroy(flexflow_tensor_t tensor) {
  Py_XDECREF((PyObject *)tensor);
}

int flexflow_tensor_attach_raw_ptr(flexflow_model_t model,
                                   flexflow_tensor_t tensor, const void *ptr,
                                   const int64_t *shape, int ndims,
                                   int is_int) {
  return shim_call_status(
      "tensor_attach_raw_ptr",
      Py_BuildValue("(OOKNi)", (PyObject *)model, (PyObject *)tensor,
                    (unsigned long long)(uintptr_t)ptr,
                    int64_list(shape, ndims), is_int));
}

int flexflow_tensor_detach_raw_ptr(flexflow_model_t model,
                                   flexflow_tensor_t tensor) {
  return shim_call_status(
      "tensor_detach_raw_ptr",
      Py_BuildValue("(OO)", (PyObject *)model, (PyObject *)tensor));
}

/* initializers ---------------------------------------------------------- */

flexflow_initializer_t flexflow_glorot_uniform_initializer_create(int seed) {
  return shim_call("initializer_create",
                   Py_BuildValue("(siddd)", "glorot", seed, 0.0, 0.0, 0.0));
}
flexflow_initializer_t flexflow_zero_initializer_create(void) {
  return shim_call("initializer_create",
                   Py_BuildValue("(siddd)", "zero", 0, 0.0, 0.0, 0.0));
}
flexflow_initializer_t flexflow_uniform_initializer_create(int seed,
                                                           float min_val,
                                                           float max_val) {
  return shim_call(
      "initializer_create",
      Py_BuildValue("(siddd)", "uniform", seed, (double)min_val,
                    (double)max_val, 0.0));
}
flexflow_initializer_t flexflow_norm_initializer_create(int seed, float mean,
                                                        float stddev) {
  return shim_call("initializer_create",
                   Py_BuildValue("(siddd)", "norm", seed, (double)mean,
                                 (double)stddev, 0.0));
}
flexflow_initializer_t flexflow_constant_initializer_create(float value) {
  return shim_call(
      "initializer_create",
      Py_BuildValue("(siddd)", "constant", 0, (double)value, 0.0, 0.0));
}
void flexflow_initializer_destroy(flexflow_initializer_t handle) {
  Py_XDECREF((PyObject *)handle);
}

/* optimizers ------------------------------------------------------------ */

flexflow_sgd_optimizer_t flexflow_sgd_optimizer_create(flexflow_model_t model,
                                                       double lr,
                                                       double momentum,
                                                       int nesterov,
                                                       double weight_decay) {
  (void)model;  // reference passes the model; ours binds at compile
  return shim_call("sgd_optimizer_create",
                   Py_BuildValue("(ddid)", lr, momentum, nesterov,
                                 weight_decay));
}

void flexflow_sgd_optimizer_set_lr(flexflow_sgd_optimizer_t handle,
                                   double lr) {
  shim_call_status("optimizer_set_lr",
                   Py_BuildValue("(Od)", (PyObject *)handle, lr));
}

flexflow_adam_optimizer_t flexflow_adam_optimizer_create(
    flexflow_model_t model, double alpha, double beta1, double beta2,
    double weight_decay, double epsilon) {
  (void)model;
  return shim_call("adam_optimizer_create",
                   Py_BuildValue("(ddddd)", alpha, beta1, beta2,
                                 weight_decay, epsilon));
}

void flexflow_adam_optimizer_set_lr(flexflow_adam_optimizer_t handle,
                                    double lr) {
  shim_call_status("optimizer_set_lr",
                   Py_BuildValue("(Od)", (PyObject *)handle, lr));
}

int flexflow_model_set_sgd_optimizer(flexflow_model_t model,
                                     flexflow_sgd_optimizer_t handle) {
  return shim_call_status(
      "model_set_optimizer",
      Py_BuildValue("(OO)", (PyObject *)model, (PyObject *)handle));
}

int flexflow_model_set_adam_optimizer(flexflow_model_t model,
                                      flexflow_adam_optimizer_t handle) {
  return shim_call_status(
      "model_set_optimizer",
      Py_BuildValue("(OO)", (PyObject *)model, (PyObject *)handle));
}

void flexflow_sgd_optimizer_destroy(flexflow_sgd_optimizer_t handle) {
  Py_XDECREF((PyObject *)handle);
}
void flexflow_adam_optimizer_destroy(flexflow_adam_optimizer_t handle) {
  Py_XDECREF((PyObject *)handle);
}

/* layer builders -------------------------------------------------------- */

flexflow_tensor_t flexflow_model_add_dense(flexflow_model_t model,
                                           flexflow_tensor_t input,
                                           int out_features, int activation,
                                           int use_bias) {
  return flexflow_model_add_dense_ex(model, input, out_features, activation,
                                     use_bias, nullptr, nullptr);
}

flexflow_tensor_t flexflow_model_add_dense_ex(
    flexflow_model_t model, flexflow_tensor_t input, int out_features,
    int activation, int use_bias, flexflow_initializer_t kernel_init,
    flexflow_initializer_t bias_init) {
  return shim_call(
      "add_dense",
      Py_BuildValue("(OOiiiNN)", (PyObject *)model, (PyObject *)input,
                    out_features, activation, use_bias,
                    none_or((PyObject *)kernel_init),
                    none_or((PyObject *)bias_init)));
}

flexflow_tensor_t flexflow_model_add_conv2d(flexflow_model_t model,
                                            flexflow_tensor_t input,
                                            int out_channels, int kernel_h,
                                            int kernel_w, int stride_h,
                                            int stride_w, int padding_h,
                                            int padding_w, int activation) {
  return flexflow_model_add_conv2d_ex(model, input, out_channels, kernel_h,
                                      kernel_w, stride_h, stride_w, padding_h,
                                      padding_w, activation, 1, 1, nullptr,
                                      nullptr);
}

flexflow_tensor_t flexflow_model_add_conv2d_ex(
    flexflow_model_t model, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w, int padding_h,
    int padding_w, int activation, int groups, int use_bias,
    flexflow_initializer_t kernel_init, flexflow_initializer_t bias_init) {
  return shim_call(
      "add_conv2d",
      Py_BuildValue("(OOiiiiiiiiiiNN)", (PyObject *)model, (PyObject *)input,
                    out_channels, kernel_h, kernel_w, stride_h, stride_w,
                    padding_h, padding_w, activation, groups, use_bias,
                    none_or((PyObject *)kernel_init),
                    none_or((PyObject *)bias_init)));
}

flexflow_tensor_t flexflow_model_add_pool2d(flexflow_model_t model,
                                            flexflow_tensor_t input,
                                            int kernel_h, int kernel_w,
                                            int stride_h, int stride_w,
                                            int padding_h, int padding_w,
                                            int pool_type) {
  return shim_call(
      "add_pool2d",
      Py_BuildValue("(OOiiiiiii)", (PyObject *)model, (PyObject *)input,
                    kernel_h, kernel_w, stride_h, stride_w, padding_h,
                    padding_w, pool_type));
}

flexflow_tensor_t flexflow_model_add_flat(flexflow_model_t model,
                                          flexflow_tensor_t input) {
  return shim_call("add_flat", Py_BuildValue("(OO)", (PyObject *)model,
                                             (PyObject *)input));
}

flexflow_tensor_t flexflow_model_add_embedding(flexflow_model_t model,
                                               flexflow_tensor_t input,
                                               int num_entries, int out_dim) {
  return flexflow_model_add_embedding_ex(model, input, num_entries, out_dim,
                                         0, nullptr);
}

flexflow_tensor_t flexflow_model_add_embedding_ex(
    flexflow_model_t model, flexflow_tensor_t input, int num_entries,
    int out_dim, int aggr, flexflow_initializer_t kernel_init) {
  return shim_call(
      "add_embedding",
      Py_BuildValue("(OOiiiN)", (PyObject *)model, (PyObject *)input,
                    num_entries, out_dim, aggr,
                    none_or((PyObject *)kernel_init)));
}

flexflow_tensor_t flexflow_model_add_multihead_attention(
    flexflow_model_t model, flexflow_tensor_t query, flexflow_tensor_t key,
    flexflow_tensor_t value, int embed_dim, int num_heads) {
  return flexflow_model_add_multihead_attention_ex(
      model, query, key, value, embed_dim, num_heads, 0, 0, 0.0f, 1, 0);
}

flexflow_tensor_t flexflow_model_add_multihead_attention_ex(
    flexflow_model_t model, flexflow_tensor_t query, flexflow_tensor_t key,
    flexflow_tensor_t value, int embed_dim, int num_heads, int kdim, int vdim,
    float dropout, int bias, int causal) {
  return shim_call(
      "add_multihead_attention",
      Py_BuildValue("(OOOOiiiifii)", (PyObject *)model, (PyObject *)query,
                    (PyObject *)key, (PyObject *)value, embed_dim, num_heads,
                    kdim, vdim, dropout, bias, causal));
}

flexflow_tensor_t flexflow_model_add_batch_matmul(flexflow_model_t model,
                                                  flexflow_tensor_t a,
                                                  flexflow_tensor_t b) {
  return shim_call("add_batch_matmul",
                   Py_BuildValue("(OOO)", (PyObject *)model, (PyObject *)a,
                                 (PyObject *)b));
}

flexflow_tensor_t flexflow_model_add_batch_norm(flexflow_model_t model,
                                                flexflow_tensor_t input,
                                                int relu) {
  return shim_call("add_batch_norm",
                   Py_BuildValue("(OOi)", (PyObject *)model,
                                 (PyObject *)input, relu));
}

flexflow_tensor_t flexflow_model_add_layer_norm(flexflow_model_t model,
                                                flexflow_tensor_t input,
                                                int n_axes, const int *axes,
                                                int elementwise_affine,
                                                float eps) {
  return shim_call(
      "add_layer_norm",
      Py_BuildValue("(OONif)", (PyObject *)model, (PyObject *)input,
                    int_list(axes, n_axes), elementwise_affine, eps));
}

flexflow_tensor_t flexflow_model_add_concat(flexflow_model_t model,
                                            int n_tensors,
                                            const flexflow_tensor_t *tensors,
                                            int axis) {
  PyObject *l = PyList_New(n_tensors);
  for (int i = 0; i < n_tensors; ++i) {
    PyObject *t = (PyObject *)tensors[i];
    Py_INCREF(t);
    PyList_SET_ITEM(l, i, t);
  }
  return shim_call("add_concat",
                   Py_BuildValue("(ONi)", (PyObject *)model, l, axis));
}

int flexflow_model_add_split(flexflow_model_t model, flexflow_tensor_t input,
                             int n, const int *sizes, int axis,
                             flexflow_tensor_t *outputs) {
  PyObject *out = shim_call(
      "add_split",
      Py_BuildValue("(OONi)", (PyObject *)model, (PyObject *)input,
                    int_list(sizes, n), axis));
  if (out == nullptr || !PyList_Check(out)) {
    Py_XDECREF(out);
    return 1;
  }
  int m = (int)PyList_Size(out);
  if (m != n) {
    // nothing is written on a count mismatch: the caller owns no handles
    // and outputs[] stays untouched
    Py_DECREF(out);
    return 1;
  }
  for (int i = 0; i < n; ++i) {
    PyObject *t = PyList_GetItem(out, i);
    Py_INCREF(t);
    outputs[i] = t;
  }
  Py_DECREF(out);
  return 0;
}

flexflow_tensor_t flexflow_model_add_reshape(flexflow_model_t model,
                                             flexflow_tensor_t input,
                                             int ndims, const int *dims) {
  return shim_call("add_reshape",
                   Py_BuildValue("(OON)", (PyObject *)model,
                                 (PyObject *)input, int_list(dims, ndims)));
}

flexflow_tensor_t flexflow_model_add_transpose(flexflow_model_t model,
                                               flexflow_tensor_t input,
                                               int ndims, const int *perm) {
  return shim_call("add_transpose",
                   Py_BuildValue("(OON)", (PyObject *)model,
                                 (PyObject *)input, int_list(perm, ndims)));
}

flexflow_tensor_t flexflow_model_add_reverse(flexflow_model_t model,
                                             flexflow_tensor_t input,
                                             int axis) {
  return shim_call("add_reverse",
                   Py_BuildValue("(OOi)", (PyObject *)model,
                                 (PyObject *)input, axis));
}

flexflow_tensor_t flexflow_model_add_mean(flexflow_model_t model,
                                          flexflow_tensor_t input,
                                          int n_dims, const int *dims,
                                          int keepdims) {
  return shim_call("add_mean",
                   Py_BuildValue("(OONi)", (PyObject *)model,
                                 (PyObject *)input, int_list(dims, n_dims),
                                 keepdims));
}

flexflow_tensor_t flexflow_model_add_reduce_sum(flexflow_model_t model,
                                                flexflow_tensor_t input,
                                                int n_dims, const int *dims,
                                                int keepdims) {
  return shim_call("add_reduce_sum",
                   Py_BuildValue("(OONi)", (PyObject *)model,
                                 (PyObject *)input, int_list(dims, n_dims),
                                 keepdims));
}

flexflow_tensor_t flexflow_model_add_cast(flexflow_model_t model,
                                          flexflow_tensor_t input,
                                          int dtype) {
  return shim_call("add_cast",
                   Py_BuildValue("(OOi)", (PyObject *)model,
                                 (PyObject *)input, dtype));
}

flexflow_tensor_t flexflow_model_add_softmax(flexflow_model_t model,
                                             flexflow_tensor_t input) {
  return shim_call("add_softmax", Py_BuildValue("(OO)", (PyObject *)model,
                                                (PyObject *)input));
}

flexflow_tensor_t flexflow_model_add_dropout(flexflow_model_t model,
                                             flexflow_tensor_t input,
                                             float rate) {
  return shim_call("add_dropout",
                   Py_BuildValue("(OOf)", (PyObject *)model,
                                 (PyObject *)input, rate));
}

#define UNARY(name)                                                         \
  flexflow_tensor_t flexflow_model_add_##name(flexflow_model_t model,       \
                                              flexflow_tensor_t input) {    \
    return shim_call("add_unary",                                           \
                     Py_BuildValue("(OsO)", (PyObject *)model, #name,       \
                                   (PyObject *)input));                     \
  }
UNARY(relu)
UNARY(sigmoid)
UNARY(tanh)
UNARY(elu)
UNARY(gelu)
UNARY(identity)
UNARY(exp)
UNARY(sin)
UNARY(cos)
UNARY(rsqrt)
#undef UNARY

flexflow_tensor_t flexflow_model_add_pow(flexflow_model_t model,
                                         flexflow_tensor_t input,
                                         float exponent) {
  return shim_call("add_scalar_op",
                   Py_BuildValue("(OsOf)", (PyObject *)model, "pow",
                                 (PyObject *)input, exponent));
}

#define SCALAR(name)                                                        \
  flexflow_tensor_t flexflow_model_add_scalar_##name(                       \
      flexflow_model_t model, flexflow_tensor_t input, float scalar) {      \
    return shim_call("add_scalar_op",                                       \
                     Py_BuildValue("(OsOf)", (PyObject *)model,             \
                                   "scalar_" #name, (PyObject *)input,      \
                                   scalar));                                \
  }
SCALAR(add)
SCALAR(sub)
SCALAR(multiply)
SCALAR(truediv)
#undef SCALAR

#define BINARY(name, pyname)                                                \
  flexflow_tensor_t flexflow_model_add_##name(                              \
      flexflow_model_t model, flexflow_tensor_t a, flexflow_tensor_t b) {   \
    return shim_call("add_binary",                                          \
                     Py_BuildValue("(OsOO)", (PyObject *)model, pyname,     \
                                   (PyObject *)a, (PyObject *)b));          \
  }
BINARY(add, "add")
BINARY(subtract, "subtract")
BINARY(multiply, "multiply")
BINARY(divide, "divide")
#undef BINARY

flexflow_tensor_t flexflow_model_add_unary(flexflow_model_t model,
                                           const char *op,
                                           flexflow_tensor_t input) {
  return shim_call("add_unary", Py_BuildValue("(OsO)", (PyObject *)model, op,
                                              (PyObject *)input));
}

flexflow_tensor_t flexflow_model_add_binary(flexflow_model_t model,
                                            const char *op,
                                            flexflow_tensor_t a,
                                            flexflow_tensor_t b) {
  return shim_call("add_binary",
                   Py_BuildValue("(OsOO)", (PyObject *)model, op,
                                 (PyObject *)a, (PyObject *)b));
}

/* compile / train -------------------------------------------------------- */

int flexflow_model_compile(flexflow_model_t model, const char *loss,
                           const char *metrics, double learning_rate) {
  return shim_call_status(
      "compile_model",
      Py_BuildValue("(Ossd)", (PyObject *)model, loss ? loss : "",
                    metrics ? metrics : "", learning_rate));
}

double flexflow_model_fit(flexflow_model_t model, const float *x,
                          const int64_t *x_shape, int x_ndims, const void *y,
                          const int64_t *y_shape, int y_ndims, int y_is_int,
                          int epochs) {
  return shim_call_double(
      "fit_ptr",
      Py_BuildValue("(OKNKNii)", (PyObject *)model,
                    (unsigned long long)(uintptr_t)x,
                    int64_list(x_shape, x_ndims),
                    (unsigned long long)(uintptr_t)y,
                    int64_list(y_shape, y_ndims), y_is_int, epochs));
}

#define MODEL_VERB(name)                                           \
  int flexflow_model_##name(flexflow_model_t model) {              \
    return shim_call_status("model_" #name,                        \
                            Py_BuildValue("(O)", (PyObject *)model)); \
  }
MODEL_VERB(init_layers)
MODEL_VERB(forward)
MODEL_VERB(zero_gradients)
MODEL_VERB(backward)
MODEL_VERB(update)
MODEL_VERB(reset_metrics)
MODEL_VERB(compute_metrics)
MODEL_VERB(print_layers)
#undef MODEL_VERB

void flexflow_begin_trace(flexflow_model_t model, int trace_id) {
  (void)model;
  (void)trace_id;  // subsumed by jit compile caching (SURVEY §5)
}
void flexflow_end_trace(flexflow_model_t model, int trace_id) {
  (void)model;
  (void)trace_id;
}

double flexflow_model_get_last_loss(flexflow_model_t model) {
  return shim_call_double("model_last_loss",
                          Py_BuildValue("(O)", (PyObject *)model));
}

/* metrics ---------------------------------------------------------------- */

flexflow_perf_metrics_t flexflow_model_get_perf_metrics(
    flexflow_model_t model) {
  return shim_call("model_perf_metrics",
                   Py_BuildValue("(O)", (PyObject *)model));
}

double flexflow_per_metrics_get_accuracy(flexflow_perf_metrics_t handle) {
  return shim_call_double("perf_metrics_accuracy",
                          Py_BuildValue("(O)", (PyObject *)handle));
}

void flexflow_per_metrics_destroy(flexflow_perf_metrics_t handle) {
  Py_XDECREF((PyObject *)handle);
}

/* layer / parameter introspection ----------------------------------------- */

int flexflow_model_get_num_layers(flexflow_model_t model) {
  return (int)shim_call_long("model_num_layers",
                             Py_BuildValue("(O)", (PyObject *)model), -1);
}

flexflow_op_t flexflow_model_get_layer_by_id(flexflow_model_t model,
                                             int layer_id) {
  return shim_call("model_layer_by_id",
                   Py_BuildValue("(Oi)", (PyObject *)model, layer_id));
}

flexflow_op_t flexflow_model_get_last_layer(flexflow_model_t model) {
  return shim_call("model_last_layer",
                   Py_BuildValue("(O)", (PyObject *)model));
}

int flexflow_op_get_num_inputs(flexflow_op_t op) {
  return (int)shim_call_long("op_num_inputs",
                             Py_BuildValue("(O)", (PyObject *)op), -1);
}
int flexflow_op_get_num_outputs(flexflow_op_t op) {
  return (int)shim_call_long("op_num_outputs",
                             Py_BuildValue("(O)", (PyObject *)op), -1);
}
int flexflow_op_get_num_parameters(flexflow_op_t op) {
  return (int)shim_call_long("op_num_parameters",
                             Py_BuildValue("(O)", (PyObject *)op), -1);
}
flexflow_tensor_t flexflow_op_get_input_by_id(flexflow_op_t op, int idx) {
  return shim_call("op_input_by_id",
                   Py_BuildValue("(Oi)", (PyObject *)op, idx));
}
flexflow_tensor_t flexflow_op_get_output_by_id(flexflow_op_t op, int idx) {
  return shim_call("op_output_by_id",
                   Py_BuildValue("(Oi)", (PyObject *)op, idx));
}
flexflow_parameter_t flexflow_op_get_parameter_by_id(flexflow_op_t op,
                                                     int idx) {
  return shim_call("op_parameter_by_id",
                   Py_BuildValue("(Oi)", (PyObject *)op, idx));
}

int64_t flexflow_parameter_get_num_elements(flexflow_parameter_t handle) {
  return (int64_t)shim_call_long(
      "parameter_num_elements", Py_BuildValue("(O)", (PyObject *)handle), -1);
}

int flexflow_parameter_get_weights_float(flexflow_parameter_t handle,
                                         float *buf, int64_t count) {
  return shim_call_status(
      "parameter_get_weights",
      Py_BuildValue("(OKL)", (PyObject *)handle,
                    (unsigned long long)(uintptr_t)buf, (long long)count));
}

int flexflow_parameter_set_weights_float(flexflow_parameter_t handle,
                                         const float *buf, int64_t count) {
  return shim_call_status(
      "parameter_set_weights",
      Py_BuildValue("(OKL)", (PyObject *)handle,
                    (unsigned long long)(uintptr_t)buf, (long long)count));
}

/* dataloader -------------------------------------------------------------- */

flexflow_single_dataloader_t flexflow_single_dataloader_create(
    flexflow_model_t model, flexflow_tensor_t tensor, const void *full_data,
    const int64_t *shape, int ndims, int is_int) {
  return shim_call(
      "dataloader_create",
      Py_BuildValue("(OOKNi)", (PyObject *)model, (PyObject *)tensor,
                    (unsigned long long)(uintptr_t)full_data,
                    int64_list(shape, ndims), is_int));
}

flexflow_single_dataloader_t flexflow_single_dataloader_create_label(
    flexflow_model_t model, const void *full_data, const int64_t *shape,
    int ndims, int is_int) {
  return shim_call(
      "dataloader_create_label",
      Py_BuildValue("(OKNi)", (PyObject *)model,
                    (unsigned long long)(uintptr_t)full_data,
                    int64_list(shape, ndims), is_int));
}

int flexflow_single_dataloader_get_num_samples(
    flexflow_single_dataloader_t loader) {
  return (int)shim_call_long("dataloader_num_samples",
                             Py_BuildValue("(O)", (PyObject *)loader), -1);
}

int flexflow_single_dataloader_set_num_samples(
    flexflow_single_dataloader_t loader, int num) {
  return shim_call_status(
      "dataloader_set_num_samples",
      Py_BuildValue("(Oi)", (PyObject *)loader, num));
}

int flexflow_single_dataloader_reset(flexflow_single_dataloader_t loader) {
  return shim_call_status("dataloader_reset",
                          Py_BuildValue("(O)", (PyObject *)loader));
}

int flexflow_single_dataloader_next_batch(
    flexflow_single_dataloader_t loader) {
  return shim_call_status("dataloader_next_batch",
                          Py_BuildValue("(O)", (PyObject *)loader));
}

void flexflow_single_dataloader_destroy(flexflow_single_dataloader_t loader) {
  Py_XDECREF((PyObject *)loader);
}

/* C API tail (reference parity, flexflow_c.h:59-669) ---------------------- */

void flexflow_config_parse_args(flexflow_config_t config, char **argv,
                                int argc) {
  PyObject *l = PyList_New(argc);
  for (int i = 0; i < argc; ++i)
    PyList_SET_ITEM(l, i, PyUnicode_FromString(argv[i]));
  PyObject *out = shim_call(
      "config_parse_args", Py_BuildValue("(ON)", (PyObject *)config, l));
  Py_XDECREF(out);
}

void flexflow_config_parse_args_default(flexflow_config_t config) {
  // reference: parse_args(default): re-reads the Legion command line;
  // here the process argv was already consumed by flexflow_config_create,
  // so the default parse is a no-op by design.
  (void)config;
}

flexflow_tensor_t flexflow_model_get_label_tensor(flexflow_model_t model) {
  return shim_call("model_get_label_tensor",
                   Py_BuildValue("(O)", (PyObject *)model));
}

flexflow_tensor_t flexflow_model_get_parameter_by_id(flexflow_model_t model,
                                                     int layer_id) {
  return shim_call("model_get_parameter_by_id",
                   Py_BuildValue("(Oi)", (PyObject *)model, layer_id));
}

flexflow_tensor_t flexflow_constant_create(flexflow_model_t model,
                                           int num_dims, const int *dims,
                                           float value, int data_type) {
  return shim_call(
      "constant_create",
      Py_BuildValue("(ONdi)", (PyObject *)model, int_list(dims, num_dims),
                    (double)value, data_type));
}

int flexflow_tensor_get_dim(flexflow_tensor_t tensor, int legion_axis) {
  /* reference: Legion dim order is innermost-first; ours is row-major */
  return (int)shim_call_long(
      "tensor_get_dim_legion",
      Py_BuildValue("(Oi)", (PyObject *)tensor, legion_axis), -1);
}

#define TENSOR_IO(suffix, ctype, np_tag)                                      \
  int flexflow_tensor_set_tensor_##suffix(                                    \
      flexflow_tensor_t tensor, flexflow_model_t model, int num_dim,          \
      const int *dims, const ctype *data) {                                   \
    return shim_call_status(                                                  \
        "tensor_set_tensor",                                                  \
        Py_BuildValue("(OONKs)", (PyObject *)model, (PyObject *)tensor,       \
                      int_list(dims, num_dim),                                \
                      (unsigned long long)(uintptr_t)data, np_tag));          \
  }                                                                           \
  int flexflow_tensor_get_tensor_##suffix(                                    \
      flexflow_tensor_t tensor, flexflow_model_t model, ctype *data,          \
      int get_gradients) {                                                    \
    return shim_call_status(                                                  \
        "tensor_get_tensor",                                                  \
        Py_BuildValue("(OOKsi)", (PyObject *)model, (PyObject *)tensor,       \
                      (unsigned long long)(uintptr_t)data, np_tag,            \
                      get_gradients));                                        \
  }
TENSOR_IO(float, float, "f4")
TENSOR_IO(int, int, "i4")
TENSOR_IO(int64, int64_t, "i8")
#undef TENSOR_IO

flexflow_initializer_t flexflow_initializer_create_null(void) {
  /* reference: a null initializer means "use the op's default" */
  Py_RETURN_NONE;
}

/* the reference exposes per-type destroys; every handle here is a Python
   object, so they all alias the generic decref */
void flexflow_glorot_uniform_initializer_destroy(
    flexflow_initializer_t handle) {
  Py_XDECREF((PyObject *)handle);
}
void flexflow_zero_initializer_destroy(flexflow_initializer_t handle) {
  Py_XDECREF((PyObject *)handle);
}
void flexflow_uniform_initializer_destroy(flexflow_initializer_t handle) {
  Py_XDECREF((PyObject *)handle);
}
void flexflow_norm_initializer_destroy(flexflow_initializer_t handle) {
  Py_XDECREF((PyObject *)handle);
}
void flexflow_constant_initializer_destroy(flexflow_initializer_t handle) {
  Py_XDECREF((PyObject *)handle);
}

void flexflow_op_init(flexflow_op_t op, flexflow_model_t model) {
  PyObject *out = shim_call(
      "op_init", Py_BuildValue("(OO)", (PyObject *)op, (PyObject *)model));
  Py_XDECREF(out);
}

void flexflow_op_forward(flexflow_op_t op, flexflow_model_t model) {
  PyObject *out = shim_call(
      "op_forward", Py_BuildValue("(OO)", (PyObject *)op, (PyObject *)model));
  Py_XDECREF(out);
}

flexflow_single_dataloader_t flexflow_single_dataloader_create2(
    flexflow_model_t model, flexflow_tensor_t tensor,
    const void *full_data_ptr, int num_samples, int is_int) {
  /* reference create2: raw pointer + sample count; the per-sample shape
     comes from the attached tensor */
  return shim_call(
      "dataloader_create2",
      Py_BuildValue("(OOKii)", (PyObject *)model, (PyObject *)tensor,
                    (unsigned long long)(uintptr_t)full_data_ptr,
                    num_samples, is_int));
}

/* handles ----------------------------------------------------------------- */

void flexflow_handle_destroy(void *handle) {
  Py_XDECREF((PyObject *)handle);
}

}  // extern "C"
