// Flat C API implementation: embeds CPython and drives the Python core
// through flexflow_tpu.capi_shim (see native/include/flexflow_c.h for the
// design note; reference: python/flexflow_c.cc — the same surface in the
// opposite direction).

#include "../include/flexflow_c.h"

#include <Python.h>

#include <cmath>
#include <cstdio>
#include <vector>

namespace {

PyObject *g_shim = nullptr;  // flexflow_tpu.capi_shim module

void print_error() {
  if (PyErr_Occurred()) PyErr_Print();
}

// Call shim.<fn>(*args); returns a NEW reference or nullptr.
PyObject *shim_call(const char *fn, PyObject *args) {
  if (g_shim == nullptr) {
    std::fprintf(stderr, "flexflow_c: flexflow_init() not called\n");
    Py_XDECREF(args);
    return nullptr;
  }
  if (args == nullptr) {
    // a failed Py_BuildValue (e.g. a NULL handle from an earlier failed
    // call formatted with "O") — surface that instead of calling the shim
    // with no arguments
    std::fprintf(stderr, "flexflow_c: %s called with invalid handle\n", fn);
    print_error();
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(g_shim, fn);
  if (f == nullptr) {
    print_error();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (out == nullptr) print_error();
  return out;
}

PyObject *int_list(const int *v, int n) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i) PyList_SET_ITEM(l, i, PyLong_FromLong(v[i]));
  return l;
}

PyObject *int64_list(const int64_t *v, int n) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(l, i, PyLong_FromLongLong(v[i]));
  return l;
}

}  // namespace

extern "C" {

int flexflow_init(int argc, char **argv) {
  if (g_shim != nullptr) return 0;
  if (!Py_IsInitialized()) Py_InitializeEx(0);
  // make the working directory importable (the embedded interpreter has no
  // script directory on sys.path)
  PyRun_SimpleString("import sys, os; sys.path.insert(0, os.getcwd())");
  g_shim = PyImport_ImportModule("flexflow_tpu.capi_shim");
  if (g_shim == nullptr) {
    print_error();
    return 1;
  }
  (void)argc;
  (void)argv;
  return 0;
}

void flexflow_finalize(void) {
  Py_XDECREF(g_shim);
  g_shim = nullptr;
  if (Py_IsInitialized()) Py_FinalizeEx();
}

flexflow_config_t flexflow_config_create(int argc, char **argv) {
  PyObject *l = PyList_New(argc);
  for (int i = 0; i < argc; ++i)
    PyList_SET_ITEM(l, i, PyUnicode_FromString(argv[i]));
  return shim_call("config_create", Py_BuildValue("(N)", l));
}

flexflow_model_t flexflow_model_create(flexflow_config_t config) {
  return shim_call("model_create",
                   Py_BuildValue("(O)", (PyObject *)config));
}

flexflow_tensor_t flexflow_tensor_create(flexflow_model_t model, int ndims,
                                         const int *dims, const char *name) {
  return shim_call(
      "tensor_create",
      Py_BuildValue("(ONs)", (PyObject *)model, int_list(dims, ndims),
                    name ? name : ""));
}

flexflow_tensor_t flexflow_model_add_dense(flexflow_model_t model,
                                           flexflow_tensor_t input,
                                           int out_features, int activation,
                                           int use_bias) {
  return shim_call("add_dense",
                   Py_BuildValue("(OOiii)", (PyObject *)model,
                                 (PyObject *)input, out_features, activation,
                                 use_bias));
}

flexflow_tensor_t flexflow_model_add_conv2d(flexflow_model_t model,
                                            flexflow_tensor_t input,
                                            int out_channels, int kernel_h,
                                            int kernel_w, int stride_h,
                                            int stride_w, int padding_h,
                                            int padding_w, int activation) {
  return shim_call(
      "add_conv2d",
      Py_BuildValue("(OOiiiiiiii)", (PyObject *)model, (PyObject *)input,
                    out_channels, kernel_h, kernel_w, stride_h, stride_w,
                    padding_h, padding_w, activation));
}

flexflow_tensor_t flexflow_model_add_pool2d(flexflow_model_t model,
                                            flexflow_tensor_t input,
                                            int kernel_h, int kernel_w,
                                            int stride_h, int stride_w,
                                            int padding_h, int padding_w,
                                            int pool_type) {
  return shim_call(
      "add_pool2d",
      Py_BuildValue("(OOiiiiiii)", (PyObject *)model, (PyObject *)input,
                    kernel_h, kernel_w, stride_h, stride_w, padding_h,
                    padding_w, pool_type));
}

flexflow_tensor_t flexflow_model_add_flat(flexflow_model_t model,
                                          flexflow_tensor_t input) {
  return shim_call("add_flat", Py_BuildValue("(OO)", (PyObject *)model,
                                             (PyObject *)input));
}

flexflow_tensor_t flexflow_model_add_embedding(flexflow_model_t model,
                                               flexflow_tensor_t input,
                                               int num_entries, int out_dim) {
  return shim_call("add_embedding",
                   Py_BuildValue("(OOii)", (PyObject *)model,
                                 (PyObject *)input, num_entries, out_dim));
}

flexflow_tensor_t flexflow_model_add_multihead_attention(
    flexflow_model_t model, flexflow_tensor_t query, flexflow_tensor_t key,
    flexflow_tensor_t value, int embed_dim, int num_heads) {
  return shim_call(
      "add_multihead_attention",
      Py_BuildValue("(OOOOii)", (PyObject *)model, (PyObject *)query,
                    (PyObject *)key, (PyObject *)value, embed_dim,
                    num_heads));
}

flexflow_tensor_t flexflow_model_add_unary(flexflow_model_t model,
                                           const char *op,
                                           flexflow_tensor_t input) {
  return shim_call("add_unary", Py_BuildValue("(OsO)", (PyObject *)model, op,
                                              (PyObject *)input));
}

flexflow_tensor_t flexflow_model_add_binary(flexflow_model_t model,
                                            const char *op,
                                            flexflow_tensor_t a,
                                            flexflow_tensor_t b) {
  return shim_call("add_binary",
                   Py_BuildValue("(OsOO)", (PyObject *)model, op,
                                 (PyObject *)a, (PyObject *)b));
}

flexflow_tensor_t flexflow_model_add_softmax(flexflow_model_t model,
                                             flexflow_tensor_t input) {
  return shim_call("add_softmax", Py_BuildValue("(OO)", (PyObject *)model,
                                                (PyObject *)input));
}

flexflow_tensor_t flexflow_model_add_dropout(flexflow_model_t model,
                                             flexflow_tensor_t input,
                                             float rate) {
  return shim_call("add_dropout",
                   Py_BuildValue("(OOf)", (PyObject *)model,
                                 (PyObject *)input, rate));
}

int flexflow_model_compile(flexflow_model_t model, const char *loss,
                           const char *metrics, double learning_rate) {
  PyObject *out = shim_call(
      "compile_model",
      Py_BuildValue("(Ossd)", (PyObject *)model, loss ? loss : "",
                    metrics ? metrics : "", learning_rate));
  if (out == nullptr) return 1;
  Py_DECREF(out);
  return 0;
}

double flexflow_model_fit(flexflow_model_t model, const float *x,
                          const int64_t *x_shape, int x_ndims, const void *y,
                          const int64_t *y_shape, int y_ndims, int y_is_int,
                          int epochs) {
  PyObject *out = shim_call(
      "fit_ptr",
      Py_BuildValue("(OKNKNii)", (PyObject *)model,
                    (unsigned long long)(uintptr_t)x,
                    int64_list(x_shape, x_ndims),
                    (unsigned long long)(uintptr_t)y,
                    int64_list(y_shape, y_ndims), y_is_int, epochs));
  if (out == nullptr) return NAN;
  double v = PyFloat_AsDouble(out);
  Py_DECREF(out);
  if (PyErr_Occurred()) {
    print_error();
    return NAN;
  }
  return v;
}

void flexflow_handle_destroy(void *handle) {
  Py_XDECREF((PyObject *)handle);
}

}  // extern "C"
