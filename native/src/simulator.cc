// Event-driven task-graph simulator.
//
// Native rebuild of the reference's Simulator::simulate_runtime
// (reference: src/runtime/simulator.cc:810-1240): build a DAG of SimTasks
// (forward/backward/update work pinned to devices, communication on links),
// replay it with per-resource FIFO queues and a global event heap, and
// return the makespan. The Python side (flexflow_tpu/search/simulator.py)
// lowers an annotated PCG + strategy into the flat task arrays; this file
// only knows about tasks, devices, and links.
//
// Differences from the reference, by design for TPU:
//  * compute resources are chips (one stream each — XLA serializes a step's
//    ops per chip), not CUDA streams per GPU;
//  * communication occupies LINK resources assigned by the Python lowering
//    — one per mesh axis, since collectives over different mesh axes ride
//    disjoint ICI torus dimensions and can overlap, while collectives on
//    the same axis serialize. This replaces the reference's MachineModel
//    comm-path devices (reference: simulator.h:133-157, get_comm_path);
//  * no per-task launch overhead parameter (Legion's is gone under XLA),
//    but a fixed per-collective latency can be folded into task durations.

#include <cstdint>
#include <queue>
#include <vector>

namespace {

struct Event {
  double time;
  int32_t task;
  bool operator>(const Event& o) const {
    if (time != o.time) return time > o.time;
    return task > o.task;  // deterministic tie-break
  }
};

}  // namespace

extern "C" {

// Simulate a task DAG.
//   n               number of tasks
//   resource_of[i]  resource (chip or link) executing task i, in [0, R)
//   duration[i]     execution time of task i (seconds)
//   m, esrc, edst   dependency edges: edst ready only after esrc completes
//   R               total number of resources (chips + links)
//   out_busy[R]     (optional, may be null) per-resource busy time
//   out_finish[n]   (optional, may be null) per-task completion time
// Returns makespan in seconds, or -1.0 on error (cycle / bad input).
//
// Scheduling: a task becomes READY when all predecessors finished; each
// resource runs one task at a time, picking the ready task that became
// ready earliest (FIFO by ready time, task id tie-break) — the reference's
// ready-queue replay (simulator.cc:810+).
double ffn_simulate(int32_t n, const int32_t* resource_of,
                    const double* duration, int32_t m, const int32_t* esrc,
                    const int32_t* edst, int32_t R, double* out_busy,
                    double* out_finish) {
  if (n < 0 || m < 0 || R <= 0) return -1.0;
  std::vector<std::vector<int32_t>> out_edges(n);
  std::vector<int32_t> unmet(n, 0);
  for (int32_t e = 0; e < m; ++e) {
    if (esrc[e] < 0 || esrc[e] >= n || edst[e] < 0 || edst[e] >= n)
      return -1.0;
    out_edges[esrc[e]].push_back(edst[e]);
    unmet[edst[e]]++;
  }
  for (int32_t i = 0; i < n; ++i)
    if (resource_of[i] < 0 || resource_of[i] >= R) return -1.0;

  // Per-resource queue of ready tasks ordered by (ready_time, id).
  using RQ = std::priority_queue<Event, std::vector<Event>, std::greater<Event>>;
  std::vector<RQ> ready(R);
  std::vector<double> free_at(R, 0.0);
  std::vector<char> running(R, 0);
  std::vector<double> busy(R, 0.0);
  std::vector<double> finish(n, 0.0);

  std::priority_queue<Event, std::vector<Event>, std::greater<Event>> done;
  int32_t completed = 0;
  double makespan = 0.0;

  auto try_start = [&](int32_t r, double now) {
    if (running[r] || ready[r].empty()) return;
    Event ev = ready[r].top();
    ready[r].pop();
    double start = std::max(now, free_at[r]);
    double end = start + duration[ev.task];
    running[r] = 1;
    free_at[r] = end;
    busy[r] += duration[ev.task];
    finish[ev.task] = end;
    done.push({end, ev.task});
  };

  for (int32_t i = 0; i < n; ++i)
    if (unmet[i] == 0) ready[resource_of[i]].push({0.0, i});
  for (int32_t r = 0; r < R; ++r) try_start(r, 0.0);

  while (!done.empty()) {
    Event ev = done.top();
    done.pop();
    double now = ev.time;
    makespan = std::max(makespan, now);
    completed++;
    int32_t r = resource_of[ev.task];
    running[r] = 0;
    for (int32_t succ : out_edges[ev.task]) {
      if (--unmet[succ] == 0) ready[resource_of[succ]].push({now, succ});
    }
    // The finishing resource can start its next task; successors may also
    // unblock idle resources.
    try_start(r, now);
    for (int32_t succ : out_edges[ev.task]) {
      int32_t rs = resource_of[succ];
      if (!running[rs]) try_start(rs, now);
    }
  }

  if (completed != n) return -1.0;  // cycle: some tasks never became ready
  if (out_busy)
    for (int32_t r = 0; r < R; ++r) out_busy[r] = busy[r];
  if (out_finish)
    for (int32_t i = 0; i < n; ++i) out_finish[i] = finish[i];
  return makespan;
}

}  // extern "C"
