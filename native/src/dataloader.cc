// Threaded host-side data loader.
//
// Native rebuild of the reference's C++ SingleDataLoader
// (reference: python/flexflow_dataloader.{h,cc} — full dataset resident in
// host memory, next_batch copies per-shard slices toward the device). On
// TPU the device transfer is JAX's job; the native layer owns what the
// reference's CPU tasks owned: epoch shuffling, row gather into contiguous
// batch buffers, and background prefetch so the accelerator never waits on
// Python-side batch assembly.
//
// Ownership: the caller keeps the source arrays alive for the loader's
// lifetime. Batch buffers are owned by the loader and reused; a slot
// returned by ffn_loader_next stays valid until the next
// ffn_loader_next/reset call. The Python wrapper copies the slot into a
// caller-owned array (its public API makes no lifetime promise); the
// prefetch win is that the row gather ran on this thread while the
// accelerator executed the previous step.

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <numeric>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<std::vector<uint8_t>> buffers;  // one per array
  int64_t index = -1;   // batch index within the epoch
  bool ready = false;
};

struct Loader {
  std::vector<const uint8_t*> arrays;
  std::vector<int64_t> row_bytes;  // bytes per sample, per array
  int64_t num_samples = 0;
  int64_t batch_size = 0;
  bool drop_last = true;

  // Sample order for the epoch. Always supplied by the caller (the Python
  // wrapper shuffles with numpy's seeded RNG) so that the batch stream is
  // bit-identical with and without the native library.
  std::vector<int64_t> perm;
  int64_t num_batches = 0;

  std::vector<Batch> slots;
  int64_t produced = 0;  // next batch index the worker will fill
  int64_t consumed = 0;  // next batch index the caller will take
  bool handed_out = false;  // caller still owns the last returned slot
  bool filling = false;     // worker is copying outside the lock
  bool stop = false;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;

  void set_perm(const int64_t* p) {
    perm.resize(num_samples);
    if (p)
      std::memcpy(perm.data(), p, sizeof(int64_t) * num_samples);
    else
      std::iota(perm.begin(), perm.end(), 0);
  }

  void fill(Batch* b, int64_t batch_idx) {
    int64_t begin = batch_idx * batch_size;
    int64_t rows = std::min(batch_size, num_samples - begin);
    for (size_t a = 0; a < arrays.size(); ++a) {
      int64_t rb = row_bytes[a];
      b->buffers[a].resize((size_t)(batch_size * rb));
      uint8_t* dst = b->buffers[a].data();
      for (int64_t r = 0; r < rows; ++r)
        std::memcpy(dst + r * rb, arrays[a] + perm[begin + r] * rb,
                    (size_t)rb);
      // pad a short final batch by repeating row 0 (static shapes for XLA)
      for (int64_t r = rows; r < batch_size; ++r)
        std::memcpy(dst + r * rb, arrays[a] + perm[begin] * rb, (size_t)rb);
    }
    b->index = batch_idx;
    b->ready = true;
  }

  void run() {
    for (;;) {
      std::unique_lock<std::mutex> lk(mu);
      cv_produce.wait(lk, [&] {
        return stop || (produced < num_batches &&
                        produced - consumed < (int64_t)slots.size());
      });
      if (stop) return;
      int64_t idx = produced;
      Batch* slot = &slots[idx % slots.size()];
      filling = true;
      lk.unlock();
      fill(slot, idx);
      lk.lock();
      filling = false;
      // A reset may have rewound `produced` while we copied; only publish
      // if this fill still corresponds to the expected next batch.
      if (produced == idx) produced++;
      cv_consume.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// arrays[i] points at num_samples rows of row_bytes[i] bytes each.
// perm (nullable -> identity) gives the epoch's sample order.
void* ffn_loader_create(const void** arrays, const int64_t* row_bytes,
                        int32_t num_arrays, int64_t num_samples,
                        int64_t batch_size, const int64_t* perm,
                        int32_t drop_last, int32_t prefetch_depth) {
  if (num_arrays <= 0 || num_samples <= 0 || batch_size <= 0) return nullptr;
  Loader* L = new Loader();
  for (int32_t i = 0; i < num_arrays; ++i) {
    L->arrays.push_back((const uint8_t*)arrays[i]);
    L->row_bytes.push_back(row_bytes[i]);
  }
  L->num_samples = num_samples;
  L->batch_size = batch_size;
  L->drop_last = drop_last != 0;
  L->num_batches = drop_last ? num_samples / batch_size
                             : (num_samples + batch_size - 1) / batch_size;
  L->set_perm(perm);
  int32_t depth = prefetch_depth < 1 ? 1 : prefetch_depth;
  L->slots.resize((size_t)depth);
  for (auto& s : L->slots) s.buffers.resize((size_t)num_arrays);
  L->worker = std::thread([L] { L->run(); });
  return L;
}

int64_t ffn_loader_num_batches(void* loader) {
  return ((Loader*)loader)->num_batches;
}

// Blocks until the next batch is prefetched; writes per-array buffer
// pointers into out_ptrs. Returns the batch index, or -1 at epoch end.
// The returned buffers stay valid until the NEXT ffn_loader_next/reset
// call — the slot is only recycled once the caller asks for more.
int64_t ffn_loader_next(void* loader, void** out_ptrs) {
  Loader* L = (Loader*)loader;
  std::unique_lock<std::mutex> lk(L->mu);
  if (L->handed_out) {  // release the previously returned slot
    L->handed_out = false;
    L->consumed++;
    L->cv_produce.notify_all();
  }
  if (L->consumed >= L->num_batches) return -1;
  int64_t idx = L->consumed;
  L->cv_consume.wait(lk, [&] { return L->produced > idx; });
  Batch& b = L->slots[idx % L->slots.size()];
  for (size_t a = 0; a < L->arrays.size(); ++a)
    out_ptrs[a] = b.buffers[a].data();
  L->handed_out = true;
  return idx;
}

// New epoch: install the caller's new sample order and restart prefetching.
void ffn_loader_reset(void* loader, const int64_t* perm) {
  Loader* L = (Loader*)loader;
  std::unique_lock<std::mutex> lk(L->mu);
  // Wait until the worker is parked on the condition variable (not copying
  // outside the lock) before touching the permutation or counters.
  L->cv_consume.wait(lk, [&] { return !L->filling; });
  L->set_perm(perm);
  L->produced = 0;
  L->consumed = 0;
  L->handed_out = false;
  for (auto& s : L->slots) s.ready = false;
  L->cv_produce.notify_all();
}

void ffn_loader_destroy(void* loader) {
  Loader* L = (Loader*)loader;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->stop = true;
    L->cv_produce.notify_all();
  }
  L->worker.join();
  delete L;
}

}  // extern "C"
