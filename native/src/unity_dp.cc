// Native Unity DP search core.
//
// The reference's SearchHelper::graph_cost (src/runtime/graph.cc:1346-1431)
// is compute-bound tree search in C++; this is the TPU rebuild's native
// counterpart (SURVEY §7 prescribes exactly this split). Python
// (flexflow_tpu/search/unity.py) precomputes per-node scalars — FLOPs,
// bytes moved, weight bytes, batch/channel divisibility — and this library
// owns the hot part: machine-view enumeration per resource block, roofline
// + ring-collective costing, bottleneck detection via immediate
// post-dominators, the memoized sequence/nonsequence recursion, and choice
// reconstruction. Graphs up to 256 nodes use a bitset subgraph key; larger
// graphs fall back to the Python implementation.
//
// Semantics mirror unity.py exactly (equivalence-tested from Python):
//   op cost   = max(flops/n / peak, bytes/n / hbm) * bwd_mult
//             + ring_all_reduce(wbytes / ch, dp)
//             + ring_all_gather(sbytes / (dp*ch), dp)   (sparse row sync)
//             + ufactor * (ubytes / ch [/ dp if u_dp_scaled]) / hbm  (optim.)
//   xfer cost = 0 if views equal else all_to_all(bytes / ndst, max(ns, nd))
//   views     = 1-D data views (n | block, batch % n == 0, block-tileable)
//             + 2-D (dp, ch) grids for channel ops (chan % ch == 0)

#include <bitset>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

struct Machine {
  int num_nodes;
  int chips_per_node;
  double peak;     // effective FLOP/s
  double hbm;      // effective bytes/s
  double ici;      // effective bytes/s per link
  double lat;      // seconds per hop
  double ufactor;  // optimizer bytes multiplier (2*state_factor - 1,
                   // received from CostModel.update_traffic_factor)
};

struct Block {  // MachineResource
  int nn, cpn, sn, sc;
  int chips() const { return nn * cpn; }
  bool operator==(const Block &o) const {
    return nn == o.nn && cpn == o.cpn && sn == o.sn && sc == o.sc;
  }
};

struct View {
  int dp, ch;
  // placement identity, mirroring the Python ViewOption key (MachineView
  // hash covers start + dims/strides): origin = block's first chip id,
  // grid_rows = 0 for 1-D in-node views (geometry independent of the
  // block), else n/cpn for node-major grids. Cross-block views with equal
  // (dp, ch) are NOT interchangeable — transfers between them cost.
  int origin, grid_rows;
  int ndev() const { return dp * ch; }
  bool operator==(const View &o) const {
    return dp == o.dp && ch == o.ch && origin == o.origin &&
           grid_rows == o.grid_rows;
  }
};

struct NodeInfo {
  int64_t batch;    // partitionable sample-dim size (<=0: only 1-chip view)
  int64_t chan;     // channel/head size (<=0: no 2-D views)
  double flops, bytes, wbytes;
  double bwd_mult;  // 3 for MXU ops, 2 elementwise, 0 input/parallel
  double ubytes;    // optimizer-update bytes basis (== wbytes normally;
                    // touched-rows bytes for sparse-eligible embeddings,
                    // whose wbytes is then 0 — no grad all-reduce)
  int u_dp_scaled;  // 1: update traffic divides by dp too (sparse rows
                    // follow the batch sharding, not the weight layout)
  double sbytes;    // sparse touched-row bytes basis: the dp replicas
                    // all-gather rows x dim before the scatter-update
                    // (unity.py CostModel.sparse_sync_cost)
};

struct MeasuredView {
  int dp, ch;
  double cost;  // measured fwd(+bwd) seconds of the shard's real kernel
};

struct Problem {
  int n;
  std::vector<NodeInfo> nodes;
  std::vector<std::vector<int>> preds;   // producers per node
  std::vector<std::vector<int>> succs;   // consumers per node
  std::vector<std::vector<std::pair<int, double>>> in_edges;  // (src, bytes)
  Machine m;
  int allow_subblock = 0;  // cost concurrent branches on resource
                           // sub-blocks (unity.py allow_subblock_views)
  // measured-mode leaf costs, pre-resolved by unity.py (calibrated
  // kernels, reference: simulator.cc:532): per-node (dp, ch) -> seconds
  // replacing the analytic roofline term; nodes/views without an entry
  // fall back to the roofline.
  std::vector<std::vector<MeasuredView>> measured;
};

double ring_all_reduce(const Machine &m, double bytes_per_chip, int g) {
  if (g <= 1 || bytes_per_chip <= 0) return 0.0;
  double wire = 2.0 * (g - 1) / g * bytes_per_chip;
  return wire / m.ici + 2.0 * (g - 1) * m.lat;
}

double all_to_all(const Machine &m, double bytes_per_chip, int g) {
  if (g <= 1 || bytes_per_chip <= 0) return 0.0;
  double wire = (double)(g - 1) / g * bytes_per_chip;
  return wire / m.ici + (g - 1) * m.lat;
}

double ring_all_gather(const Machine &m, double bytes_per_chip, int g) {
  if (g <= 1 || bytes_per_chip <= 0) return 0.0;
  double wire = (double)(g - 1) * bytes_per_chip;
  return wire / m.ici + (g - 1) * m.lat;
}

double op_cost(const Problem &p, int node, View v) {
  const NodeInfo &ni = p.nodes[node];
  if (ni.bwd_mult <= 0.0) return 0.0;
  int n = v.ndev();
  double t = -1.0;
  if (!p.measured.empty())
    for (const MeasuredView &mv : p.measured[node])
      if (mv.dp == v.dp && mv.ch == v.ch) {
        t = mv.cost;
        break;
      }
  if (t < 0.0) {
    double t_f = (ni.flops / n) / p.m.peak;
    double t_m = (ni.bytes / n) / p.m.hbm;
    t = (t_f > t_m ? t_f : t_m) * ni.bwd_mult;
  }
  if (ni.wbytes > 0) t += ring_all_reduce(p.m, ni.wbytes / v.ch, v.dp);
  // sparse tables: touched-row all-gather over the dp replicas
  if (ni.sbytes > 0)
    t += ring_all_gather(p.m, ni.sbytes / (v.dp * v.ch), v.dp);
  if (ni.ubytes > 0) {
    // optimizer update HBM traffic (CostModel.update_traffic_factor)
    double per_chip = ni.ubytes / v.ch;
    if (ni.u_dp_scaled) per_chip /= v.dp;
    t += p.m.ufactor * per_chip / p.m.hbm;
  }
  return t;
}

double xfer_cost(const Problem &p, double bytes, View a, View b) {
  if (a == b) return 0.0;
  int n = a.ndev() > b.ndev() ? a.ndev() : b.ndev();
  return all_to_all(p.m, bytes / b.ndev(), n);
}

// block-tileable device counts (unity.py _block_view)
bool block_tileable(const Block &b, int n) {
  if (n <= b.cpn) return true;
  return (n % b.cpn == 0) && (n / b.cpn <= b.nn);
}

void valid_views(const Problem &p, int node, const Block &b,
                 std::vector<View> &out) {
  out.clear();
  const NodeInfo &ni = p.nodes[node];
  int total = b.chips();
  int origin = b.sn * p.m.chips_per_node + b.sc;
  auto rows = [&b](int n) { return n <= b.cpn ? 0 : n / b.cpn; };
  for (int n = 1; n <= total; ++n) {
    if (total % n != 0 || !block_tileable(b, n)) continue;
    if (ni.batch > 0 && ni.batch % n == 0)
      out.push_back({n, 1, origin, rows(n)});
    if (ni.chan > 0) {
      for (int dp = 1; dp <= n; ++dp) {
        if (n % dp != 0) continue;
        int ch = n / dp;
        if (ch > 1 && (ni.batch > 0 && ni.batch % dp == 0) &&
            ni.chan % ch == 0)
          out.push_back({dp, ch, origin, rows(n)});
      }
    }
  }
  if (out.empty()) out.push_back({1, 1, origin, 0});
}

constexpr int kMaxNodes = 256;
using Bits = std::bitset<kMaxNodes>;

inline Bits one_bit(int i) {
  Bits b;
  b.set(i);
  return b;
}

struct Key {
  Bits sub;
  int src_node;
  View src_view;
  int sink;
  View sink_view;
  Block block;
  bool operator==(const Key &o) const {
    return sub == o.sub && src_node == o.src_node &&
           src_view == o.src_view && sink == o.sink &&
           sink_view == o.sink_view && block == o.block;
  }
};

struct KeyHash {
  size_t operator()(const Key &k) const {
    uint64_t h = (uint64_t)std::hash<Bits>{}(k.sub);
    auto mix = [&h](uint64_t v) {
      h ^= v + 0x9e3779b97f4a7c15ULL + (h << 6) + (h >> 2);
    };
    mix((uint64_t)(k.src_node + 1));
    mix(((uint64_t)k.src_view.dp << 32) | (uint64_t)k.src_view.ch);
    mix(((uint64_t)(k.src_view.origin + 1) << 32) |
        (uint64_t)(k.src_view.grid_rows + 1));
    mix((uint64_t)k.sink);
    mix(((uint64_t)k.sink_view.dp << 32) | (uint64_t)k.sink_view.ch);
    mix(((uint64_t)(k.sink_view.origin + 1) << 32) |
        (uint64_t)(k.sink_view.grid_rows + 1));
    mix(((uint64_t)k.block.nn << 48) | ((uint64_t)k.block.cpn << 32) |
        ((uint64_t)k.block.sn << 16) | (uint64_t)k.block.sc);
    return (size_t)h;
  }
};

struct Entry {
  double cost;
  std::vector<std::pair<int, View>> views;  // choices for sub \ {sink}
};

struct Solver {
  const Problem &p;
  std::unordered_map<Key, Entry, KeyHash> memo;
  explicit Solver(const Problem &prob) : p(prob) {}

  Bits ancestors_within(int node, const Bits &sub) const {
    Bits seen = one_bit(node);
    std::vector<int> stack{node};
    while (!stack.empty()) {
      int v = stack.back();
      stack.pop_back();
      for (int u : p.preds[v]) {
        if (sub.test(u) && !seen.test(u)) {
          seen.set(u);
          stack.push_back(u);
        }
      }
    }
    return seen;
  }

  // interior node on every source->sink path of `sub` (unity.py
  // _find_bottleneck: first interior node post-dominating the virtual
  // source), or -1.
  int find_bottleneck(const Bits &sub, int sink) const {
    std::vector<int> nodes;
    for (int i = 0; i < p.n; ++i)
      if (sub.test(i)) nodes.push_back(i);
    int n = (int)nodes.size();
    std::vector<int> index(p.n, -1);
    for (int i = 0; i < n; ++i) index[nodes[i]] = i;
    // local succs within sub, plus virtual source n feeding sub-sources
    std::vector<std::vector<int>> succ(n + 1);
    std::vector<int> indeg(n, 0);
    for (int i = 0; i < n; ++i)
      for (int u : p.preds[nodes[i]])
        if (index[u] >= 0) {
          succ[index[u]].push_back(i);
          indeg[i]++;
        }
    for (int i = 0; i < n; ++i)
      if (indeg[i] == 0) succ[n].push_back(i);
    // topo order (local) — Kahn over the n+1 nodes incl. the virtual source
    std::vector<int> order;
    order.reserve(n + 1);
    std::vector<int> full_deg(n + 1, 0);
    for (int v = 0; v <= n; ++v)
      for (int w : succ[v]) full_deg[w]++;
    std::vector<int> ready;
    for (int v = 0; v <= n; ++v)
      if (full_deg[v] == 0) ready.push_back(v);
    while (!ready.empty()) {
      int v = ready.back();
      ready.pop_back();
      order.push_back(v);
      for (int w : succ[v])
        if (--full_deg[w] == 0) ready.push_back(w);
    }
    if ((int)order.size() != n + 1) return -1;
    // post-dominator sets by reverse-topo bitset dataflow
    Bits full;
    full.set();
    std::vector<Bits> pdom(n + 1, full);
    std::vector<int> pos(n + 1);
    for (int i = 0; i <= n; ++i) pos[order[i]] = i;
    for (int i = n; i >= 0; --i) {
      int v = order[i];
      if (succ[v].empty()) {
        pdom[v] = (v < n) ? one_bit(v) : Bits();
      } else {
        Bits inter = full;
        for (int w : succ[v]) inter &= pdom[w];
        if (v < n) inter.set(v);
        pdom[v] = inter;
      }
    }
    // nearest strict post-dominators of the virtual source, in topo order
    const Bits &cands = pdom[n];
    int best = -1, best_pos = 1 << 30;
    for (int i = 0; i < n; ++i) {
      if (cands.test(i) && nodes[i] != sink && pos[i] < best_pos) {
        best_pos = pos[i];
        best = nodes[i];
      }
    }
    return best;
  }

  Entry graph_cost(const Bits &sub, int src_node, View src_view, int sink,
                   View sink_view, const Block &block) {
    Key key{sub, src_node, src_view, sink, sink_view, block};
    auto it = memo.find(key);
    if (it != memo.end()) return it->second;

    Bits sink_bit = one_bit(sink);
    Bits interior = sub & ~sink_bit;
    Entry out;
    if (interior.none()) {
      double c = op_cost(p, sink, sink_view);
      for (auto &e : p.in_edges[sink])
        if (e.first == src_node)
          c += xfer_cost(p, e.second, src_view, sink_view);
      out.cost = c;
      memo.emplace(key, out);
      return out;
    }

    int b = find_bottleneck(sub, sink);
    if (b >= 0) {
      Bits pre = ancestors_within(b, sub);
      Bits post = (sub & ~pre) | sink_bit;
      std::vector<View> views;
      valid_views(p, b, block, views);
      bool first = true;
      for (View v : views) {
        Entry e1 = graph_cost(pre, src_node, src_view, b, v, block);
        Entry e2 = graph_cost(post, b, v, sink, sink_view, block);
        double c = e1.cost + e2.cost;
        if (first || c < out.cost) {
          first = false;
          out.cost = c;
          out.views = e1.views;
          out.views.insert(out.views.end(), e2.views.begin(), e2.views.end());
          out.views.push_back({b, v});
        }
      }
      memo.emplace(key, out);
      return out;
    }

    out = nonsequence(sub, src_node, src_view, sink, sink_view, block);
    memo.emplace(key, out);
    return out;
  }

  std::vector<Bits> branches(const Bits &sub, int sink) const {
    Bits rest = sub & ~one_bit(sink);
    std::vector<Bits> comps;
    while (rest.any()) {
#ifdef __GLIBCXX__
      int seed = (int)rest._Find_first();  // libstdc++ O(words) extension
#else
      int seed = 0;
      while (!rest.test(seed)) ++seed;
#endif
      Bits comp = one_bit(seed);
      std::vector<int> stack{seed};
      while (!stack.empty()) {
        int v = stack.back();
        stack.pop_back();
        auto visit = [&](int u) {
          if (rest.test(u) && !comp.test(u)) {
            comp.set(u);
            stack.push_back(u);
          }
        };
        for (int u : p.preds[v]) visit(u);
        for (int u : p.succs[v]) visit(u);
      }
      comps.push_back(comp);
      rest &= ~comp;
    }
    return comps;
  }

  // product cap for the exact multi-terminal solve (unity.py _MT_EXACT_CAP)
  static constexpr long kMTExactCap = 4096;

  Entry multi_terminal_cost(const Bits &branch, int src_node, View src_view,
                            int sink, View sink_view, const Block &block) {
    // Joint view assignment over the whole branch, charging intra-branch
    // transfers, the src boundary, and terminal->sink transfers: exact
    // enumeration when the view product fits kMTExactCap, greedy in
    // topological (ascending-index) order otherwise. Mirrors
    // unity.py:_multi_terminal_cost bit-for-bit, including tie-breaking
    // (first candidate wins) and product iteration order (last node's
    // views cycle fastest).
    std::vector<int> nodes;
    for (int i = 0; i < p.n; ++i)
      if (branch.test(i)) nodes.push_back(i);
    size_t k_n = nodes.size();
    // topological order within the branch, smallest index first (Kahn) —
    // index order mirrors guid order, which substitution rewrites can
    // leave non-topological (mirrors unity.py _branch_topo_order)
    {
      std::vector<int> indeg(p.n, 0);
      for (int g : nodes)
        for (auto &e : p.in_edges[g])
          if (branch.test(e.first)) indeg[g]++;
      std::vector<char> done(p.n, 0);
      std::vector<int> order;
      order.reserve(k_n);
      while (order.size() < k_n) {
        int pick = -1;
        for (int g : nodes)  // nodes ascend: first ready == smallest
          if (!done[g] && indeg[g] == 0) { pick = g; break; }
        if (pick < 0) break;  // cycle (impossible in a PCG): keep order
        done[pick] = 1;
        order.push_back(pick);
        for (int c : nodes)
          if (!done[c])
            for (auto &e : p.in_edges[c])
              if (e.first == pick) indeg[c]--;
      }
      if (order.size() == k_n) nodes = order;
    }
    std::vector<int> pos(p.n, -1);
    for (size_t k = 0; k < k_n; ++k) pos[nodes[k]] = (int)k;
    std::vector<std::vector<View>> opts(k_n);
    long combos = 1;
    for (size_t k = 0; k < k_n; ++k) {
      valid_views(p, nodes[k], block, opts[k]);
      if (combos <= kMTExactCap) combos *= (long)opts[k].size();
    }

    // transfers into node g under view v from already-assigned producers
    // (every intra-branch producer of nodes[k] has pos < k: indices are
    // topological) or from the src boundary
    auto edge_in_cost = [&](size_t k, View v, const std::vector<View> &assign,
                            size_t assigned_upto) {
      double c = 0.0;
      for (auto &e : p.in_edges[nodes[k]]) {
        int u = e.first;
        if (pos[u] >= 0 && (size_t)pos[u] < assigned_upto)
          c += xfer_cost(p, e.second, assign[pos[u]], v);
        else if (u == src_node)
          c += xfer_cost(p, e.second, src_view, v);
      }
      return c;
    };
    auto total_cost = [&](const std::vector<View> &assign) {
      // assign is complete here, so every intra-branch producer edge is
      // charged (assigned_upto = k_n), exactly like unity.py's total_cost
      double c = 0.0;
      for (size_t k = 0; k < k_n; ++k)
        c += op_cost(p, nodes[k], assign[k]) +
             edge_in_cost(k, assign[k], assign, k_n);
      for (auto &e : p.in_edges[sink])
        if (pos[e.first] >= 0)
          c += xfer_cost(p, e.second, assign[pos[e.first]], sink_view);
      return c;
    };

    std::vector<View> assign(k_n, View{1, 1, 0, 0});
    Entry out;
    if (combos <= kMTExactCap) {
      std::vector<size_t> idx(k_n, 0);
      bool first = true;
      std::vector<View> best_assign;
      for (;;) {
        for (size_t k = 0; k < k_n; ++k) assign[k] = opts[k][idx[k]];
        double c = total_cost(assign);
        if (first || c < out.cost) {
          first = false;
          out.cost = c;
          best_assign = assign;
        }
        // odometer: last position increments fastest (itertools.product)
        size_t k = k_n;
        while (k > 0) {
          --k;
          if (++idx[k] < opts[k].size()) break;
          idx[k] = 0;
          if (k == 0) { k = k_n + 1; break; }
        }
        if (k == k_n + 1 || k_n == 0) break;
      }
      for (size_t k = 0; k < k_n; ++k)
        out.views.push_back({nodes[k], best_assign[k]});
      return out;
    }

    for (size_t k = 0; k < k_n; ++k) {
      double bestc = -1;
      View bv = opts[k][0];
      for (View v : opts[k]) {
        double c = op_cost(p, nodes[k], v) + edge_in_cost(k, v, assign, k);
        for (auto &e : p.in_edges[sink])
          if (e.first == nodes[k]) c += xfer_cost(p, e.second, v, sink_view);
        if (bestc < 0 || c < bestc) {
          bestc = c;
          bv = v;
        }
      }
      assign[k] = bv;
    }
    out.cost = total_cost(assign);
    for (size_t k = 0; k < k_n; ++k)
      out.views.push_back({nodes[k], assign[k]});
    return out;
  }

  Entry branch_cost(const Bits &branch, int src_node, View src_view, int sink,
                    View sink_view, const Block &block) {
    // terminals: branch nodes with no consumer inside the branch
    std::vector<int> terms;
    for (int i = 0; i < p.n; ++i) {
      if (!branch.test(i)) continue;
      bool internal_consumer = false;
      for (int c : p.succs[i])
        if (branch.test(c)) internal_consumer = true;
      if (!internal_consumer) terms.push_back(i);
    }
    Entry out;
    if (terms.size() != 1)
      return multi_terminal_cost(branch, src_node, src_view, sink, sink_view,
                                 block);
    int term = terms[0];
    std::vector<View> views;
    valid_views(p, term, block, views);
    bool first = true;
    for (View v : views) {
      Entry e = graph_cost(branch, src_node, src_view, term, v, block);
      double c = e.cost;
      for (auto &edge : p.in_edges[sink])
        if (edge.first == term)
          c += xfer_cost(p, edge.second, v, sink_view);
      if (first || c < out.cost) {
        first = false;
        out.cost = c;
        out.views = e.views;
        out.views.push_back({term, v});
      }
    }
    return out;
  }

  Entry nonsequence(const Bits &sub, int src_node, View src_view, int sink,
                    View sink_view, const Block &block) {
    auto comps = branches(sub, sink);
    double sink_cost = op_cost(p, sink, sink_view);
    for (auto &e : p.in_edges[sink])
      if (e.first == src_node)
        sink_cost += xfer_cost(p, e.second, src_view, sink_view);

    // sequential: all branches on the full block
    Entry best;
    best.cost = sink_cost;
    std::vector<Entry> per_branch;
    per_branch.reserve(comps.size());
    for (Bits br : comps) {
      Entry e = branch_cost(br, src_node, src_view, sink, sink_view, block);
      best.cost += e.cost;
      best.views.insert(best.views.end(), e.views.begin(), e.views.end());
      per_branch.push_back(std::move(e));
    }

    // concurrent two-way: {first} vs {rest} on vertical/horizontal splits
    // (gated like unity.py: the one-mesh lowering runs branches
    // sequentially, so sub-block placements cost what cannot execute)
    if (p.allow_subblock && comps.size() >= 2) {
      std::vector<std::pair<Block, Block>> splits;
      for (int i = 1; i < block.nn; ++i)
        splits.push_back({{i, block.cpn, block.sn, block.sc},
                          {block.nn - i, block.cpn, block.sn + i, block.sc}});
      for (int i = 1; i < block.cpn; ++i)
        splits.push_back({{block.nn, i, block.sn, block.sc},
                          {block.nn, block.cpn - i, block.sn, block.sc + i}});
      for (auto &sp : splits) {
        Entry e1 =
            branch_cost(comps[0], src_node, src_view, sink, sink_view, sp.first);
        double c2 = 0.0;
        std::vector<std::pair<int, View>> v2;
        for (size_t bi = 1; bi < comps.size(); ++bi) {
          Entry e = branch_cost(comps[bi], src_node, src_view, sink, sink_view,
                                sp.second);
          c2 += e.cost;
          v2.insert(v2.end(), e.views.begin(), e.views.end());
        }
        double c = (e1.cost > c2 ? e1.cost : c2) + sink_cost;
        if (c < best.cost) {
          best.cost = c;
          best.views = e1.views;
          best.views.insert(best.views.end(), v2.begin(), v2.end());
        }
      }
    }
    return best;
  }
};

}  // namespace

extern "C" {

// Returns 0 on success. out_dp/out_ch get the chosen view per node
// (1/1 when unassigned); out_cost the optimal simulated step seconds.
int ffn_unity_dp(int n_nodes, int n_edges, const int32_t *esrc,
                 const int32_t *edst, const double *edge_bytes,
                 const int64_t *batch, const int64_t *chan,
                 const double *flops, const double *bytes_moved,
                 const double *wbytes, const double *bwd_mult,
                 const double *ubytes, const int32_t *u_dp_scaled,
                 const double *sbytes,
                 double update_factor, int allow_subblock,
                 int n_measured, const int32_t *meas_node,
                 const int32_t *meas_dp, const int32_t *meas_ch,
                 const double *meas_cost,
                 int machine_nodes, int chips_per_node, double peak_eff,
                 double hbm_eff, double ici_eff, double ici_lat, int sink,
                 int32_t *out_dp, int32_t *out_ch, double *out_cost) {
  if (n_nodes <= 0 || n_nodes > kMaxNodes) return 1;
  Problem p;
  p.n = n_nodes;
  p.m = {machine_nodes, chips_per_node, peak_eff, hbm_eff,
         ici_eff, ici_lat, update_factor};
  p.allow_subblock = allow_subblock;
  if (n_measured > 0) {
    p.measured.assign(n_nodes, {});
    for (int i = 0; i < n_measured; ++i) {
      int nd = meas_node[i];
      if (nd < 0 || nd >= n_nodes) return 3;
      p.measured[nd].push_back({meas_dp[i], meas_ch[i], meas_cost[i]});
    }
  }
  p.nodes.resize(n_nodes);
  for (int i = 0; i < n_nodes; ++i)
    p.nodes[i] = {batch[i], chan[i], flops[i], bytes_moved[i], wbytes[i],
                  bwd_mult[i], ubytes[i], u_dp_scaled[i],
                  sbytes ? sbytes[i] : 0.0};
  p.preds.assign(n_nodes, {});
  p.succs.assign(n_nodes, {});
  p.in_edges.assign(n_nodes, {});
  for (int e = 0; e < n_edges; ++e) {
    int s = esrc[e], d = edst[e];
    if (s < 0 || s >= n_nodes || d < 0 || d >= n_nodes) return 2;
    p.preds[d].push_back(s);
    p.succs[s].push_back(d);
    p.in_edges[d].push_back({s, edge_bytes[e]});
  }

  Solver solver(p);
  Block full{machine_nodes, chips_per_node, 0, 0};
  Bits all;
  for (int i = 0; i < n_nodes; ++i) all.set(i);
  Bits sub = solver.ancestors_within(sink, all);
  std::vector<View> sink_views;
  valid_views(p, sink, full, sink_views);
  bool first = true;
  Entry best;
  View best_sink{1, 1, 0, 0};
  for (View v : sink_views) {
    Entry e = solver.graph_cost(sub, -1, {1, 1, 0, 0}, sink, v, full);
    if (first || e.cost < best.cost) {
      first = false;
      best = e;
      best_sink = v;
    }
  }
  for (int i = 0; i < n_nodes; ++i) {
    out_dp[i] = 1;
    out_ch[i] = 1;
  }
  for (auto &cv : best.views) {
    out_dp[cv.first] = cv.second.dp;
    out_ch[cv.first] = cv.second.ch;
  }
  out_dp[sink] = best_sink.dp;
  out_ch[sink] = best_sink.ch;
  *out_cost = best.cost;
  return 0;
}

}  // extern "C"
