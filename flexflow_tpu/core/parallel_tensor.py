"""Parallel tensor shape model.

The central abstraction of the framework, re-designed for TPU/GSPMD from the
reference's `ParallelDim {size, degree, parallel_idx, is_replica_dim}`
(reference: include/flexflow/parallel_tensor.h:36-70).

Key differences from the reference:
  * dims are stored in numpy order (outermost first), not Legion order;
  * `parallel_idx` indexes a *mesh axis* of the global `jax.sharding.Mesh`
    rather than a MachineView dim — the lowering turns a shape directly into
    a `PartitionSpec`;
  * replica dims are represented explicitly like in the reference (a dim with
    `is_replica_dim=True`, size == degree) because the parallel-op rewrite
    rules (Replicate/Reduction) and the search's dim-mapping solver reason
    about them; they vanish at lowering time (GSPMD replicates implicitly).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence, Tuple

from flexflow_tpu.core.types import DataType

# Mesh axes used by the lowering. The search assigns degrees to tensor dims;
# the lowering maps each parallel dim to one of these named axes.
MAX_TENSOR_DIMS = 5  # reference: MAX_TENSOR_DIM in config.h


@dataclasses.dataclass(frozen=True)
class ParallelDim:
    """One tensor dimension with its parallel annotation.

    size: global (unpartitioned) extent of this dim.
    degree: number of shards this dim is split into (1 = not partitioned).
    parallel_idx: index of the mesh axis this dim's shards map onto
        (-1 when degree == 1).
    is_replica_dim: this is a synthetic replication dim (size == degree);
        used on weights under data parallelism and activations under
        tensor parallelism (reference: parallel_tensor.h:36-70).
    """

    size: int
    degree: int = 1
    parallel_idx: int = -1
    is_replica_dim: bool = False

    def __post_init__(self):
        if self.size <= 0:
            raise ValueError(f"dim size must be positive, got {self.size}")
        if self.degree < 1:
            raise ValueError(f"degree must be >= 1, got {self.degree}")
        if self.size % self.degree != 0:
            raise ValueError(
                f"degree {self.degree} does not divide size {self.size}"
            )
        if self.is_replica_dim and self.size != self.degree:
            raise ValueError("replica dim must have size == degree")

    @property
    def piece_size(self) -> int:
        return self.size // self.degree

    def with_degree(self, degree: int, parallel_idx: int = -1) -> "ParallelDim":
        return dataclasses.replace(
            self, degree=degree, parallel_idx=parallel_idx if degree > 1 else -1
        )


@dataclasses.dataclass(frozen=True)
class ParallelTensorShape:
    """Shape + dtype + per-dim parallel annotations.

    reference: ParallelTensorShape in parallel_tensor.h; hashing feeds the
    search memo tables (graph.cc:1531-1543).
    """

    dims: Tuple[ParallelDim, ...]
    dtype: DataType = DataType.FLOAT

    @staticmethod
    def make(
        sizes: Sequence[int],
        dtype: DataType = DataType.FLOAT,
        degrees: Optional[Sequence[int]] = None,
        parallel_idxs: Optional[Sequence[int]] = None,
    ) -> "ParallelTensorShape":
        degrees = list(degrees) if degrees is not None else [1] * len(sizes)
        pidxs = (
            list(parallel_idxs)
            if parallel_idxs is not None
            else [-1] * len(sizes)
        )
        return ParallelTensorShape(
            tuple(
                ParallelDim(s, d, p)
                for s, d, p in zip(sizes, degrees, pidxs)
            ),
            dtype,
        )

    # -- basic views ---------------------------------------------------------

    @property
    def ndim(self) -> int:
        return len(self.dims)

    @property
    def sizes(self) -> Tuple[int, ...]:
        """Global sizes including replica dims."""
        return tuple(d.size for d in self.dims)

    @property
    def logical_sizes(self) -> Tuple[int, ...]:
        """Global sizes with replica dims dropped — the array shape JAX sees."""
        return tuple(d.size for d in self.dims if not d.is_replica_dim)

    @property
    def degrees(self) -> Tuple[int, ...]:
        return tuple(d.degree for d in self.dims)

    @property
    def piece_sizes(self) -> Tuple[int, ...]:
        """Per-shard local sizes (reference: get_input_sub_tensor)."""
        return tuple(d.piece_size for d in self.dims)

    @property
    def total_degree(self) -> int:
        out = 1
        for d in self.dims:
            out *= d.degree
        return out

    @property
    def num_replica_dims(self) -> int:
        return sum(1 for d in self.dims if d.is_replica_dim)

    @property
    def replica_degree(self) -> int:
        out = 1
        for d in self.dims:
            if d.is_replica_dim:
                out *= d.degree
        return out

    def volume(self) -> int:
        """Number of logical elements (replica dims excluded)."""
        out = 1
        for d in self.dims:
            if not d.is_replica_dim:
                out *= d.size
        return out

    def piece_volume(self) -> int:
        """Elements per shard (replica dims contribute 1)."""
        out = 1
        for d in self.dims:
            out *= 1 if d.is_replica_dim else d.piece_size
        return out

    def size_bytes(self) -> int:
        return self.volume() * self.dtype.size_bytes

    def piece_bytes(self) -> int:
        return self.piece_volume() * self.dtype.size_bytes

    # -- transforms ----------------------------------------------------------

    def with_dim(self, idx: int, dim: ParallelDim) -> "ParallelTensorShape":
        dims = list(self.dims)
        dims[idx] = dim
        return dataclasses.replace(self, dims=tuple(dims))

    def with_degree(
        self, idx: int, degree: int, parallel_idx: int = -1
    ) -> "ParallelTensorShape":
        return self.with_dim(idx, self.dims[idx].with_degree(degree, parallel_idx))

    def data_parallel(self, degree: int, axis: int = 0) -> "ParallelTensorShape":
        """Partition the sample dim (reference: get_data_parallel_config)."""
        return self.with_degree(axis, degree, 0)

    def replicated_like(self) -> "ParallelTensorShape":
        """Drop all partitioning (degree 1 everywhere, no replica dims)."""
        return ParallelTensorShape(
            tuple(
                ParallelDim(d.size)
                for d in self.dims
                if not d.is_replica_dim
            ),
            self.dtype,
        )

    def append_replica_dim(self, degree: int, parallel_idx: int = -1):
        """Add a replication dim at position 0 (reference puts replica dims
        at the outermost position of weights)."""
        return ParallelTensorShape(
            (ParallelDim(degree, degree, parallel_idx, True),) + self.dims,
            self.dtype,
        )

    # -- lowering ------------------------------------------------------------

    def partition_spec(
        self,
        mesh_axis_names: Sequence[str],
        mesh_axis_sizes: Optional[Sequence[int]] = None,
    ):
        """Lower to a jax PartitionSpec over the global mesh.

        Replica dims produce no spec entry (GSPMD replicates across unused
        axes implicitly). Each partitioned logical dim maps to the mesh axis
        named by its parallel_idx — or, when `mesh_axis_sizes` is given and
        the degree exceeds that axis, to the run of consecutive axes whose
        sizes multiply to the degree (a tuple entry). Spans are how one op
        runs FULL-width data parallel (batch over data×model) while its
        neighbors shard channels on the model axis — the per-op
        heterogeneous lowering (reference: per-op MachineViews,
        graph.cc:1346-1431).
        """
        from jax.sharding import PartitionSpec

        entries = []
        for d in self.dims:
            if d.is_replica_dim:
                continue
            if d.degree == 1:
                entries.append(None)
            else:
                if d.parallel_idx < 0 or d.parallel_idx >= len(mesh_axis_names):
                    raise ValueError(
                        f"dim {d} has degree {d.degree} but no valid mesh axis"
                    )
                if (
                    mesh_axis_sizes is None
                    or mesh_axis_sizes[d.parallel_idx] == d.degree
                ):
                    entries.append(mesh_axis_names[d.parallel_idx])
                else:
                    run: list = []
                    prod = 1
                    i = d.parallel_idx
                    while i < len(mesh_axis_names) and prod < d.degree:
                        run.append(mesh_axis_names[i])
                        prod *= mesh_axis_sizes[i]
                        i += 1
                    if prod != d.degree:
                        raise ValueError(
                            f"dim {d}: degree {d.degree} is not the product "
                            f"of consecutive mesh axes starting at "
                            f"{d.parallel_idx} (sizes {tuple(mesh_axis_sizes)})"
                        )
                    entries.append(tuple(run))
        # trim trailing Nones for cleanliness
        while entries and entries[-1] is None:
            entries.pop()
        return PartitionSpec(*entries)

    def is_valid_for_mesh(self, mesh_shape: Sequence[int]) -> bool:
        """Check degrees fit the mesh: each partitioned dim's degree must
        equal the size of its assigned mesh axis (or the product of the
        consecutive run starting there — a span), and no axis is used
        twice."""
        used = set()
        for d in self.dims:
            if d.degree == 1:
                continue
            i = d.parallel_idx
            if i < 0 or i >= len(mesh_shape):
                return False
            prod = 1
            while i < len(mesh_shape) and prod < d.degree:
                if i in used:
                    return False
                used.add(i)
                prod *= mesh_shape[i]
                i += 1
            if prod != d.degree:
                return False
        return True

    def __str__(self):
        parts = []
        for d in self.dims:
            tag = "r" if d.is_replica_dim else ""
            if d.degree > 1:
                parts.append(f"{d.size}/{d.degree}@{d.parallel_idx}{tag}")
            else:
                parts.append(f"{d.size}{tag}")
        return f"[{', '.join(parts)}]:{self.dtype.value}"
