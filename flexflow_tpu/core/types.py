"""Core enums and type definitions for flexflow_tpu.

TPU-native re-design of the reference's type system
(reference: include/flexflow/ffconst.h:62-232). We keep the *vocabulary*
(operator types, loss/metrics enums, sync types) because the search engine,
substitution rules, and frontends key off it, but the values and layout are
our own.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp


class DataType(enum.Enum):
    """Tensor element types (reference: ffconst.h DataType)."""

    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    FLOAT = "float32"
    DOUBLE = "float64"

    def to_jnp(self):
        return {
            DataType.BOOL: jnp.bool_,
            DataType.INT32: jnp.int32,
            DataType.INT64: jnp.int64,
            DataType.HALF: jnp.float16,
            DataType.BFLOAT16: jnp.bfloat16,
            DataType.FLOAT: jnp.float32,
            DataType.DOUBLE: jnp.float64,
        }[self]

    @staticmethod
    def from_jnp(dt) -> "DataType":
        return {
            jnp.dtype("bool"): DataType.BOOL,
            jnp.dtype("int32"): DataType.INT32,
            jnp.dtype("int64"): DataType.INT64,
            jnp.dtype("float16"): DataType.HALF,
            jnp.dtype("bfloat16"): DataType.BFLOAT16,
            jnp.dtype("float32"): DataType.FLOAT,
            jnp.dtype("float64"): DataType.DOUBLE,
        }[jnp.dtype(dt)]

    @property
    def size_bytes(self) -> int:
        return {
            DataType.BOOL: 1,
            DataType.INT32: 4,
            DataType.INT64: 8,
            DataType.HALF: 2,
            DataType.BFLOAT16: 2,
            DataType.FLOAT: 4,
            DataType.DOUBLE: 8,
        }[self]


class OperatorType(enum.Enum):
    """Operator vocabulary (reference: ffconst.h:62-154 OperatorType).

    Grouped as: graph sources, compute ops, MoE ops, parallel (layout) ops.
    """

    # Graph source / structural
    NOOP = enum.auto()
    INPUT = enum.auto()
    WEIGHT = enum.auto()

    # Dense / conv family
    LINEAR = enum.auto()
    CONV2D = enum.auto()
    POOL2D_MAX = enum.auto()
    POOL2D_AVG = enum.auto()
    BATCHNORM = enum.auto()
    LAYERNORM = enum.auto()
    EMBEDDING = enum.auto()
    DROPOUT = enum.auto()

    # Attention
    MULTIHEAD_ATTENTION = enum.auto()

    # Element-wise unary (reference folds these into OP_RELU..OP_RSQRT etc.)
    RELU = enum.auto()
    SIGMOID = enum.auto()
    TANH = enum.auto()
    ELU = enum.auto()
    GELU = enum.auto()
    IDENTITY = enum.auto()
    EXP = enum.auto()
    SIN = enum.auto()
    COS = enum.auto()
    POW = enum.auto()
    RSQRT = enum.auto()
    SCALAR_MULTIPLY = enum.auto()
    SCALAR_ADD = enum.auto()
    SCALAR_SUB = enum.auto()
    SCALAR_TRUE_DIV = enum.auto()

    # Element-wise binary
    EW_ADD = enum.auto()
    EW_SUB = enum.auto()
    EW_MUL = enum.auto()
    EW_DIV = enum.auto()
    EW_MAX = enum.auto()
    EW_MIN = enum.auto()

    # Matmul / reductions
    BATCHMATMUL = enum.auto()
    REDUCE_SUM = enum.auto()
    MEAN = enum.auto()

    # Shape / layout compute ops
    SOFTMAX = enum.auto()
    CONCAT = enum.auto()
    SPLIT = enum.auto()
    RESHAPE = enum.auto()
    TRANSPOSE = enum.auto()
    REVERSE = enum.auto()
    FLAT = enum.auto()
    CAST = enum.auto()

    # MoE family (reference: group_by/aggregate/topk/cache, SURVEY §2.2)
    TOPK = enum.auto()
    GROUP_BY = enum.auto()
    AGGREGATE = enum.auto()
    AGGREGATE_SPEC = enum.auto()
    # TPU-native addition (no reference counterpart): batched expert FFN
    # whose leading expert dim shards over the mesh — GShard-style expert
    # parallelism (the reference's EP is per-expert op placement instead)
    EXPERT_FFN = enum.auto()
    CACHE = enum.auto()
    GATHER = enum.auto()

    # Fused
    FUSED = enum.auto()

    # Parallel ops (layout-only; reference: src/parallel_ops/, SURVEY §2.3)
    REPARTITION = enum.auto()
    COMBINE = enum.auto()
    REPLICATE = enum.auto()
    REDUCTION = enum.auto()
    FUSED_PARALLEL = enum.auto()
    PIPELINE = enum.auto()
    ALLTOALL = enum.auto()  # TPU-native addition: sequence/expert all-to-all


PARALLEL_OP_TYPES = frozenset(
    {
        OperatorType.REPARTITION,
        OperatorType.COMBINE,
        OperatorType.REPLICATE,
        OperatorType.REDUCTION,
        OperatorType.FUSED_PARALLEL,
        OperatorType.PIPELINE,
        OperatorType.ALLTOALL,
    }
)


class ActiMode(enum.Enum):
    """Fused-activation modes (reference: ffconst.h ActiMode)."""

    NONE = enum.auto()
    RELU = enum.auto()
    SIGMOID = enum.auto()
    TANH = enum.auto()
    GELU = enum.auto()


class AggrMode(enum.Enum):
    """Embedding aggregation (reference: ffconst.h AggrMode)."""

    NONE = enum.auto()
    SUM = enum.auto()
    AVG = enum.auto()


class PoolType(enum.Enum):
    MAX = enum.auto()
    AVG = enum.auto()


class LossType(enum.Enum):
    """reference: ffconst.h LossType"""

    CATEGORICAL_CROSSENTROPY = enum.auto()
    SPARSE_CATEGORICAL_CROSSENTROPY = enum.auto()
    MEAN_SQUARED_ERROR_AVG_REDUCE = enum.auto()
    MEAN_SQUARED_ERROR_SUM_REDUCE = enum.auto()
    IDENTITY = enum.auto()


class MetricsType(enum.Enum):
    """reference: metrics_functions.h:12-45"""

    ACCURACY = enum.auto()
    CATEGORICAL_CROSSENTROPY = enum.auto()
    SPARSE_CATEGORICAL_CROSSENTROPY = enum.auto()
    MEAN_SQUARED_ERROR = enum.auto()
    ROOT_MEAN_SQUARED_ERROR = enum.auto()
    MEAN_ABSOLUTE_ERROR = enum.auto()


class ParameterSyncType(enum.Enum):
    """Gradient sync mode (reference: ffconst.h ParameterSyncType {NONE,PS,NCCL}).

    On TPU both map to XLA collectives; we keep the enum for API parity.
    PS → host-side aggregation (debug path), ALLREDUCE → psum over mesh.
    """

    NONE = enum.auto()
    PS = enum.auto()
    ALLREDUCE = enum.auto()  # reference's NCCL mode


class CompMode(enum.Enum):
    """reference: ffconst.h CompMode {COMP_MODE_TRAINING, COMP_MODE_INFERENCE}"""

    TRAINING = enum.auto()
    INFERENCE = enum.auto()
