"""TPU machine abstraction: views, resources, and hardware specs.

Re-design of the reference's MachineView/MachineResource
(reference: include/flexflow/machine_view.h:14-96) for TPU pod slices.
A MachineView keeps the reference's {start_device_id, dim[], stride[]}
shape — the search enumerates and hashes them the same way — but devices
are TPU chips on an ICI mesh instead of GPUs on nodes, and the lowering
maps a view onto axes of one global `jax.sharding.Mesh`.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class MachineView:
    """A strided grid of device ids (reference: machine_view.h:14-35).

    device id of grid point p = start_device_id + sum_i p[i] * stride[i].
    """

    start_device_id: int
    dims: Tuple[int, ...]
    strides: Tuple[int, ...]

    def __post_init__(self):
        if len(self.dims) != len(self.strides):
            raise ValueError("dims and strides must have equal length")
        if any(d <= 0 for d in self.dims):
            raise ValueError("view dims must be positive")

    @property
    def ndims(self) -> int:
        return len(self.dims)

    @property
    def num_devices(self) -> int:
        out = 1
        for d in self.dims:
            out *= d
        return out

    def device_ids(self) -> List[int]:
        ids = []
        for point in itertools.product(*(range(d) for d in self.dims)):
            ids.append(
                self.start_device_id
                + sum(p * s for p, s in zip(point, self.strides))
            )
        return ids

    def get_device_id(self, point: Sequence[int]) -> int:
        return self.start_device_id + sum(
            p * s for p, s in zip(point, self.strides)
        )

    def hash(self) -> int:
        """Stable content hash (reference: MachineView::hash() used as the
        Legion MappingTagID; here it keys simulator/search memo tables)."""
        h = 17
        h = h * 31 + self.start_device_id
        for d, s in zip(self.dims, self.strides):
            h = h * 31 + d
            h = h * 31 + s
        return h & 0x7FFFFFFFFFFFFFFF

    @staticmethod
    def dp_view(num_devices: int) -> "MachineView":
        """1-D view over all devices (reference: the --only-data-parallel
        default view, graph.cc:1588-1613)."""
        return MachineView(0, (num_devices,), (1,))


@dataclasses.dataclass(frozen=True)
class MachineResource:
    """Device budget available to a sub-search
    (reference: machine_view.h:51-60 {num_nodes, available_gpus_per_node...}).

    For TPU: num_nodes = hosts, chips_per_node = chips per host. The Unity
    DP search splits resources vertically (fewer hosts) or horizontally
    (fewer chips per host) when exploring parallel branches
    (reference: graph.cc:252-306).
    """

    num_nodes: int
    chips_per_node: int
    start_chip_id: int = 0
    start_node_id: int = 0

    @property
    def num_chips(self) -> int:
        return self.num_nodes * self.chips_per_node

    def is_valid_view(self, view: MachineView, total_chips_per_node: int) -> bool:
        """All device ids of the view must lie inside this resource block."""
        lo = self.start_node_id * total_chips_per_node + self.start_chip_id
        for did in view.device_ids():
            node = did // total_chips_per_node
            chip = did % total_chips_per_node
            if not (
                self.start_node_id <= node < self.start_node_id + self.num_nodes
            ):
                return False
            if not (
                self.start_chip_id <= chip < self.start_chip_id + self.chips_per_node
            ):
                return False
        del lo
        return True

    def vertical_split(self, n_left: int):
        """Split by nodes (reference: graph.cc 'vertical(i)')."""
        left = dataclasses.replace(self, num_nodes=n_left)
        right = dataclasses.replace(
            self,
            num_nodes=self.num_nodes - n_left,
            start_node_id=self.start_node_id + n_left,
        )
        return left, right

    def horizontal_split(self, n_left: int):
        """Split by chips-per-node (reference: graph.cc 'horizontal(i)')."""
        left = dataclasses.replace(self, chips_per_node=n_left)
        right = dataclasses.replace(
            self,
            chips_per_node=self.chips_per_node - n_left,
            start_chip_id=self.start_chip_id + n_left,
        )
        return left, right


# Known chip specs for the analytic cost model. Values are public figures;
# they feed the simulator's roofline estimates (SURVEY §2.5 machine model).
CHIP_SPECS = {
    # name: (bf16 TFLOP/s, HBM GB/s, HBM GiB, ICI GB/s per link, ici links)
    "v4": (275.0, 1228.0, 32.0, 50.0, 6),
    "v5e": (197.0, 819.0, 16.0, 45.0, 4),
    "v5p": (459.0, 2765.0, 95.0, 100.0, 6),
    "cpu-sim": (0.2, 50.0, 16.0, 10.0, 2),
}


@dataclasses.dataclass(frozen=True)
class MachineSpec:
    """Hardware description of the pod slice the search targets.

    Replaces the reference's SimpleMachineModel/EnhancedMachineModel inputs
    (reference: simulator.h:203-367): instead of NVLink/PCIe/NIC we model
    ICI torus links intra-slice and DCN across slices.
    """

    num_nodes: int = 1
    chips_per_node: int = 4
    chip: str = "v4"
    # mesh topology of the full slice, e.g. (4, 4, 2) for v4-32.
    torus: Optional[Tuple[int, ...]] = None
    dcn_bandwidth_gbps: float = 25.0  # per-host DCN GB/s
    # override the chip's HBM capacity (search-without-hardware: probe
    # feasibility against a hypothetical memory budget)
    hbm_bytes_override: Optional[int] = None

    @property
    def num_chips(self) -> int:
        return self.num_nodes * self.chips_per_node

    @property
    def peak_tflops(self) -> float:
        return CHIP_SPECS[self.chip][0]

    @property
    def hbm_gbps(self) -> float:
        return CHIP_SPECS[self.chip][1]

    @property
    def hbm_bytes(self) -> int:
        if self.hbm_bytes_override is not None:
            return self.hbm_bytes_override
        return int(CHIP_SPECS[self.chip][2] * (1 << 30))

    @property
    def ici_gbps(self) -> float:
        return CHIP_SPECS[self.chip][3]

    def resource(self) -> MachineResource:
        return MachineResource(self.num_nodes, self.chips_per_node)


def enumerate_machine_views(
    num_nodes: int, chips_per_node: int
) -> List[MachineView]:
    """All 1-D strided views over the chip grid
    (reference: register_all_machine_views, graph.cc:1783-1814):
    for every divisor-count of chips, contiguous and node-strided layouts.
    """
    total = num_nodes * chips_per_node
    views = []
    seen = set()

    def add(v: MachineView):
        key = (v.start_device_id, v.dims, v.strides)
        if key not in seen:
            seen.add(key)
            views.append(v)

    for ndev in range(1, total + 1):
        if total % ndev != 0:
            continue
        # contiguous runs
        for start in range(0, total - ndev + 1):
            add(MachineView(start, (ndev,), (1,)))
        # strided across nodes (one chip per node position)
        if ndev <= num_nodes and chips_per_node > 0:
            for chip in range(chips_per_node):
                add(MachineView(chip, (ndev,), (chips_per_node,)))
    return views
