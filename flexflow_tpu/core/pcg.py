"""Parallel Computation Graph (PCG).

The IR everything else operates on: the builder produces it, the substitution
engine rewrites it, the Unity DP search assigns MachineViews to its nodes, and
the executor lowers it to a jitted XLA program with GSPMD shardings.

Re-design of the reference's PCG (reference: include/flexflow/graph.h:245,
src/runtime/graph.cc) — same concepts (nodes = operators, edges carry tensor
indices, order-independent graph hash for search memoization,
split-at-bottleneck helpers), but a pure-data immutable-ish Python IR rather
than Legion-coupled C++ objects.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.core.parallel_tensor import ParallelTensorShape
from flexflow_tpu.core.types import OperatorType, PARALLEL_OP_TYPES


@dataclasses.dataclass(frozen=True)
class TensorRef:
    """A reference to output `out_idx` of node `guid`."""

    guid: int
    out_idx: int = 0


@dataclasses.dataclass
class PCGNode:
    """One operator node.

    params holds the op's static attributes (out_features, strides, activation,
    …) — the equivalent of the reference's per-op `Params` structs used for
    hashing/caching (SURVEY §2.2). weight_shapes lists this op's parameter
    tensors (reference: Op::weights).
    """

    guid: int
    op_type: OperatorType
    name: str
    inputs: Tuple[TensorRef, ...]
    params: Dict[str, object]
    output_shapes: Tuple[ParallelTensorShape, ...]
    weight_shapes: Tuple[ParallelTensorShape, ...] = ()
    machine_view: Optional[MachineView] = None

    @property
    def is_parallel_op(self) -> bool:
        return self.op_type in PARALLEL_OP_TYPES

    @property
    def num_outputs(self) -> int:
        return len(self.output_shapes)

    def params_hash(self) -> int:
        """Hash of (op_type, params) — keys the op-cost cache
        (reference: simulator.cc:532-572 keyed by OperatorParameters)."""
        items = tuple(sorted((k, repr(v)) for k, v in self.params.items()))
        return hash((self.op_type, items))


class PCGGraph:
    """Mutable DAG of PCGNodes.

    Edges are implicit in each node's `inputs` tuple; consumer maps are
    maintained for reverse traversal (reference keeps in/out edge multimaps,
    graph.h:245+).
    """

    def __init__(self):
        self.nodes: Dict[int, PCGNode] = {}
        self._next_guid = 100  # reference starts op guids at a magic base
        self._consumers: Dict[int, Set[int]] = defaultdict(set)

    # -- construction --------------------------------------------------------

    def fresh_guid(self) -> int:
        g = self._next_guid
        self._next_guid += 1
        return g

    def add_node(
        self,
        op_type: OperatorType,
        name: str,
        inputs: Sequence[TensorRef],
        params: Dict[str, object],
        output_shapes: Sequence[ParallelTensorShape],
        weight_shapes: Sequence[ParallelTensorShape] = (),
        guid: Optional[int] = None,
    ) -> PCGNode:
        guid = self.fresh_guid() if guid is None else guid
        node = PCGNode(
            guid=guid,
            op_type=op_type,
            name=name,
            inputs=tuple(inputs),
            params=dict(params),
            output_shapes=tuple(output_shapes),
            weight_shapes=tuple(weight_shapes),
        )
        self.nodes[guid] = node
        for ref in node.inputs:
            self._consumers[ref.guid].add(guid)
        return node

    def remove_node(self, guid: int):
        node = self.nodes.pop(guid)
        for ref in node.inputs:
            self._consumers[ref.guid].discard(guid)
        self._consumers.pop(guid, None)

    def replace_input(self, guid: int, old: TensorRef, new: TensorRef):
        node = self.nodes[guid]
        new_inputs = tuple(new if r == old else r for r in node.inputs)
        if new_inputs != node.inputs:
            self._consumers[old.guid].discard(guid)
            self._consumers[new.guid].add(guid)
            node.inputs = new_inputs

    def rebuild_consumers(self):
        self._consumers = defaultdict(set)
        for g, node in self.nodes.items():
            for ref in node.inputs:
                self._consumers[ref.guid].add(g)

    # -- queries -------------------------------------------------------------

    def consumers(self, guid: int) -> Set[int]:
        return set(self._consumers.get(guid, set()))

    def producers(self, guid: int) -> List[int]:
        return [r.guid for r in self.nodes[guid].inputs]

    def sources(self) -> List[int]:
        return [g for g, n in self.nodes.items() if not n.inputs]

    def sinks(self) -> List[int]:
        return [g for g in self.nodes if not self._consumers.get(g)]

    def shape_of(self, ref: TensorRef) -> ParallelTensorShape:
        return self.nodes[ref.guid].output_shapes[ref.out_idx]

    def topo_order(self) -> List[int]:
        """Kahn topological sort, deterministic (sorted by guid) so the
        executor's program order is stable (reference: dominators.h:156)."""
        indeg = {g: 0 for g in self.nodes}
        for node in self.nodes.values():
            seen_producers = set()
            for ref in node.inputs:
                if ref.guid in self.nodes and ref.guid not in seen_producers:
                    seen_producers.add(ref.guid)
                    indeg[node.guid] += 1
        ready = sorted(g for g, d in indeg.items() if d == 0)
        order = []
        while ready:
            g = ready.pop(0)
            order.append(g)
            for c in sorted(self._consumers.get(g, ())):
                prods = set(self.producers(c))
                if g in prods:
                    indeg[c] -= 1
                    if indeg[c] == 0:
                        ready.append(c)
            ready.sort()
        if len(order) != len(self.nodes):
            raise ValueError("PCG has a cycle")
        return order

    def hash(self) -> int:
        """Order-independent structural hash for search memoization
        (reference: Graph::hash, graph.cc:1513-1529 — sums per-node hashes
        so node iteration order doesn't matter)."""
        total = 0
        for node in self.nodes.values():
            h = node.params_hash()
            h = h * 31 + hash(tuple(node.output_shapes))
            h = h * 31 + hash(
                tuple((r.guid, r.out_idx) for r in node.inputs)
            )
            if node.machine_view is not None:
                h = h * 31 + node.machine_view.hash()
            total = (total + (h & 0xFFFFFFFFFFFFFFF)) & 0x7FFFFFFFFFFFFFFF
        return total

    def copy(self) -> "PCGGraph":
        g = PCGGraph()
        g._next_guid = self._next_guid
        for guid, node in self.nodes.items():
            g.nodes[guid] = dataclasses.replace(
                node,
                inputs=tuple(node.inputs),
                params=dict(node.params),
            )
        g.rebuild_consumers()
        return g

    # -- analysis helpers used by the search ---------------------------------

    def reachable_from(self, start: Iterable[int]) -> Set[int]:
        seen = set(start)
        stack = list(seen)
        while stack:
            g = stack.pop()
            for c in self._consumers.get(g, ()):
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        return seen

    def ancestors_of(self, start: Iterable[int]) -> Set[int]:
        seen = set(start)
        stack = list(seen)
        while stack:
            g = stack.pop()
            for p in self.producers(g):
                if p in self.nodes and p not in seen:
                    seen.add(p)
                    stack.append(p)
        return seen

    def split_at_node(self, guid: int) -> Tuple["PCGGraph", "PCGGraph"]:
        """Split into (prefix including guid, suffix) — the Unity sequence
        split (reference: graph.h:297 split_at_node). The bottleneck node is
        duplicated into both halves as the interface: it is the sink of the
        first half and an input source of the second.
        """
        pre_set = self.ancestors_of([guid])
        first = PCGGraph()
        second = PCGGraph()
        first._next_guid = second._next_guid = self._next_guid
        for g, node in self.nodes.items():
            tgt = first if g in pre_set else second
            tgt.nodes[g] = dataclasses.replace(
                node, inputs=tuple(node.inputs), params=dict(node.params)
            )
        # In the second half, the bottleneck appears as a NOOP source with
        # the same outputs.
        boundary = self.nodes[guid]
        needs_boundary = any(
            any(r.guid == guid for r in n.inputs)
            for n in second.nodes.values()
        )
        if needs_boundary:
            second.nodes[guid] = PCGNode(
                guid=guid,
                op_type=OperatorType.NOOP,
                name=boundary.name + ".boundary",
                inputs=(),
                params={},
                output_shapes=tuple(boundary.output_shapes),
                machine_view=boundary.machine_view,
            )
        first.rebuild_consumers()
        second.rebuild_consumers()
        return first, second

    def __len__(self):
        return len(self.nodes)

    def __repr__(self):
        lines = [f"PCGGraph({len(self.nodes)} nodes)"]
        for g in self.topo_order():
            n = self.nodes[g]
            ins = ", ".join(f"{r.guid}:{r.out_idx}" for r in n.inputs)
            outs = ", ".join(str(s) for s in n.output_shapes)
            mv = f" @{n.machine_view.dims}" if n.machine_view else ""
            lines.append(
                f"  {g} {n.op_type.name} '{n.name}' ({ins}) -> {outs}{mv}"
            )
        return "\n".join(lines)


def trace_embedding_ids_input(graph: "PCGGraph", guid: int) -> Optional[TensorRef]:
    """If `guid` is an EMBEDDING whose ids come (through layout-only
    parallel ops) straight from a batch INPUT, return the TensorRef of
    that input, else None.

    This is THE sparse-embedding eligibility tracer — the single source
    shared by the executor's fast path (Executor._sparse_embedding_guids,
    runtime/executor.py) and the search's update costing
    (search/simulator._sparse_embedding_rows), so the two can never
    disagree about which tables take the touched-rows update."""
    node = graph.nodes[guid]
    if node.op_type != OperatorType.EMBEDDING:
        return None
    if len(node.weight_shapes) != 1 or len(node.inputs) != 1:
        return None
    ref = node.inputs[0]
    src = graph.nodes[ref.guid]
    while src.is_parallel_op and len(src.inputs) == 1:
        ref = src.inputs[0]
        src = graph.nodes[ref.guid]
    if src.op_type != OperatorType.INPUT or src.inputs:
        return None
    return ref
