"""ONNX frontend: onnx graph -> FFModel builder calls.

Rebuild of the reference's ONNX importer (reference:
python/flexflow/onnx/model.py — node-type dispatch building FFModel layers
for Conv/Gemm/Pool/Concat/Split/Flatten/Add/Relu/...). The `onnx` package is
not part of this image's baked-in set, so the frontend is import-gated: it
raises a clear error at use, and everything else in flexflow_tpu works
without it.

Layout: ONNX convs are NCHW; like the torch frontend, inputs keep the NCHW
convention at the boundary and a transpose to NHWC is inserted before
conv-family ops, transposing back at Flatten.
"""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.core.types import DataType


def _require_onnx():
    try:
        import onnx  # noqa: F401

        return onnx
    except ImportError:
        raise ImportError(
            "the ONNX frontend needs the `onnx` package, which is not "
            "installed in this environment; use the torch_fx or keras_api "
            "frontend, or install onnx"
        ) from None


class ONNXModel:
    """Replays an ONNX graph into FFModel calls
    (reference: ONNXModel.apply, flexflow/onnx/model.py)."""

    def __init__(self, path_or_proto):
        onnx = _require_onnx()
        if isinstance(path_or_proto, (str, bytes)):
            self.model = onnx.load(path_or_proto)
        else:
            self.model = path_or_proto
        self.inits = {i.name for i in self.model.graph.initializer}

    def _const_array(self, name: str, env: Dict):
        """Static value of `name`: a graph initializer or a Constant/Range
        node's numpy output recorded in env. None when neither (i.e. the
        value is a runtime tensor). ONE lookup path for every handler
        that needs a static operand (shape/axes/pads/...)."""
        import numpy as np
        from onnx import numpy_helper

        init = next(
            (i for i in self.model.graph.initializer if i.name == name),
            None,
        )
        if init is not None:
            return numpy_helper.to_array(init)
        v = env.get(name)
        return v if isinstance(v, np.ndarray) else None

    @staticmethod
    def _attrs(node) -> Dict:
        out = {}
        for a in node.attribute:
            if a.type == 1:
                out[a.name] = a.f
            elif a.type == 2:
                out[a.name] = a.i
            elif a.type == 7:
                out[a.name] = list(a.ints)
            elif a.type == 3:
                out[a.name] = a.s.decode()
        return out

    def apply(self, ffmodel, input_tensors: Dict[str, object]):
        env = dict(input_tensors)
        nchw = {k: len(t.dims) == 4 for k, t in input_tensors.items()}

        def to_nhwc(name):
            t = env[name]
            if nchw.get(name, False):
                t = ffmodel.transpose(t, [0, 2, 3, 1])
                nchw[name] = False
            return t

        for node in self.model.graph.node:
            a = self._attrs(node)
            ins = [i for i in node.input if i not in self.inits]
            out = node.output[0]
            op = node.op_type
            if op == "Conv":
                x = to_nhwc(ins[0])
                k = a.get("kernel_shape", [1, 1])
                s = a.get("strides", [1, 1])
                p = a.get("pads", [0, 0, 0, 0])
                # find out_channels from the weight initializer shape
                wname = node.input[1]
                w = next(
                    i for i in self.model.graph.initializer if i.name == wname
                )
                # ONNX pads are [top, left, bottom, right]
                env[out] = ffmodel.conv2d(
                    x, w.dims[0], k[0], k[1], s[0], s[1],
                    (p[0], p[2]), (p[1], p[3]),
                    groups=a.get("group", 1),
                    use_bias=len(node.input) > 2,
                    name=node.name or None,
                )
                nchw[out] = False
            elif op in ("MaxPool", "AveragePool"):
                x = to_nhwc(ins[0])
                k = a.get("kernel_shape", [2, 2])
                s = a.get("strides", [1, 1])  # ONNX default: stride 1
                p = a.get("pads", [0, 0, 0, 0])
                env[out] = ffmodel.pool2d(
                    x, k[0], k[1], s[0], s[1], (p[0], p[2]), (p[1], p[3]),
                    pool_type="max" if op == "MaxPool" else "avg",
                    # ONNX AveragePool default: exclude padding from divisor
                    count_include_pad=bool(a.get("count_include_pad", 0)),
                )
                nchw[out] = False
            elif op == "GlobalAveragePool":
                x = to_nhwc(ins[0])
                h, w = x.dims[1], x.dims[2]
                env[out] = ffmodel.pool2d(x, h, w, h, w, 0, 0, pool_type="avg")
                nchw[out] = False
            elif op == "Gemm" or op == "MatMul":
                wname = node.input[1]
                w = next(
                    (i for i in self.model.graph.initializer if i.name == wname),
                    None,
                )
                if w is None:
                    # activation x activation (e.g. attention scores)
                    env[out] = ffmodel.batch_matmul(env[ins[0]], env[ins[1]])
                else:
                    out_dim = w.dims[0] if a.get("transB", 0) else w.dims[-1]
                    env[out] = ffmodel.dense(
                        env[ins[0]], out_dim, use_bias=len(node.input) > 2
                    )
            elif op == "Relu":
                env[out] = ffmodel.relu(env[ins[0]])
                nchw[out] = nchw.get(ins[0], False)
            elif op == "Sigmoid":
                env[out] = ffmodel.sigmoid(env[ins[0]])
            elif op == "Tanh":
                env[out] = ffmodel.tanh(env[ins[0]])
            elif op == "Softmax":
                env[out] = ffmodel.softmax(env[ins[0]], dim=a.get("axis", -1))
            elif op == "Flatten":
                x = env[ins[0]]
                if len(x.dims) == 4 and not nchw.get(ins[0], True):
                    x = ffmodel.transpose(x, [0, 3, 1, 2])
                env[out] = ffmodel.flat(x)
            elif op in ("Add", "Sub", "Mul"):
                import numpy as np

                xa, xb = env[ins[0]], env[ins[1]]
                if isinstance(xa, np.ndarray) or isinstance(xb, np.ndarray):
                    raise NotImplementedError(
                        f"ONNX frontend: {op} with a static (Constant/"
                        "Range) operand — materialize it as a graph input"
                    )
                fn2 = {
                    "Add": ffmodel.add,
                    "Sub": ffmodel.subtract,
                    "Mul": ffmodel.multiply,
                }[op]
                env[out] = fn2(xa, xb)
            elif op == "Concat":
                env[out] = ffmodel.concat([env[i] for i in ins], a.get("axis", 0))
            elif op == "Split":
                sizes = a.get("split")
                outs = ffmodel.split(
                    env[ins[0]],
                    sizes if sizes else len(node.output),
                    a.get("axis", 0),
                )
                for o, t in zip(node.output, outs):
                    env[o] = t
                continue
            elif op == "Reshape":
                import numpy as np

                shape_arr = self._const_array(node.input[1], env)
                if shape_arr is None:
                    raise NotImplementedError(
                        "ONNX frontend: Reshape with a runtime shape tensor"
                    )
                shape = [int(v) for v in shape_arr]
                x = env[ins[0]]
                if any(s == -1 for s in shape):
                    known = int(np.prod([s for s in shape if s != -1]))
                    total = int(np.prod(x.dims))
                    shape = [total // known if s == -1 else s for s in shape]
                env[out] = ffmodel.reshape(x, shape)
            elif op == "Transpose":
                env[out] = ffmodel.transpose(env[ins[0]], a["perm"])
            elif op == "Dropout":
                env[out] = ffmodel.dropout(env[ins[0]], a.get("ratio", 0.5))
            elif op == "Identity":
                env[out] = env[ins[0]]
            elif op == "BatchNormalization":
                x = to_nhwc(ins[0])
                env[out] = ffmodel.batch_norm(x, relu=False)
                nchw[out] = False
            elif op == "Cast":
                # ONNX TensorProto dtype codes -> framework dtypes
                # (reference: handleCast, flexflow/onnx/model.py)
                import numpy as np

                from flexflow_tpu.core.types import DataType

                codes = {
                    1: DataType.FLOAT,
                    6: DataType.INT32,
                    7: DataType.INT64,
                    10: DataType.HALF,
                    16: DataType.BFLOAT16,
                }
                code = int(a.get("to", 1))
                if code not in codes:
                    raise NotImplementedError(
                        f"ONNX frontend: Cast to dtype code {code}"
                    )
                x = env[ins[0]]
                if isinstance(x, np.ndarray):  # static (Constant/Range)
                    env[out] = x.astype(codes[code].to_jnp())
                else:
                    env[out] = ffmodel.cast(x, codes[code])
                    nchw[out] = nchw.get(ins[0], False)
            elif op == "Unsqueeze":
                # reference: handleUnsqueeze lowers to a reshape
                import numpy as np

                axes_l = a.get("axes")
                if axes_l is None:  # opset>=13: axes is a tensor input
                    axes_arr = self._const_array(node.input[1], env)
                    if axes_arr is None:
                        raise NotImplementedError(
                            "ONNX frontend: Unsqueeze with runtime axes"
                        )
                    axes_l = [int(v) for v in axes_arr]
                x = env[ins[0]]
                if isinstance(x, np.ndarray):  # static (Range position ids)
                    for ax in sorted(ax % (x.ndim + len(axes_l))
                                     for ax in axes_l):
                        x = np.expand_dims(x, ax)
                    env[out] = x
                else:
                    shape = list(x.dims)
                    nd = len(shape) + len(axes_l)
                    for ax in sorted(ax % nd for ax in axes_l):
                        shape.insert(ax, 1)
                    env[out] = ffmodel.reshape(x, shape)
            elif op == "Pad":
                pads = a.get("pads")
                if pads is None and len(node.input) > 1:
                    pad_arr = self._const_array(node.input[1], env)
                    if pad_arr is None:
                        raise NotImplementedError(
                            "ONNX frontend: Pad with a runtime pads tensor"
                        )
                    pads = [int(v) for v in pad_arr]
                if pads is not None and not any(pads):
                    env[out] = env[ins[0]]  # no-op pad
                    nchw[out] = nchw.get(ins[0], False)
                else:
                    raise NotImplementedError(
                        "ONNX frontend: non-zero Pad outside conv/pool "
                        "attributes (fold pads into the consumer op)"
                    )
            elif op == "Constant":
                # materialized at consumers (initializer-like); the
                # reference records the numpy value (handleConstant)
                from onnx import numpy_helper

                for attr in node.attribute:
                    if attr.name == "value":
                        env[out] = numpy_helper.to_array(attr.t)
            elif op == "Range":
                # reference: handleRange builds the static index vector
                import numpy as np

                start, limit, delta = (env.get(i, i) for i in node.input)
                env[out] = np.arange(
                    float(np.asarray(start)),
                    float(np.asarray(limit)),
                    float(np.asarray(delta)),
                )
            else:
                raise NotImplementedError(f"ONNX frontend: op {op!r}")

        outputs = [env[o.name] for o in self.model.graph.output if o.name in env]
        return outputs if len(outputs) != 1 else outputs[0]


class ONNXModelKeras(ONNXModel):
    """Keras-exported ONNX graphs (reference: flexflow/onnx/model.py:339 —
    same replay, reference ctor spelling (filename, ffconfig, ffmodel);
    keras exporters emit dense kernels as initializers the base replay
    already resolves through _const_array)."""

    def __init__(self, path_or_proto, ffconfig=None, ffmodel=None):
        super().__init__(path_or_proto)
