"""Keras-style dataset loaders (reference: python/flexflow/keras/datasets/
— mnist, cifar10/100, reuters loaders used by the keras example zoo).

Each `load_data()` first looks for a locally cached copy (the standard
`~/.keras/datasets` npz layout, or `FF_DATASETS_DIR`); with no cache and no
network (this environment has zero egress) it falls back to DETERMINISTIC
synthetic data with the real shapes/dtypes/class counts so the example zoo
runs end-to-end — a warning marks the substitution.
"""

from __future__ import annotations

import os
import warnings
from typing import Tuple

import numpy as np


def _cache_dir() -> str:
    return os.environ.get(
        "FF_DATASETS_DIR",
        os.path.join(os.path.expanduser("~"), ".keras", "datasets"),
    )


def _synthetic_images(name, n_train, n_test, shape, classes, seed):
    warnings.warn(
        f"{name}: no cached dataset found; using deterministic synthetic "
        f"data (set FF_DATASETS_DIR to use a real copy)",
        stacklevel=3,
    )
    rng = np.random.RandomState(seed)
    x_train = rng.randint(0, 256, size=(n_train,) + shape, dtype=np.uint8)
    y_train = rng.randint(0, classes, size=(n_train,)).astype(np.int64)
    x_test = rng.randint(0, 256, size=(n_test,) + shape, dtype=np.uint8)
    y_test = rng.randint(0, classes, size=(n_test,)).astype(np.int64)
    return (x_train, y_train), (x_test, y_test)


def _load_npz(path, keys):
    with np.load(path, allow_pickle=True) as f:
        return tuple(f[k] for k in keys)


def load_mnist(n_train: int = 60000, n_test: int = 10000):
    """(x_train [n,28,28] u8, y_train), (x_test, y_test)."""
    path = os.path.join(_cache_dir(), "mnist.npz")
    if os.path.exists(path):
        x_tr, y_tr, x_te, y_te = _load_npz(
            path, ["x_train", "y_train", "x_test", "y_test"]
        )
        return (x_tr, y_tr), (x_te, y_te)
    return _synthetic_images("mnist", n_train, n_test, (28, 28), 10, seed=0)


def load_cifar10(n_train: int = 50000, n_test: int = 10000):
    """(x_train [n,32,32,3] u8, y_train [n,1]), (x_test, y_test) — the
    keras cifar layout (labels are column vectors)."""
    path = os.path.join(_cache_dir(), "cifar10.npz")
    if os.path.exists(path):
        x_tr, y_tr, x_te, y_te = _load_npz(
            path, ["x_train", "y_train", "x_test", "y_test"]
        )
        return (x_tr, y_tr), (x_te, y_te)
    (x_tr, y_tr), (x_te, y_te) = _synthetic_images(
        "cifar10", n_train, n_test, (32, 32, 3), 10, seed=1
    )
    return (x_tr, y_tr.reshape(-1, 1)), (x_te, y_te.reshape(-1, 1))


def load_cifar100(n_train: int = 50000, n_test: int = 10000):
    path = os.path.join(_cache_dir(), "cifar100.npz")
    if os.path.exists(path):
        x_tr, y_tr, x_te, y_te = _load_npz(
            path, ["x_train", "y_train", "x_test", "y_test"]
        )
        return (x_tr, y_tr), (x_te, y_te)
    (x_tr, y_tr), (x_te, y_te) = _synthetic_images(
        "cifar100", n_train, n_test, (32, 32, 3), 100, seed=2
    )
    return (x_tr, y_tr.reshape(-1, 1)), (x_te, y_te.reshape(-1, 1))


def load_reuters(
    num_words: int = 10000,
    maxlen: int = 200,
    n_train: int = 8982,
    n_test: int = 2246,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Padded int32 sequences [n, maxlen] + 46-class labels (the reference's
    reuters MLP example consumes exactly this after its own pad step)."""
    path = os.path.join(_cache_dir(), "reuters.npz")
    if os.path.exists(path):
        x_tr, y_tr, x_te, y_te = _load_npz(
            path, ["x_train", "y_train", "x_test", "y_test"]
        )
        return (x_tr, y_tr), (x_te, y_te)
    warnings.warn(
        "reuters: no cached dataset found; using deterministic synthetic "
        "sequences",
        stacklevel=2,
    )
    rng = np.random.RandomState(3)

    def seqs(n):
        x = rng.randint(1, num_words, size=(n, maxlen)).astype(np.int32)
        lengths = rng.randint(maxlen // 4, maxlen, size=n)
        for i, L in enumerate(lengths):  # zero-pad the tails like real data
            x[i, L:] = 0
        y = rng.randint(0, 46, size=n).astype(np.int64)
        return x, y

    return seqs(n_train), seqs(n_test)
