"""Keras preprocessing utilities, implemented natively.

The reference's `flexflow.keras.preprocessing` is a thin re-export of the
external `keras_preprocessing` pip package (reference:
python/flexflow/keras/preprocessing/sequence.py, text.py); this module
provides the same surface without the dependency: `pad_sequences`,
`make_sampling_table`, `skipgrams` (sequence.py) and a minimal
`Tokenizer` / `one_hot` / `text_to_word_sequence` (text.py).
"""

from __future__ import annotations

import hashlib
import random as _random
from typing import List, Optional, Sequence

import numpy as np


def pad_sequences(
    sequences,
    maxlen: Optional[int] = None,
    dtype="int32",
    padding: str = "pre",
    truncating: str = "pre",
    value=0.0,
):
    """keras_preprocessing.sequence.pad_sequences semantics."""
    lengths = [len(s) for s in sequences]
    if maxlen is None:
        maxlen = max(lengths) if lengths else 0
    out = np.full((len(sequences), maxlen), value, dtype=dtype)
    for i, s in enumerate(sequences):
        if not len(s):
            continue
        if truncating == "pre":
            trunc = s[-maxlen:]
        elif truncating == "post":
            trunc = s[:maxlen]
        else:
            raise ValueError(f"truncating must be pre|post, got {truncating!r}")
        trunc = np.asarray(trunc, dtype=dtype)
        if padding == "post":
            out[i, : len(trunc)] = trunc
        elif padding == "pre":
            out[i, -len(trunc):] = trunc
        else:
            raise ValueError(f"padding must be pre|post, got {padding!r}")
    return out


def make_sampling_table(size: int, sampling_factor: float = 1e-5):
    """Zipf-based word-frequency sampling table (word2vec subsampling)."""
    gamma = 0.577
    rank = np.arange(size)
    rank[0] = 1
    inv_fq = rank * (np.log(rank) + gamma) + 0.5 - 1.0 / (12.0 * rank)
    f = sampling_factor * inv_fq
    return np.minimum(1.0, f / np.sqrt(f))


def skipgrams(
    sequence: Sequence[int],
    vocabulary_size: int,
    window_size: int = 4,
    negative_samples: float = 1.0,
    shuffle: bool = True,
    sampling_table=None,
    seed: Optional[int] = None,
):
    """(word, context) couples with labels, keras semantics."""
    couples = []
    labels = []
    for i, wi in enumerate(sequence):
        if not wi:
            continue
        if sampling_table is not None:
            if sampling_table[wi] < _random.random():
                continue
        window_start = max(0, i - window_size)
        window_end = min(len(sequence), i + window_size + 1)
        for j in range(window_start, window_end):
            if j == i:
                continue
            wj = sequence[j]
            if not wj:
                continue
            couples.append([wi, wj])
            labels.append(1)
    if negative_samples > 0:
        num_negative = int(len(labels) * negative_samples)
        words = [c[0] for c in couples]
        _random.shuffle(words)
        couples += [
            [words[i % len(words)], _random.randint(1, vocabulary_size - 1)]
            for i in range(num_negative)
        ]
        labels += [0] * num_negative
    if shuffle:
        if seed is None:
            seed = _random.randint(0, 10**6)
        _random.Random(seed).shuffle(couples)
        _random.Random(seed).shuffle(labels)
    return couples, labels


def text_to_word_sequence(
    text: str,
    filters='!"#$%&()*+,-./:;<=>?@[\\]^_`{|}~\t\n',
    lower: bool = True,
    split: str = " ",
) -> List[str]:
    if lower:
        text = text.lower()
    table = str.maketrans({c: split for c in filters})
    return [w for w in text.translate(table).split(split) if w]


def one_hot(text: str, n: int, **kw) -> List[int]:
    """Hash each word into [1, n) (keras one_hot is hashing, not 1-hot)."""
    words = text_to_word_sequence(text, **kw)
    return [
        1 + int(hashlib.md5(w.encode()).hexdigest(), 16) % (n - 1)
        for w in words
    ]


class Tokenizer:
    """Minimal keras Tokenizer: fit_on_texts + texts_to_sequences +
    texts_to_matrix(binary/count)."""

    def __init__(self, num_words: Optional[int] = None, oov_token=None, **kw):
        self.num_words = num_words
        self.oov_token = oov_token
        self.word_counts: dict = {}
        self.word_index: dict = {}

    def fit_on_texts(self, texts):
        for text in texts:
            for w in text_to_word_sequence(text):
                self.word_counts[w] = self.word_counts.get(w, 0) + 1
        ordered = sorted(
            self.word_counts.items(), key=lambda kv: (-kv[1], kv[0])
        )
        start = 1
        self.word_index = {}
        if self.oov_token is not None:
            self.word_index[self.oov_token] = 1
            start = 2
        for i, (w, _) in enumerate(ordered):
            self.word_index[w] = i + start

    def texts_to_sequences(self, texts):
        oov = (
            self.word_index.get(self.oov_token)
            if self.oov_token is not None
            else None
        )
        out = []
        for text in texts:
            seq = []
            for w in text_to_word_sequence(text):
                idx = self.word_index.get(w, oov)
                if idx is None:
                    continue
                if self.num_words and idx >= self.num_words:
                    idx = oov
                    if idx is None:
                        continue
                seq.append(idx)
            out.append(seq)
        return out

    def texts_to_matrix(self, texts, mode: str = "binary"):
        n = self.num_words or (len(self.word_index) + 1)
        m = np.zeros((len(texts), n), dtype=np.float32)
        for i, seq in enumerate(self.texts_to_sequences(texts)):
            for idx in seq:
                if mode == "binary":
                    m[i, idx] = 1.0
                elif mode == "count":
                    m[i, idx] += 1.0
                else:
                    raise ValueError(f"mode must be binary|count, got {mode!r}")
        return m


# ---- np_utils (reference: python/flexflow/keras/utils/np_utils.py) ---------


def to_categorical(y, num_classes: Optional[int] = None, dtype="float32"):
    """Integer class vector -> one-hot matrix, classes axis last; a
    trailing singleton dim is squeezed first (so shape [n, 1] labels
    one-hot to [n, k] like flat ones). Scatter-indexed like the
    reference (np_utils.py:45-55): a label >= num_classes raises
    IndexError rather than silently emitting an all-zero row, and
    negative labels index from the end (numpy semantics)."""
    y = np.asarray(y, dtype="int64")
    shape = y.shape
    if len(shape) > 1 and shape[-1] == 1:
        shape = shape[:-1]
    flat = y.reshape(-1)
    k = int(num_classes) if num_classes else int(flat.max()) + 1
    out = np.zeros((flat.shape[0], k), dtype=dtype)
    out[np.arange(flat.shape[0]), flat] = 1
    return out.reshape(shape + (k,))


def normalize(x, axis: int = -1, order: int = 2):
    """Lp-normalize an array along `axis` (zero-norm slices pass through)."""
    x = np.asarray(x)
    norm = np.atleast_1d(np.linalg.norm(x, order, axis))
    norm[norm == 0] = 1.0
    return x / np.expand_dims(norm, axis)
