"""Frontends (reference: SURVEY §2.7 — python/flexflow/{torch,keras,onnx}).

torch_fx   — torch.fx trace -> FFModel replay (+ weight transfer)
keras_api  — Sequential/functional Model with Keras layer/optimizer names
onnx_model — ONNX graph replay (import-gated: `onnx` not baked in)
"""
