"""Keras-style frontend.

Rebuild of the reference's Keras frontend (reference: python/flexflow/keras/
— Sequential + functional Model over FFModel, BaseModel.fit/evaluate
keras/models/base_model.py:196-283, layer classes under keras/layers/).
Layers are lightweight specs; `Model.compile` lowers the layer graph into
FFModel builder calls, then fit/evaluate delegate to the runtime.

    from flexflow_tpu.frontends import keras_api as keras
    model = keras.Sequential([
        keras.Input(shape=(784,)),
        keras.Dense(512, activation="relu"),
        keras.Dense(10),
    ])
    model.compile(optimizer=keras.SGD(0.01), loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, epochs=2, batch_size=64)
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.types import ActiMode, DataType, LossType, MetricsType
from flexflow_tpu.frontends import keras_callbacks as callbacks  # noqa: F401
from flexflow_tpu.frontends.keras_callbacks import (  # noqa: F401
    Callback,
    EpochVerifyMetrics,
    LearningRateScheduler,
    VerifyMetrics,
)
from flexflow_tpu.runtime.model import FFModel
from flexflow_tpu.runtime.optimizer import AdamOptimizer, SGDOptimizer

_ACT = {
    None: ActiMode.NONE,
    "relu": ActiMode.RELU,
    "sigmoid": ActiMode.SIGMOID,
    "tanh": ActiMode.TANH,
    "gelu": ActiMode.GELU,
    "softmax": "softmax",  # handled as a separate op
}

_LOSS = {
    "categorical_crossentropy": LossType.CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy": LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
}

_METRIC = {
    "accuracy": MetricsType.ACCURACY,
    "sparse_categorical_crossentropy": MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "categorical_crossentropy": MetricsType.CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.MEAN_SQUARED_ERROR,
    "mse": MetricsType.MEAN_SQUARED_ERROR,
    "root_mean_squared_error": MetricsType.ROOT_MEAN_SQUARED_ERROR,
    "rmse": MetricsType.ROOT_MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.MEAN_ABSOLUTE_ERROR,
    "mae": MetricsType.MEAN_ABSOLUTE_ERROR,
}


# -- initializers (reference: flexflow/keras/initializers.py) ---------------


class Initializer:
    """Maps to a runtime initializer (runtime/initializer.py); pass as
    Dense/Conv2D kernel_initializer / bias_initializer."""

    def _runtime(self):
        raise NotImplementedError


class DefaultInitializer(Initializer):
    def _runtime(self):
        return None  # op picks its default (glorot for kernels, zero bias)


class Zeros(Initializer):
    def _runtime(self):
        from flexflow_tpu.runtime.initializer import ZeroInitializer

        return ZeroInitializer()


class GlorotUniform(Initializer):
    def __init__(self, seed=0):
        self.seed = seed

    def _runtime(self):
        from flexflow_tpu.runtime.initializer import GlorotUniform as G

        return G(seed=self.seed)


class RandomUniform(Initializer):
    def __init__(self, seed=0, minval=-0.05, maxval=0.05):
        self.seed, self.minval, self.maxval = seed, minval, maxval

    def _runtime(self):
        from flexflow_tpu.runtime.initializer import UniformInitializer

        return UniformInitializer(
            seed=self.seed, min_val=self.minval, max_val=self.maxval
        )


class RandomNormal(Initializer):
    def __init__(self, seed=0, mean=0.0, stddev=0.05):
        self.seed, self.mean, self.stddev = seed, mean, stddev

    def _runtime(self):
        from flexflow_tpu.runtime.initializer import NormInitializer

        return NormInitializer(
            seed=self.seed, mean=self.mean, stddev=self.stddev
        )


def _init_arg(init):
    if init is None:
        return None
    if isinstance(init, Initializer):
        return init._runtime()
    return init  # a runtime initializer passed directly


# -- losses / metrics objects (reference: keras/losses.py, keras/metrics.py)


class Loss:
    type = None


class CategoricalCrossentropy(Loss):
    type = "categorical_crossentropy"


class SparseCategoricalCrossentropy(Loss):
    type = "sparse_categorical_crossentropy"


class MeanSquaredError(Loss):
    type = "mean_squared_error"


class Metric:
    type = None


class Accuracy(Metric):
    type = "accuracy"


class MetricCategoricalCrossentropy(Metric):
    type = "categorical_crossentropy"


class MetricSparseCategoricalCrossentropy(Metric):
    type = "sparse_categorical_crossentropy"


class MetricMeanSquaredError(Metric):
    type = "mean_squared_error"


class RootMeanSquaredError(Metric):
    type = "root_mean_squared_error"


class MeanAbsoluteError(Metric):
    type = "mean_absolute_error"


# -- optimizers (reference: flexflow/keras/optimizers.py) -------------------


def SGD(learning_rate=0.01, momentum=0.0, nesterov=False, weight_decay=0.0):
    return SGDOptimizer(
        lr=learning_rate,
        momentum=momentum,
        nesterov=nesterov,
        weight_decay=weight_decay,
    )


def Adam(learning_rate=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-8):
    return AdamOptimizer(
        alpha=learning_rate, beta1=beta_1, beta2=beta_2, epsilon=epsilon
    )


# -- layer specs ------------------------------------------------------------


class Layer:
    def __init__(self, name=None):
        self.name = name

    def __call__(self, *inputs):
        """Functional API: returns a Node wiring this layer after inputs.
        Merge layers accept a single list (keras: Concatenate(axis)([a, b]))."""
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = tuple(inputs[0])
        return Node(self, [n for n in inputs])

    def build(self, ff: FFModel, tensors):
        raise NotImplementedError

    def _weight_guid(self, ffmodel):
        """PCG guid of this layer's (first) lowered op — set once the
        model is compiled (output_tensors recorded by Model._lower)."""
        outs = getattr(self, "output_tensors", None)
        if not outs:
            raise RuntimeError(
                f"layer {self.name or type(self).__name__} has no lowered "
                "op; compile the model first"
            )
        return outs[0].ref.guid

    def get_weights(self, ffmodel):
        """reference: Layer.get_weights(ffmodel) → per-weight numpy copies
        (net2net teacher→student transfer,
        examples/python/keras/func_mnist_mlp_net2net.py)."""
        import numpy as _np

        guid = self._weight_guid(ffmodel)
        return tuple(_np.asarray(w) for w in ffmodel.params.get(guid, ()))

    def set_weights(self, ffmodel, *weights):
        """reference: Layer.set_weights(ffmodel, kernel[, bias])."""
        import jax.numpy as _jnp

        guid = self._weight_guid(ffmodel)
        cur = ffmodel.params.get(guid, [])
        if len(weights) != len(cur):
            raise ValueError(
                f"layer expects {len(cur)} weight arrays, got {len(weights)}"
            )
        ffmodel.params[guid] = [
            _jnp.asarray(w, dtype=c.dtype).reshape(c.shape)
            for w, c in zip(weights, cur)
        ]


class Node:
    """Functional-API handle: a layer applied to upstream nodes."""

    def __init__(self, layer: Optional[Layer], inputs: List["Node"], shape=None):
        self.layer = layer
        self.inputs = inputs
        self.shape = shape  # only for Input nodes


_STR_DTYPE = {
    "float32": DataType.FLOAT,
    "float16": DataType.HALF,
    "bfloat16": DataType.BFLOAT16,
    "int32": DataType.INT32,
    "int64": DataType.INT64,
}


def Input(shape: Sequence[int], dtype=DataType.FLOAT, name=None):
    n = Node(None, [], shape=tuple(shape))
    if isinstance(dtype, str):
        if dtype not in _STR_DTYPE:
            raise ValueError(
                f"unsupported dtype {dtype!r}; supported: "
                f"{sorted(_STR_DTYPE)}"
            )
        dtype = _STR_DTYPE[dtype]
    n.dtype = dtype
    n.name = name
    return n


def _resolve_act(name):
    if name not in _ACT:
        raise ValueError(
            f"unknown activation {name!r}; supported: "
            f"{sorted(k for k in _ACT if k)}"
        )
    return _ACT[name]


class Dense(Layer):
    def __init__(self, units, activation=None, use_bias=True, name=None,
                 kernel_initializer=None, bias_initializer=None,
                 input_shape=None):
        super().__init__(name)
        self.units = units
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer
        # input_shape accepted for keras source compatibility; shapes come
        # from the upstream node here
        self.input_shape = input_shape

    def build(self, ff, ts):
        act = _resolve_act(self.activation)
        kw = dict(
            use_bias=self.use_bias,
            name=self.name,
            kernel_initializer=_init_arg(self.kernel_initializer),
            bias_initializer=_init_arg(self.bias_initializer),
        )
        if act == "softmax":
            t = ff.dense(ts[0], self.units, **kw)
            return ff.softmax(t)
        return ff.dense(ts[0], self.units, activation=act, **kw)


def _same_pad(in_size, kernel, stride):
    """TF/keras 'same' padding: out = ceil(in/stride), extra pad on the
    bottom/right side."""
    out = -(-in_size // stride)
    total = max((out - 1) * stride + kernel - in_size, 0)
    return (total // 2, total - total // 2)


def _resolve_pad(padding, dims_hw, kernel, strides):
    """padding: "valid" | "same" | int | (ph, pw) (the reference keras
    frontend takes explicit tuples — layers/convolutional.py)."""
    if padding == "same":
        h, w = dims_hw
        return (
            _same_pad(h, kernel[0], strides[0]),
            _same_pad(w, kernel[1], strides[1]),
        )
    if padding == "valid" or padding is None:
        return 0, 0
    if isinstance(padding, int):
        return padding, padding
    ph, pw = padding
    return ph, pw


class _SpatialLayer(Layer):
    """Shared channels_first/last handling: the engine computes in NHWC
    (the TPU-native layout); channels_first inputs (the reference's
    native layout) are transposed in and back out per layer — XLA elides
    the adjacent inverse-transpose pairs between consecutive layers."""

    data_format = "channels_last"

    def _in(self, ff, t):
        if self.data_format == "channels_first":
            return ff.transpose(t, [0, 2, 3, 1])
        return t

    def _out(self, ff, t):
        if self.data_format == "channels_first":
            return ff.transpose(t, [0, 3, 1, 2])
        return t


class Conv2D(_SpatialLayer):
    def __init__(self, filters, kernel_size=(3, 3), strides=(1, 1),
                 padding="valid", activation=None, groups=1, use_bias=True,
                 name=None, kernel_initializer=None, bias_initializer=None,
                 input_shape=None, data_format=None):
        super().__init__(name)
        self.filters = filters
        k = kernel_size if isinstance(kernel_size, (tuple, list)) else (kernel_size,) * 2
        s = strides if isinstance(strides, (tuple, list)) else (strides,) * 2
        self.kernel, self.strides = k, s
        self.padding = padding
        self.activation = activation
        self.groups = groups
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer
        self.input_shape = input_shape  # keras source compat; unused
        if data_format is not None:
            self.data_format = data_format

    def build(self, ff, ts):
        x = self._in(ff, ts[0])
        _, h, w, _ = x.dims  # NHWC
        ph, pw = _resolve_pad(self.padding, (h, w), self.kernel, self.strides)
        act = _resolve_act(self.activation)
        softmax = act == "softmax"
        t = ff.conv2d(
            x, self.filters, self.kernel[0], self.kernel[1],
            self.strides[0], self.strides[1], ph, pw,
            activation=ActiMode.NONE if softmax else act,
            groups=self.groups, use_bias=self.use_bias, name=self.name,
            kernel_initializer=_init_arg(self.kernel_initializer),
            bias_initializer=_init_arg(self.bias_initializer),
        )
        if softmax:
            t = ff.softmax(t)
        return self._out(ff, t)


class _Pool2D(_SpatialLayer):
    kind = "max"

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid",
                 name=None, data_format=None):
        super().__init__(name)
        p = pool_size if isinstance(pool_size, (tuple, list)) else (pool_size,) * 2
        s = strides if strides is not None else p
        s = s if isinstance(s, (tuple, list)) else (s,) * 2
        self.pool, self.strides, self.padding = p, s, padding
        if data_format is not None:
            self.data_format = data_format

    def build(self, ff, ts):
        x = self._in(ff, ts[0])
        _, h, w, _ = x.dims  # NHWC
        ph, pw = _resolve_pad(self.padding, (h, w), self.pool, self.strides)
        t = ff.pool2d(
            x, self.pool[0], self.pool[1], self.strides[0], self.strides[1],
            ph, pw, pool_type=self.kind, count_include_pad=False,
            name=self.name,
        )
        return self._out(ff, t)


class MaxPooling2D(_Pool2D):
    kind = "max"


class AveragePooling2D(_Pool2D):
    kind = "avg"


class Flatten(Layer):
    def build(self, ff, ts):
        return ff.flat(ts[0], name=self.name)


class Dropout(Layer):
    def __init__(self, rate, name=None):
        super().__init__(name)
        self.rate = rate

    def build(self, ff, ts):
        return ff.dropout(ts[0], self.rate, name=self.name)


class Activation(Layer):
    def __init__(self, fn, name=None):
        super().__init__(name)
        self.fn = fn

    def build(self, ff, ts):
        if self.fn == "softmax":
            return ff.softmax(ts[0], name=self.name)
        return {
            "relu": ff.relu,
            "sigmoid": ff.sigmoid,
            "tanh": ff.tanh,
            "gelu": ff.gelu,
        }[self.fn](ts[0], name=self.name)


class Embedding(Layer):
    def __init__(self, input_dim, output_dim, name=None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def build(self, ff, ts):
        return ff.embedding(ts[0], self.input_dim, self.output_dim, name=self.name)


class BatchNormalization(_SpatialLayer):
    def __init__(self, name=None, data_format=None):
        super().__init__(name)
        if data_format is not None:
            self.data_format = data_format

    def build(self, ff, ts):
        x = self._in(ff, ts[0]) if len(ts[0].dims) == 4 else ts[0]
        t = ff.batch_norm(x, relu=False, name=self.name)
        return self._out(ff, t) if len(ts[0].dims) == 4 else t


class LayerNormalization(Layer):
    def __init__(self, epsilon=1e-5, name=None):
        super().__init__(name)
        self.eps = epsilon

    def build(self, ff, ts):
        return ff.layer_norm(ts[0], eps=self.eps, name=self.name)


class Reshape(Layer):
    """reference: keras/layers/core.py Reshape — target_shape EXCLUDES the
    batch dim (keras semantics)."""

    def __init__(self, target_shape, name=None):
        super().__init__(name)
        self.target_shape = tuple(target_shape)

    def build(self, ff, ts):
        batch = ts[0].dims[0]
        return ff.reshape(
            ts[0], (batch,) + self.target_shape, name=self.name
        )


class Permute(Layer):
    """reference: keras/layers/core.py Permute — dims are 1-indexed over
    the non-batch axes (keras semantics); the batch axis stays first."""

    def __init__(self, dims, name=None):
        super().__init__(name)
        self.dims = tuple(dims)

    def build(self, ff, ts):
        perm = (0,) + tuple(d for d in self.dims)
        return ff.transpose(ts[0], perm, name=self.name)


class Concatenate(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__(name)
        self.axis = axis

    def build(self, ff, ts):
        return ff.concat(ts, self.axis, name=self.name)


class Add(Layer):
    def build(self, ff, ts):
        return ff.add(ts[0], ts[1], name=self.name)


class Subtract(Layer):
    def build(self, ff, ts):
        return ff.subtract(ts[0], ts[1], name=self.name)


class Multiply(Layer):
    def build(self, ff, ts):
        return ff.multiply(ts[0], ts[1], name=self.name)


# functional-style merge aliases (reference: keras/layers/merge.py exports
# both the classes and lowercase functions)


def concatenate(tensors, axis=-1, name=None):
    return Concatenate(axis=axis, name=name)(*tensors)


def add(tensors, name=None):
    return Add(name=name)(*tensors)


def subtract(tensors, name=None):
    return Subtract(name=name)(*tensors)


def multiply(tensors, name=None):
    return Multiply(name=name)(*tensors)


# -- models (reference: keras/models/base_model.py) -------------------------


class Model:
    def __init__(self, inputs=None, outputs=None, config: Optional[FFConfig] = None):
        self._inputs = (
            [inputs] if isinstance(inputs, Node) else list(inputs or [])
        )
        self._outputs = (
            [outputs] if isinstance(outputs, Node) else list(outputs or [])
        )
        self.config = config or FFConfig()
        self.ffmodel: Optional[FFModel] = None

    # lower the Node graph into FFModel builder calls
    def _lower(self, batch_size: int) -> FFModel:
        ff = FFModel(self.config)
        built = {}
        self._layers_by_name = {}
        self._layer_order = []
        registered: set = set()
        counters: dict = {}

        def auto_name(layer: Layer) -> str:
            base = {"Flatten": "flat"}.get(
                type(layer).__name__, type(layer).__name__.lower()
            )
            n = counters.get(base, 0)
            counters[base] = n + 1
            return base if n == 0 else f"{base}_{n}"

        def visit(node: Node):
            if id(node) in built:
                return built[id(node)]
            if node.layer is None:  # Input
                t = ff.create_tensor(
                    (batch_size,) + tuple(node.shape),
                    dtype=getattr(node, "dtype", DataType.FLOAT),
                    name=getattr(node, "name", None),
                )
                t.from_layer = None
                t.to_layers = []
            else:
                layer = node.layer
                ins = [visit(i) for i in node.inputs]
                t = layer.build(ff, ins)
                # introspection surface (reference: keras tensors carry
                # from_layer/to_layers, layers carry input/output_tensors
                # — func_mnist_cnn.py reads them via model.get_layer).
                # A layer object applied N times (weight-style sharing is
                # NOT implied — each application lowers fresh ops)
                # registers ONCE and ACCUMULATES its per-application
                # tensors; duplicate explicit names are an error rather
                # than a silent shadow.
                if id(layer) not in registered:
                    reg = layer.name or auto_name(layer)
                    if reg in self._layers_by_name:
                        raise ValueError(
                            f"two layers named {reg!r}; layer names must "
                            "be unique"
                        )
                    self._layers_by_name[reg] = layer
                    self._layer_order.append(layer)
                    registered.add(id(layer))
                    layer.input_tensors = []
                    layer.output_tensors = []
                layer.input_tensors.extend(ins)
                layer.output_tensors.append(t)
                t.from_layer = layer
                t.to_layers = []
                for i in ins:
                    if getattr(i, "to_layers", None) is not None:
                        i.to_layers.append(layer)
            built[id(node)] = t
            return t

        for out in self._outputs:
            visit(out)
        # fit()'s x list follows the DECLARED Model(inputs=[...]) order,
        # which can differ from graph-discovery order (the engine's
        # _input_order) when a later input is reached first — e.g.
        # Multiply()([nx1, nx0]) (reference:
        # examples/python/keras/elementwise_mul_broadcast.py)
        self._input_names = [
            ff.graph.nodes[built[id(node)].ref.guid].name
            for node in self._inputs
            if id(node) in built
        ]
        return ff

    def get_layer(self, name=None, index=None):
        """reference: BaseModel.get_layer (keras/models/base_model.py) —
        by registered name (explicit or auto: dense, dense_1, conv2d,
        flat, ...) or by build order index."""
        if self.ffmodel is None:
            raise RuntimeError("call compile() first")
        if name is not None:
            if name not in self._layers_by_name:
                raise ValueError(
                    f"no layer named {name!r}; have "
                    f"{sorted(self._layers_by_name)}"
                )
            return self._layers_by_name[name]
        if index is not None:
            return self._layer_order[index]
        raise ValueError("pass name= or index=")

    def compile(self, optimizer=None, loss="sparse_categorical_crossentropy",
                metrics=("accuracy",), batch_size: Optional[int] = None):
        if isinstance(optimizer, str):
            optimizer = {"sgd": SGD(), "adam": Adam()}[optimizer.lower()]
        if isinstance(loss, Loss):  # reference keras.losses objects
            loss = loss.type
        metrics = [m.type if isinstance(m, Metric) else m for m in metrics]
        bs = batch_size or self.config.batch_size
        self.ffmodel = self._lower(bs)
        self.ffmodel.compile(
            optimizer=optimizer,
            loss_type=_LOSS[loss] if isinstance(loss, str) else loss,
            metrics=[
                _METRIC[m] if isinstance(m, str) else m for m in metrics
            ],
        )

    @staticmethod
    def _squeeze_labels(y):
        """keras sparse labels arrive as (n, 1) column vectors (the
        reference examples reshape them so); the engine's sparse-CE takes
        (n,)."""
        y = np.asarray(y)
        if (
            y.ndim >= 2
            and y.shape[-1] == 1
            and np.issubdtype(y.dtype, np.integer)
        ):
            return y.reshape(y.shape[:-1])
        return y

    def _name_inputs(self, x):
        """Zip a positional x list with the DECLARED input order (see
        _lower's _input_names note)."""
        names = getattr(self, "_input_names", None)
        if not names or isinstance(x, dict):
            return x
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        if len(xs) != len(names):
            return x  # let the engine's arity error speak
        return dict(zip(names, xs))

    def fit(self, x, y, epochs=1, batch_size: Optional[int] = None,
            callbacks=None, **kw):
        if self.ffmodel is None:
            raise RuntimeError("call compile() first")
        for cb in callbacks or []:
            # reference: base_model.py:374-377 — callbacks see the KERAS
            # model (engine reachable as .ffmodel, keras/callbacks.py:69)
            cb.set_model(self)
        return self.ffmodel.fit(
            self._name_inputs(x), self._squeeze_labels(y), epochs=epochs,
            batch_size=batch_size, callbacks=callbacks, **kw,
        )

    def evaluate(self, x, y, batch_size: Optional[int] = None,
                 callbacks=None):
        for cb in callbacks or []:
            cb.set_model(self)
        return self.ffmodel.evaluate(
            self._name_inputs(x), self._squeeze_labels(y),
            batch_size=batch_size, callbacks=callbacks
        )

    def __call__(self, *inputs):
        """Functional composition (reference: keras models are callable —
        func_mnist_mlp_concat.py builds submodels and applies them to a
        shared input). Re-applies this model's layer graph to the given
        input nodes. Layers are re-applied as specs: each call lowers to
        fresh FFModel ops (no cross-call weight sharing)."""
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = tuple(inputs[0])  # keras list convention: model([a, b])
        if len(inputs) != len(self._inputs):
            raise ValueError(
                f"model takes {len(self._inputs)} inputs, got {len(inputs)}"
            )
        mapping = {id(i): arg for i, arg in zip(self._inputs, inputs)}

        def clone(node: Node):
            if id(node) in mapping:
                return mapping[id(node)]
            if node.layer is None:
                raise ValueError("model called with an unbound Input")
            new = Node(node.layer, [clone(i) for i in node.inputs])
            mapping[id(node)] = new
            return new

        outs = [clone(o) for o in self._outputs]
        return outs[0] if len(outs) == 1 else outs

    def summary(self):
        if self.ffmodel is None:
            # reference scripts print summaries BEFORE compile too
            # (seq_mnist_cnn_nested.py) — describe the declared structure
            layers = getattr(self, "layers", None) or self._outputs
            return (
                f"<{type(self).__name__}: {len(layers)} declared "
                "layers (uncompiled)>"
            )
        return repr(self.ffmodel.graph)


class Sequential(Model):
    def __init__(self, layers=None, config: Optional[FFConfig] = None):
        super().__init__(config=config)
        self.layers: List = list(layers or [])

    def add(self, layer):
        self.layers.append(layer)

    @staticmethod
    def _declared_input_shape(layer):
        """input_shape declared by a leading layer — directly (keras
        idiom: Dense(512, input_shape=(784,)),
        examples/python/keras/seq_mnist_mlp.py) or through a nested
        Sequential's own first layer (seq_mnist_cnn_nested.py)."""
        shape = getattr(layer, "input_shape", None)
        if shape:
            return shape
        if isinstance(layer, Sequential) and layer.layers:
            return Sequential._declared_input_shape(layer.layers[0])
        return None

    def _chain(self, node):
        """Wire self.layers after `node`; nested Models/Sequentials are
        applied as callables (reference: models are layers,
        seq_mnist_cnn_nested.py builds Sequential([model1, model2]))."""
        for layer in self.layers:
            if isinstance(layer, Node):
                continue  # a leading Input; the chain is already rooted
            node = layer(node) if isinstance(layer, Model) else Node(
                layer, [node]
            )
        return node

    def __call__(self, *inputs):
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = tuple(inputs[0])
        if len(inputs) != 1:
            raise ValueError("Sequential models take exactly one input")
        return self._chain(inputs[0])

    def compile(self, *args, **kw):
        if not self.layers:
            raise ValueError("Sequential model has no layers")
        first = self.layers[0]
        if isinstance(first, Node):
            inp = first
        else:
            shape = self._declared_input_shape(first)
            if not shape:
                raise ValueError(
                    "first layer needs input_shape=(...) or an explicit "
                    "keras_api.Input(shape=...)"
                )
            inp = Input(shape=tuple(shape))
        self._inputs = [inp]
        self._outputs = [self._chain(inp)]
        super().compile(*args, **kw)
