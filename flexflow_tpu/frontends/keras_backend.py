"""Keras backend functions over the functional API
(reference: python/flexflow/keras/backend/ — batch_dot/sin/cos/exp/pow/
sum built on the BatchMatmul/Sin/Cos/Exp/Pow/ReduceSum internal layers,
backend_functions.py:25-45, internal.py:23-233).

Same surface here: tiny Layer subclasses lowering to the FFModel builder
ops, plus the functional wrappers and `backend()` reporting the backend
name.
"""

from __future__ import annotations

from flexflow_tpu.frontends.keras_api import Layer

_BACKEND = "flexflow_tpu"


def backend() -> str:
    return _BACKEND


class BatchMatmul(Layer):
    """[b, n, k] x [b, k, m] -> [b, n, m] (internal.py:23 restricts to
    3-d tensors; the builder op checks contraction sizes)."""

    def build(self, ff, ts):
        if len(ts) != 2:
            raise ValueError(f"BatchMatmul expects 2 tensors, got {len(ts)}")
        return ff.batch_matmul(ts[0], ts[1], name=self.name)


class Sin(Layer):
    def build(self, ff, ts):
        return ff.sin(ts[0], name=self.name)


class Cos(Layer):
    def build(self, ff, ts):
        return ff.cos(ts[0], name=self.name)


class Exp(Layer):
    def build(self, ff, ts):
        return ff.exp(ts[0], name=self.name)


class Pow(Layer):
    def __init__(self, a, name=None):
        super().__init__(name)
        self.a = float(a)

    def build(self, ff, ts):
        return ff.pow(ts[0], self.a, name=self.name)


class ReduceSum(Layer):
    """axis None sums EVERY dim, batch included (internal.py:205-217
    sets axis = range(0, ndims)); int or list axes pass through."""

    def __init__(self, axis=None, keepdims=False, name=None):
        super().__init__(name)
        if isinstance(axis, int):
            axis = [axis]
        self.axis = None if axis is None else list(axis)
        self.keepdims = bool(keepdims)

    def build(self, ff, ts):
        axes = self.axis
        if axes is None:
            axes = list(range(len(ts[0].dims)))
        return ff.reduce_sum(ts[0], axes, keepdims=self.keepdims,
                             name=self.name)


def batch_dot(x, y):
    return BatchMatmul()([x, y])


def sin(x):
    return Sin()(x)


def cos(x):
    return Cos()(x)


def exp(x):
    return Exp()(x)


def pow(x, a):  # noqa: A001 — keras spells it `pow` (backend/__init__.py)
    return Pow(a)(x)


def sum(x, axis=None, keepdims=False):  # noqa: A001 — keras spelling
    return ReduceSum(axis, keepdims)(x)
