"""`keras_exp` — the experimental Keras-frontend variant.

The reference ships two Keras frontends: `flexflow.keras` (4.2k LoC) and
`flexflow.keras_exp` (547 LoC), an experimental functional-API variant
that traces `Model(inputs, outputs)` graphs eagerly instead of through
the Sequential layer list (reference: python/flexflow/keras_exp/models/
model.py). In this rebuild one implementation already serves both
construction styles — `frontends.keras_api.Model` accepts functional
(inputs/outputs Node graphs) AND Sequential construction — so this
module is the keras_exp-compatible import surface over the same engine
rather than a second tracer: the reference's two frontends exist because
its Sequential path predated functional tracing, a split a fresh design
does not need to reproduce.

    from flexflow_tpu.frontends import keras_exp as keras
    x = keras.Input(shape=(32,))
    t = keras.Dense(64, activation="relu")(x)
    out = keras.Dense(4)(t)
    model = keras.Model(x, out)
    model.compile(optimizer="sgd")
    model.fit(X, y, epochs=2)
"""

from flexflow_tpu.frontends.keras_api import (  # noqa: F401
    SGD,
    Activation,
    Adam,
    Add,
    Callback,
    EpochVerifyMetrics,
    LearningRateScheduler,
    VerifyMetrics,
    callbacks,
    AveragePooling2D,
    BatchNormalization,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    Input,
    Layer,
    LayerNormalization,
    MaxPooling2D,
    Model,
    Multiply,
    Sequential,
)

__all__ = [
    "SGD",
    "Activation",
    "Adam",
    "Add",
    "AveragePooling2D",
    "BatchNormalization",
    "Concatenate",
    "Conv2D",
    "Dense",
    "Dropout",
    "Embedding",
    "Flatten",
    "Input",
    "Layer",
    "LayerNormalization",
    "MaxPooling2D",
    "Model",
    "Multiply",
    "Sequential",
]
