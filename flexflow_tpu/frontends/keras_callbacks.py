"""Keras training callbacks.

Mirrors the reference's callback protocol (reference:
python/flexflow/keras/callbacks.py:1-90 — Callback base with
epoch/batch/train hooks, LearningRateScheduler driving
optimizer.set_learning_rate per epoch, VerifyMetrics asserting final
accuracy, EpochVerifyMetrics early-stopping when an accuracy target is
reached) and the invocation points of BaseModel._train (reference:
python/flexflow/keras/models/base_model.py:374-430 — set_model /
on_train_begin / per-epoch / per-batch hooks, with a True return from
on_epoch_end stopping training early).

Callbacks work both through the keras frontend (`model` is the keras
Model; the underlying engine is `model.ffmodel`) and directly on
`FFModel.fit(callbacks=...)` (`model` IS the FFModel).
"""

from __future__ import annotations

import numbers


def _engine(model):
    """The FFModel under a keras Model (or the FFModel itself)."""
    return getattr(model, "ffmodel", None) or model


class Callback:
    """Hook protocol (reference: keras/callbacks.py:21-46)."""

    def __init__(self):
        self.validation_data = None
        self.model = None
        self.params = None

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, batch, logs=None):
        pass

    def on_batch_end(self, batch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass


class LearningRateScheduler(Callback):
    """Per-epoch LR schedule (reference: keras/callbacks.py:48-62 — the
    schedule maps epoch -> float; non-float outputs are rejected)."""

    def __init__(self, schedule):
        super().__init__()
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        lr = self.schedule(epoch)
        if isinstance(lr, bool) or not isinstance(lr, numbers.Real):
            raise ValueError(
                'The output of the "schedule" function should be float.'
            )
        eng = _engine(self.model)
        eng.set_learning_rate(float(lr))
        print("set learning rate ", float(lr))


class VerifyMetrics(Callback):
    """Assert the final training accuracy reaches a target (reference:
    keras/callbacks.py:64-73). `accuracy` is a percentage or an enum with
    a `.value` percentage (the reference's ModelAccuracy enums)."""

    def __init__(self, accuracy):
        super().__init__()
        self.accuracy = getattr(accuracy, "value", accuracy)

    def on_train_end(self, logs=None):
        perf = _engine(self.model).get_perf_metrics()
        accuracy = perf.get_accuracy()
        assert accuracy >= self.accuracy, (
            f"Accuracy is wrong: {accuracy:.2f} < {self.accuracy}"
        )


class EpochVerifyMetrics(Callback):
    """Early-stop once an accuracy target is reached (reference:
    keras/callbacks.py:75-90 — on_epoch_end returning True stops the
    training loop, base_model.py:423-428)."""

    def __init__(self, accuracy, early_stop=True):
        super().__init__()
        self.accuracy = getattr(accuracy, "value", accuracy)
        self.early_stop = early_stop

    def on_epoch_end(self, epoch, logs=None):
        if not self.early_stop:
            return False
        perf = _engine(self.model).get_perf_metrics()
        return perf.get_accuracy() > self.accuracy
