"""PyTorch frontend: torch.fx symbolic trace -> FFModel builder calls.

Rebuild of the reference's torch frontend (reference:
python/flexflow/torch/model.py — `torch_to_flexflow(model, filename)` writes
a serialized op list; `PyTorchModel(filename).apply(ffmodel, inputs)` replays
it with ~60 per-node decode classes). Same two-step shape here, with a JSON
op-list instead of the reference's ad-hoc string format:

    from flexflow_tpu.frontends.torch_fx import torch_to_flexflow, PyTorchModel
    torch_to_flexflow(my_module, "model.ff.json", example_shapes)
    ...
    t = PyTorchModel("model.ff.json").apply(ffmodel, [input_tensor])

or in one step: `PyTorchModel(my_module).apply(ffmodel, [input_tensor])`.

Layout note (TPU-native divergence): convolutions run NHWC here (the
reference and torch are NCHW). The importer keeps the *torch* NCHW calling
convention at the boundary — image inputs are created as [N, C, H, W] and a
transpose to NHWC is inserted before the first conv-family op; `flatten`
transposes back so downstream Linear weights line up with torch's.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from flexflow_tpu.core.types import ActiMode, AggrMode, DataType, OperatorType


# ---------------------------------------------------------------------------
# Step 1: trace + serialize
# ---------------------------------------------------------------------------


def trace_module(module, concrete_args=None) -> List[dict]:
    """fx-trace a torch.nn.Module into the portable op list."""
    import torch
    import torch.fx as fx
    import torch.nn as nn

    gm = fx.symbolic_trace(module, concrete_args=concrete_args)
    ops: List[dict] = []

    def emit(name, op, inputs, **params):
        ops.append(
            {"name": name, "op": op, "inputs": list(inputs), "params": params}
        )

    modules = dict(gm.named_modules())
    for node in gm.graph.nodes:
        ins = [
            a.name
            for a in node.args
            if isinstance(a, fx.Node)
        ]
        if node.op == "placeholder":
            emit(node.name, "input", [])
        elif node.op == "output":
            arg = node.args[0]
            args = list(arg) if isinstance(arg, (tuple, list)) else [arg]
            emit(
                node.name,
                "output",
                [a.name for a in args if isinstance(a, fx.Node)],
            )
        elif node.op == "call_module":
            m = modules[node.target]
            if isinstance(m, nn.Linear):
                emit(
                    node.name,
                    "linear",
                    ins,
                    out_features=m.out_features,
                    use_bias=m.bias is not None,
                    module=node.target,
                )
            elif isinstance(m, nn.Conv2d):
                emit(
                    node.name,
                    "conv2d",
                    ins,
                    out_channels=m.out_channels,
                    kernel=list(m.kernel_size),
                    stride=list(m.stride),
                    padding=list(m.padding)
                    if isinstance(m.padding, (tuple, list))
                    else [m.padding, m.padding],
                    groups=m.groups,
                    use_bias=m.bias is not None,
                    module=node.target,
                )
            elif isinstance(m, (nn.MaxPool2d, nn.AvgPool2d)):
                k = m.kernel_size
                s = m.stride if m.stride is not None else k
                p = m.padding
                to2 = lambda v: list(v) if isinstance(v, (tuple, list)) else [v, v]
                emit(
                    node.name,
                    "pool2d",
                    ins,
                    kernel=to2(k),
                    stride=to2(s),
                    padding=to2(p),
                    pool_type="max" if isinstance(m, nn.MaxPool2d) else "avg",
                    count_include_pad=getattr(m, "count_include_pad", True),
                )
            elif isinstance(m, nn.AdaptiveAvgPool2d):
                emit(node.name, "adaptive_avg_pool2d", ins,
                     output_size=list(m.output_size)
                     if isinstance(m.output_size, (tuple, list))
                     else [m.output_size, m.output_size])
            elif isinstance(m, nn.BatchNorm2d):
                emit(node.name, "batch_norm", ins, module=node.target)
            elif isinstance(m, nn.LayerNorm):
                emit(
                    node.name,
                    "layer_norm",
                    ins,
                    normalized_shape=list(m.normalized_shape),
                    eps=m.eps,
                    affine=m.elementwise_affine,
                    module=node.target,
                )
            elif isinstance(m, nn.Embedding):
                emit(
                    node.name,
                    "embedding",
                    ins,
                    num_embeddings=m.num_embeddings,
                    embedding_dim=m.embedding_dim,
                    module=node.target,
                )
            elif isinstance(m, nn.MultiheadAttention):
                emit(
                    node.name,
                    "multihead_attention",
                    ins,
                    embed_dim=m.embed_dim,
                    num_heads=m.num_heads,
                    dropout=m.dropout,
                    # torch default is batch_first=False ([s, b, e]); the
                    # replay inserts the transposes to our [b, s, e]
                    batch_first=bool(m.batch_first),
                    module=node.target,
                )
            elif isinstance(m, nn.Dropout):
                emit(node.name, "dropout", ins, rate=m.p)
            elif isinstance(m, (nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh)):
                emit(node.name, "activation", ins,
                     fn=type(m).__name__.lower())
            elif isinstance(m, nn.Softmax):
                emit(node.name, "softmax", ins, dim=m.dim)
            elif isinstance(m, nn.Flatten):
                emit(node.name, "flatten", ins)
            elif isinstance(m, nn.Identity):
                emit(node.name, "identity", ins)
            else:
                raise NotImplementedError(
                    f"torch frontend: unsupported module {type(m).__name__}"
                )
        elif node.op in ("call_function", "call_method"):
            t = node.target if node.op == "call_function" else str(node.target)
            fname = getattr(t, "__name__", str(t)).lstrip("_")
            if fname in ("add", "sub", "mul", "truediv", "div"):
                scalars = [a for a in node.args if not isinstance(a, fx.Node)]
                if scalars:
                    # reflected forms (1.0 - x, 2 / x) have the scalar as
                    # args[0]; sub/div are not commutative, record it
                    reflected = not isinstance(node.args[0], fx.Node)
                    emit(
                        node.name,
                        f"scalar_{fname}",
                        ins,
                        scalar=float(scalars[0]),
                        reflected=reflected,
                    )
                else:
                    emit(node.name, fname, ins)
            elif fname in ("relu", "gelu", "sigmoid", "tanh", "exp", "sin",
                           "cos", "rsqrt"):
                emit(node.name, "activation", ins, fn=fname)
            elif fname == "matmul":
                emit(node.name, "batch_matmul", ins)
            elif fname == "softmax":
                dim = node.kwargs.get("dim", -1)
                if len(node.args) > 1 and not isinstance(node.args[1], fx.Node):
                    dim = node.args[1]
                emit(node.name, "softmax", ins, dim=dim)
            elif fname == "cat":
                seq = node.args[0]
                ins = [a.name for a in seq if isinstance(a, fx.Node)]
                dim = node.kwargs.get("dim", node.args[1] if len(node.args) > 1 else 0)
                emit(node.name, "concat", ins, dim=dim)
            elif fname in ("flatten", "reshape", "view"):
                if fname == "flatten":
                    emit(node.name, "flatten", ins)
                else:
                    shape = [a for a in node.args[1:] if not isinstance(a, fx.Node)]
                    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
                        shape = list(shape[0])
                    emit(node.name, "reshape", ins, shape=[int(s) for s in shape])
            elif fname in ("permute", "transpose"):
                dims = [a for a in node.args[1:] if not isinstance(a, fx.Node)]
                emit(node.name, fname, ins, dims=[int(d) for d in dims])
            elif fname == "mean":
                dims = node.kwargs.get("dim", node.args[1] if len(node.args) > 1 else None)
                keep = node.kwargs.get("keepdim", False)
                if isinstance(dims, int):
                    dims = [dims]
                # dims=None marks torch's global mean (all axes)
                emit(
                    node.name,
                    "mean",
                    ins,
                    dims=None if dims is None else list(dims),
                    keepdims=bool(keep),
                )
            elif fname == "getitem":
                emit(node.name, "getitem", ins, index=int(node.args[1]))
            elif fname in ("dropout",):
                emit(node.name, "dropout", ins, rate=node.kwargs.get("p", 0.5))
            elif fname in ("contiguous", "clone", "detach", "to", "float"):
                emit(node.name, "identity", ins)
            elif fname == "split":
                size = node.args[1]
                dim = node.kwargs.get("dim", node.args[2] if len(node.args) > 2 else 0)
                emit(node.name, "split", ins, sizes=size, dim=dim)
            elif fname == "pow":
                emit(node.name, "pow", ins, exponent=float(node.args[1]))
            else:
                raise NotImplementedError(
                    f"torch frontend: unsupported function/method {fname!r}"
                )
        elif node.op == "get_attr":
            raise NotImplementedError(
                "torch frontend: free get_attr tensors not supported; wrap "
                "them in modules"
            )
    return ops


def torch_to_flexflow(module, filename: str, concrete_args=None):
    """Serialize a torch module's traced op list (the reference's
    `torch_to_flexflow` writing the .ff file, model.py:2408)."""
    ops = trace_module(module, concrete_args)
    with open(filename, "w") as f:
        json.dump({"format": "flexflow_tpu.torch_fx.v1", "ops": ops}, f, indent=1)
    return ops


# ---------------------------------------------------------------------------
# Step 2: replay into an FFModel
# ---------------------------------------------------------------------------


class _UnsupportedAux:
    """Placeholder for an auxiliary torch output we cannot express; raises
    only when consumed (any attribute access) so dead unpackings pass."""

    def __init__(self, message: str):
        object.__setattr__(self, "_message", message)

    def __getattr__(self, name):
        raise NotImplementedError(object.__getattribute__(self, "_message"))


class PyTorchModel:
    """Replays a traced op list into FFModel builder calls
    (reference: PyTorchModel.apply, flexflow/torch/model.py)."""

    def __init__(self, src, concrete_args=None):
        if isinstance(src, str):
            with open(src) as f:
                doc = json.load(f)
            self.ops = doc["ops"]
            self.module = None
        elif isinstance(src, (list, tuple)):
            self.ops = list(src)
            self.module = None
        else:
            self.module = src
            self.ops = trace_module(src, concrete_args)
        # op name -> (guid, kind) for weight transfer
        self.node_map: Dict[str, object] = {}

    def torch_to_file(self, filename: str):
        """Reference-name spelling for the .ff export step
        (reference: PyTorchModel.torch_to_file, flexflow/torch/model.py —
        examples/python/pytorch/mnist_mlp_torch.py calls exactly this)."""
        if self.module is None:
            with open(filename, "w") as f:
                json.dump(
                    {"format": "flexflow_tpu.torch_fx.v1", "ops": self.ops},
                    f,
                    indent=1,
                )
            return self.ops
        return torch_to_flexflow(self.module, filename)

    @staticmethod
    def file_to_ff(filename: str, ffmodel, input_tensors: Sequence):
        """Reference-name spelling for the replay step (reference:
        PyTorchModel.file_to_ff — examples/python/pytorch/mnist_mlp.py)."""
        return PyTorchModel(filename).apply(ffmodel, input_tensors)

    def apply(self, ffmodel, input_tensors: Sequence):
        """input_tensors: FFModel Tensors matching placeholder order (image
        inputs in torch NCHW layout)."""
        # Guids are per-FFModel; a fresh apply() must not keep the previous
        # graph's entries (copy_weights would target stale guids).
        self.node_map = {}
        env: Dict[str, object] = {}
        is_channels_first: Dict[str, bool] = {}
        it = iter(input_tensors)
        outputs = []

        def to_nhwc(name):
            t = env[name]
            if is_channels_first.get(name, False):
                t = ffmodel.transpose(t, [0, 2, 3, 1], name=f"{name}_nhwc")
            return t

        def inherit_layout(name, ins):
            """Layout-preserving ops (elementwise, concat, …) carry their
            inputs' channels-first flag forward so flatten can decide."""
            if name not in is_channels_first:
                is_channels_first[name] = any(
                    is_channels_first.get(i, False) for i in ins
                )

        for spec in self.ops:
            op, name, ins, p = (
                spec["op"],
                spec["name"],
                spec["inputs"],
                spec["params"],
            )
            if op == "input":
                t = next(it)
                env[name] = t
                # 4-D inputs follow torch NCHW convention
                is_channels_first[name] = len(t.dims) == 4
                continue
            if op == "output":
                outputs.extend(env[i] for i in ins)
                continue

            if op == "linear":
                env[name] = ffmodel.dense(
                    env[ins[0]],
                    p["out_features"],
                    use_bias=p.get("use_bias", True),
                    name=name,
                )
            elif op == "conv2d":
                x = to_nhwc(ins[0])
                env[name] = ffmodel.conv2d(
                    x,
                    p["out_channels"],
                    p["kernel"][0],
                    p["kernel"][1],
                    p["stride"][0],
                    p["stride"][1],
                    p["padding"][0],
                    p["padding"][1],
                    groups=p.get("groups", 1),
                    use_bias=p.get("use_bias", True),
                    name=name,
                )
                is_channels_first[name] = False
            elif op == "pool2d":
                x = to_nhwc(ins[0])
                env[name] = ffmodel.pool2d(
                    x,
                    p["kernel"][0],
                    p["kernel"][1],
                    p["stride"][0],
                    p["stride"][1],
                    p["padding"][0],
                    p["padding"][1],
                    pool_type=p.get("pool_type", "max"),
                    count_include_pad=p.get("count_include_pad", True),
                    name=name,
                )
                is_channels_first[name] = False
            elif op == "adaptive_avg_pool2d":
                x = to_nhwc(ins[0])
                oh, ow = p["output_size"]
                h, w = x.dims[1], x.dims[2]
                if h % oh or w % ow:
                    raise NotImplementedError(
                        "adaptive_avg_pool2d: only divisible output sizes"
                    )
                env[name] = ffmodel.pool2d(
                    x, h // oh, w // ow, h // oh, w // ow, 0, 0,
                    pool_type="avg", name=name,
                )
                is_channels_first[name] = False
            elif op == "batch_norm":
                x = to_nhwc(ins[0])
                env[name] = ffmodel.batch_norm(x, relu=False, name=name)
                is_channels_first[name] = False
            elif op == "layer_norm":
                env[name] = ffmodel.layer_norm(
                    env[ins[0]],
                    axes=list(
                        range(-len(p["normalized_shape"]), 0)
                    ),
                    elementwise_affine=p.get("affine", True),
                    eps=p.get("eps", 1e-5),
                    name=name,
                )
            elif op == "embedding":
                env[name] = ffmodel.embedding(
                    env[ins[0]],
                    p["num_embeddings"],
                    p["embedding_dim"],
                    aggr=AggrMode.NONE,
                    name=name,
                )
            elif op == "multihead_attention":
                q, k, v = (env[i] for i in (ins + ins[:1] * 3)[:3])
                # batch_first=False (torch's default) means [s, b, e] inputs
                if not p.get("batch_first", False):
                    q, k, v = (
                        ffmodel.transpose(t, [1, 0, 2], name=f"{name}_bf{i}")
                        for i, t in enumerate((q, k, v))
                    )
                out = ffmodel.multihead_attention(
                    q, k, v, p["embed_dim"], p["num_heads"],
                    dropout=p.get("dropout", 0.0), name=name,
                )
                # weight transfer targets the attention node, not the
                # layout transpose appended below
                self.node_map[name] = out.ref.guid
                if not p.get("batch_first", False):
                    out = ffmodel.transpose(out, [1, 0, 2], name=f"{name}_sf")
                env[name] = out
            elif op == "dropout":
                env[name] = ffmodel.dropout(env[ins[0]], p.get("rate", 0.5), name=name)
            elif op == "activation":
                fn = p["fn"]
                env[name] = {
                    "relu": ffmodel.relu,
                    "gelu": ffmodel.gelu,
                    "sigmoid": ffmodel.sigmoid,
                    "tanh": ffmodel.tanh,
                    "exp": ffmodel.exp,
                    "sin": ffmodel.sin,
                    "cos": ffmodel.cos,
                    "rsqrt": ffmodel.rsqrt,
                }[fn](env[ins[0]], name=name)
                is_channels_first[name] = is_channels_first.get(ins[0], False)
            elif op == "softmax":
                dim = p.get("dim", -1)
                if dim is None:
                    # torch nn.Softmax(dim=None) legacy pick
                    # (torch.nn.functional._get_softmax_dim): 0 for
                    # 0/1/3-d inputs, else 1
                    ndim = len(env[ins[0]].shape.logical_sizes)
                    dim = 0 if ndim in (0, 1, 3) else 1
                env[name] = ffmodel.softmax(env[ins[0]], dim=dim, name=name)
            elif op == "flatten":
                x = env[ins[0]]
                # restore torch's NCHW element order before collapsing:
                # conv-path tensors are NHWC (flag False on a 4-D tensor)
                if len(x.dims) == 4 and not is_channels_first.get(ins[0], False):
                    x = ffmodel.transpose(x, [0, 3, 1, 2], name=f"{name}_nchw")
                env[name] = ffmodel.flat(x, name=name)
            elif op == "identity":
                env[name] = env[ins[0]]
                is_channels_first[name] = is_channels_first.get(ins[0], False)
            elif op in ("add", "sub", "mul", "truediv", "div"):
                fn = {
                    "add": ffmodel.add,
                    "sub": ffmodel.subtract,
                    "mul": ffmodel.multiply,
                    "truediv": ffmodel.divide,
                    "div": ffmodel.divide,
                }[op]
                env[name] = fn(env[ins[0]], env[ins[1]], name=name)
            elif op.startswith("scalar_"):
                x = env[ins[0]]
                s = p["scalar"]
                if p.get("reflected", False) and op in (
                    "scalar_sub",
                    "scalar_truediv",
                    "scalar_div",
                ):
                    if op == "scalar_sub":
                        # s - x = (-x) + s
                        env[name] = ffmodel.scalar_add(
                            ffmodel.scalar_multiply(x, -1.0, name=f"{name}_neg"),
                            s,
                            name=name,
                        )
                    else:
                        # s / x = s * x^-1
                        env[name] = ffmodel.scalar_multiply(
                            ffmodel.pow(x, -1.0, name=f"{name}_inv"), s, name=name
                        )
                else:
                    fn = {
                        "scalar_add": ffmodel.scalar_add,
                        "scalar_sub": ffmodel.scalar_sub,
                        "scalar_mul": ffmodel.scalar_multiply,
                        "scalar_truediv": ffmodel.scalar_true_divide,
                        "scalar_div": ffmodel.scalar_true_divide,
                    }[op]
                    env[name] = fn(x, s, name=name)
            elif op == "batch_matmul":
                env[name] = ffmodel.batch_matmul(env[ins[0]], env[ins[1]], name=name)
            elif op == "concat":
                env[name] = ffmodel.concat([env[i] for i in ins], p["dim"], name=name)
            elif op == "reshape":
                shape = p["shape"]
                x = env[ins[0]]
                if any(s == -1 for s in shape):
                    known = 1
                    for s in shape:
                        if s != -1:
                            known *= s
                    total = int(np.prod(x.dims))
                    shape = [total // known if s == -1 else s for s in shape]
                env[name] = ffmodel.reshape(x, shape, name=name)
            elif op in ("permute", "transpose"):
                dims = p["dims"]
                x = env[ins[0]]
                if op == "transpose":
                    perm = list(range(len(x.dims)))
                    a, b = dims
                    perm[a], perm[b] = perm[b], perm[a]
                else:
                    perm = dims
                env[name] = ffmodel.transpose(x, perm, name=name)
            elif op == "mean":
                x = env[ins[0]]
                dims = p["dims"]
                if dims is None or dims == []:
                    dims = list(range(len(x.dims)))  # torch global mean
                env[name] = ffmodel.mean(
                    x, dims, keepdims=p.get("keepdims", False), name=name
                )
            elif op == "pow":
                env[name] = ffmodel.pow(env[ins[0]], p["exponent"], name=name)
            elif op == "split":
                env[name] = ffmodel.split(
                    env[ins[0]], p["sizes"], p["dim"], name=name
                )
            elif op == "getitem":
                seq = env[ins[0]]
                if isinstance(seq, (list, tuple)):
                    env[name] = seq[p["index"]]
                elif p["index"] == 0:
                    # torch APIs returning (output, aux) tuples — e.g.
                    # nn.MultiheadAttention's (attn_output, weights) — map
                    # to a single FF tensor; index 0 is that tensor
                    env[name] = seq
                else:
                    # aux outputs (attention weights, …) are not exposed;
                    # `out, _ = mha(...)` traces a dead getitem(…, 1), so
                    # only raise if something actually consumes it
                    env[name] = _UnsupportedAux(
                        f"torch frontend: getitem index {p['index']} on a "
                        "single-output op (auxiliary outputs such as "
                        "attention weights are not exposed)"
                    )
            else:
                raise NotImplementedError(f"torch frontend replay: {op!r}")
            if (
                name not in self.node_map
                and not isinstance(env[name], _UnsupportedAux)
                and hasattr(env[name], "ref")
            ):
                self.node_map[name] = env[name].ref.guid
            inherit_layout(name, ins)

        return outputs if len(outputs) != 1 else outputs[0]

    # -- weight transfer -----------------------------------------------------

    def copy_weights(self, ffmodel, module=None):
        """Copy torch parameters into the compiled FFModel (reference:
        align/mt5_ff_utils.py-style state-dict import via set_tensor).
        Layout conversions: Linear [out,in]->[in,out]; Conv2d
        [out,in,kh,kw]->HWIO; Embedding as-is; MHA packed per projection."""
        import torch

        module = module or self.module
        if module is None:
            raise ValueError("copy_weights needs the live torch module")
        mods = dict(module.named_modules())
        for spec in self.ops:
            tgt = spec["params"].get("module")
            if tgt is None or spec["name"] not in self.node_map:
                continue
            guid = self.node_map[spec["name"]]
            m = mods[tgt]
            with torch.no_grad():
                if spec["op"] == "linear":
                    ffmodel.set_tensor(guid, 0, m.weight.T.numpy())
                    if m.bias is not None:
                        ffmodel.set_tensor(guid, 1, m.bias.numpy())
                elif spec["op"] == "conv2d":
                    w = m.weight.permute(2, 3, 1, 0).numpy()  # OIHW->HWIO
                    ffmodel.set_tensor(guid, 0, w)
                    if m.bias is not None:
                        ffmodel.set_tensor(guid, 1, m.bias.numpy())
                elif spec["op"] == "embedding":
                    ffmodel.set_tensor(guid, 0, m.weight.numpy())
                elif spec["op"] == "layer_norm" and m.elementwise_affine:
                    ffmodel.set_tensor(guid, 0, m.weight.numpy())
                    ffmodel.set_tensor(guid, 1, m.bias.numpy())
                elif spec["op"] == "batch_norm":
                    ffmodel.set_tensor(guid, 0, m.weight.numpy())
                    ffmodel.set_tensor(guid, 1, m.bias.numpy())
                elif spec["op"] == "multihead_attention":
                    e = m.embed_dim
                    h = m.num_heads
                    hd = e // h
                    wqkv = m.in_proj_weight.numpy()  # [3e, e]
                    for i in range(3):
                        w = wqkv[i * e : (i + 1) * e].T.reshape(e, h, hd)
                        ffmodel.set_tensor(guid, i, w)
                    wo = m.out_proj.weight.numpy().T.reshape(h, hd, e)
                    ffmodel.set_tensor(guid, 3, wo)
                    if m.in_proj_bias is not None:
                        b = m.in_proj_bias.numpy()
                        for i in range(3):
                            ffmodel.set_tensor(
                                guid, 4 + i, b[i * e : (i + 1) * e].reshape(h, hd)
                            )
                        ffmodel.set_tensor(guid, 7, m.out_proj.bias.numpy())
