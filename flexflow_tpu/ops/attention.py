"""Multi-head attention.

Re-design of the reference's MultiHeadAttention op (reference:
src/ops/attention.cc:926, attention.cu:35-128 — a monolithic
cudnnMultiHeadAttnForward call). Here attention is expressed in jnp (XLA
fuses it well on TPU) with an optional Pallas flash-attention path
(flexflow_tpu.ops.pallas.flash_attention) selected for long sequences.

Head parallelism follows the reference's substitution semantics
(reference: substitution.cc:1758-1764 create_partition_attention_combine /
create_replicate_attention_reduce): a replica dim on the query input becomes
head partitioning of the QKV/output projections; the output-projection
contraction over partitioned heads yields partial sums, i.e. a replica dim
on the output that a downstream Reduction folds.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from flexflow_tpu.core.parallel_tensor import ParallelDim, ParallelTensorShape
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.ops.registry import register_op


def _infer_mha(input_shapes, params):
    q, k, v = input_shapes
    embed_dim = params["embed_dim"]
    num_heads = params["num_heads"]
    kdim = params.get("kdim", embed_dim)
    vdim = params.get("vdim", embed_dim)
    dtype = params.get("dtype", q.dtype)
    head_dim = embed_dim // num_heads

    rep = [d for d in q.dims if d.is_replica_dim]
    logical = [d for d in q.dims if not d.is_replica_dim]
    if len(rep) > 1:
        raise ValueError("mha: at most one replica dim")
    r_deg = rep[0].degree if rep else 1
    r_idx = rep[0].parallel_idx if rep else -1
    if num_heads % r_deg != 0:
        raise ValueError("mha: replica degree must divide num_heads")

    b, s, _ = logical
    out_dims = []
    if r_deg > 1:
        out_dims.append(ParallelDim(r_deg, r_deg, r_idx, True))
    out_dims.extend(
        [
            ParallelDim(b.size, b.degree, b.parallel_idx),
            ParallelDim(s.size, s.degree, s.parallel_idx),
            ParallelDim(embed_dim),
        ]
    )
    out = ParallelTensorShape(tuple(out_dims), dtype)

    head = ParallelDim(num_heads, r_deg, r_idx)
    wq = ParallelTensorShape((ParallelDim(embed_dim), head, ParallelDim(head_dim)), dtype)
    wk = ParallelTensorShape((ParallelDim(kdim), head, ParallelDim(head_dim)), dtype)
    wv = ParallelTensorShape((ParallelDim(vdim), head, ParallelDim(head_dim)), dtype)
    wo = ParallelTensorShape((head, ParallelDim(head_dim), ParallelDim(embed_dim)), dtype)
    weights = [wq, wk, wv, wo]
    if params.get("bias", True):
        # per-projection biases (reference: cudnnMultiHeadAttn with biases):
        # q/k/v biases live in head space (shard with the heads), output
        # bias is a plain embed_dim vector.
        bqkv = ParallelTensorShape((head, ParallelDim(head_dim)), dtype)
        bo = ParallelTensorShape((ParallelDim(embed_dim),), dtype)
        weights += [bqkv, bqkv, bqkv, bo]
    return (out,), tuple(weights)


def scaled_dot_product_attention(
    q, k, v, causal=False, bias=None, dropout_rate=0.0, dropout_rng=None
):
    """q,k,v: [b, s, h, d] — plain XLA attention; fp32 softmax accumulation.
    dropout is applied to the attention probabilities (reference: cudnn MHA
    attnDropout)."""
    d = q.shape[-1]
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    if bias is not None:
        logits = logits + bias
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), dtype=bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(dropout_rng, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _lower_mha(params):
    causal = params.get("causal", False)
    use_flash = params.get("use_flash", "auto")
    use_bias = params.get("bias", True)
    dropout = params.get("dropout", 0.0)

    def fn(ins, ws, ctx):
        xq, xk, xv = ins
        wq, wk, wv, wo = ws[:4]
        q = jnp.einsum("bse,ehd->bshd", xq, wq)
        k = jnp.einsum("bse,ehd->bshd", xk, wk)
        v = jnp.einsum("bse,ehd->bshd", xv, wv)
        if use_bias:
            bq, bk, bv = ws[4], ws[5], ws[6]
            q = q + bq
            k = k + bk
            v = v + bv
        seq = q.shape[1]
        dropping = dropout > 0.0 and ctx.train and ctx.rng is not None
        flash = (
            use_flash is True or (use_flash == "auto" and seq >= 1024)
        ) and not dropping  # the Pallas kernel has no prob-dropout path
        if flash:
            from flexflow_tpu.ops.pallas.flash_attention import flash_attention

            attn = flash_attention(q, k, v, causal=causal)
        else:
            attn = scaled_dot_product_attention(
                q,
                k,
                v,
                causal=causal,
                dropout_rate=dropout if dropping else 0.0,
                dropout_rng=ctx.rng if dropping else None,
            )
        y = jnp.einsum("bshd,hde->bse", attn, wo)
        if use_bias:
            y = y + ws[7]
        return [y]

    return fn


def _flops_mha(input_shapes, params):
    q = input_shapes[0]
    b, s, e = q.logical_sizes[-3:]
    proj = 4 * 2.0 * b * s * e * e
    attn = 2 * 2.0 * b * s * s * e
    return proj + attn


register_op(OperatorType.MULTIHEAD_ATTENTION, _infer_mha, _lower_mha, _flops_mha)
