"""Multi-head attention.

Re-design of the reference's MultiHeadAttention op (reference:
src/ops/attention.cc:926, attention.cu:35-128 — a monolithic
cudnnMultiHeadAttnForward call). Here attention is expressed in jnp (XLA
fuses it well on TPU) with an optional Pallas flash-attention path
(flexflow_tpu.ops.pallas.flash_attention) selected for long sequences.

Head parallelism follows the reference's substitution semantics
(reference: substitution.cc:1758-1764 create_partition_attention_combine /
create_replicate_attention_reduce): a replica dim on the query input becomes
head partitioning of the QKV/output projections; the output-projection
contraction over partitioned heads yields partial sums, i.e. a replica dim
on the output that a downstream Reduction folds.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from flexflow_tpu.core.parallel_tensor import ParallelDim, ParallelTensorShape
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.ops.registry import mm_operands, mm_out_dtype, register_op


def _infer_mha(input_shapes, params):
    q, k, v = input_shapes
    embed_dim = params["embed_dim"]
    num_heads = params["num_heads"]
    kdim = params.get("kdim", embed_dim)
    vdim = params.get("vdim", embed_dim)
    dtype = params.get("dtype", q.dtype)
    head_dim = embed_dim // num_heads

    rep = [d for d in q.dims if d.is_replica_dim]
    logical = [d for d in q.dims if not d.is_replica_dim]
    if len(rep) > 1:
        raise ValueError("mha: at most one replica dim")
    r_deg = rep[0].degree if rep else 1
    r_idx = rep[0].parallel_idx if rep else -1
    if num_heads % r_deg != 0:
        raise ValueError("mha: replica degree must divide num_heads")

    b, s, _ = logical
    out_dims = []
    if r_deg > 1:
        out_dims.append(ParallelDim(r_deg, r_deg, r_idx, True))
    out_dims.extend(
        [
            ParallelDim(b.size, b.degree, b.parallel_idx),
            ParallelDim(s.size, s.degree, s.parallel_idx),
            ParallelDim(embed_dim),
        ]
    )
    out = ParallelTensorShape(tuple(out_dims), dtype)

    head = ParallelDim(num_heads, r_deg, r_idx)
    wq = ParallelTensorShape((ParallelDim(embed_dim), head, ParallelDim(head_dim)), dtype)
    wk = ParallelTensorShape((ParallelDim(kdim), head, ParallelDim(head_dim)), dtype)
    wv = ParallelTensorShape((ParallelDim(vdim), head, ParallelDim(head_dim)), dtype)
    wo = ParallelTensorShape((head, ParallelDim(head_dim), ParallelDim(embed_dim)), dtype)
    weights = [wq, wk, wv, wo]
    if params.get("bias", True):
        # per-projection biases (reference: cudnnMultiHeadAttn with biases):
        # q/k/v biases live in head space (shard with the heads), output
        # bias is a plain embed_dim vector.
        bqkv = ParallelTensorShape((head, ParallelDim(head_dim)), dtype)
        bo = ParallelTensorShape((ParallelDim(embed_dim),), dtype)
        weights += [bqkv, bqkv, bqkv, bo]
    return (out,), tuple(weights)


def scaled_dot_product_attention(
    q, k, v, causal=False, bias=None, dropout_rate=0.0, dropout_rng=None
):
    """q,k,v: [b, s, h, d] — plain XLA attention; fp32 softmax accumulation.
    dropout is applied to the attention probabilities (reference: cudnn MHA
    attnDropout)."""
    d = q.shape[-1]
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    if bias is not None:
        logits = logits + bias
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), dtype=bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(dropout_rng, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def mha_project_qkv(ins, ws, ctx, use_bias=True):
    """Input projections of the MHA lowering: (xq, xk, xv) [b, s, e] ->
    (q, k, v) [b, s, h, d]. Split out of _lower_mha so the serving engine
    (flexflow_tpu.serving.engine) computes the exact same projections when
    it swaps the attention core for the KV-cache decode path — projection
    numerics must match training bit-for-bit or cache-equivalence breaks."""
    xq, xk, xv = ins
    wq, wk, wv = ws[0], ws[1], ws[2]
    xq, xk, xv, wq, wk, wv = mm_operands(ctx, xq, xk, xv, wq, wk, wv)
    # compute dtype: bf16 under mixed precision (softmax/accumulation
    # stays f32 inside the attention core), else the input dtype
    cdt = xq.dtype
    mm = dict(preferred_element_type=jnp.float32)
    q = jnp.einsum("bse,ehd->bshd", xq, wq, **mm).astype(cdt)
    k = jnp.einsum("bse,ehd->bshd", xk, wk, **mm).astype(cdt)
    v = jnp.einsum("bse,ehd->bshd", xv, wv, **mm).astype(cdt)
    if use_bias:
        bq, bk, bv = ws[4], ws[5], ws[6]
        q = q + bq.astype(cdt)
        k = k + bk.astype(cdt)
        v = v + bv.astype(cdt)
    return q, k, v


def mha_project_out(attn, ws, ctx, out_dtype, use_bias=True):
    """Output projection of the MHA lowering: attn [b, s, h, d] -> [b, s, e].
    Shared with the serving engine like mha_project_qkv."""
    attn_m, wo_m = mm_operands(ctx, attn, ws[3])
    y = jnp.einsum(
        "bshd,hde->bse", attn_m, wo_m, preferred_element_type=jnp.float32
    ).astype(mm_out_dtype(ctx, out_dtype))
    if use_bias:
        y = y + ws[7].astype(y.dtype)
    return y


def lora_delta_qkv(x, tbl, a_q, b_q, a_k, b_k, a_v, b_v):
    """Batched paged LoRA deltas for the Q/K/V projections (S-LoRA /
    Punica posture): per batch row, gather that row's adapter pages out
    of the pooled A/B factors and compute `(x @ A) @ B` summed over the
    row's pages — exact, because a rank-r LoRA product is a sum over
    rank slices and paging splits exactly along rank.

    x: [b, s, e]; tbl: [b, P] int32 page table (sentinel rows of the
    pool are all-zero, so an unused/base-model row contributes exactly
    0.0). a_*: [NP+1, e, pr]; b_*: [NP+1, pr, h, d]. Returns three
    [b, s, h, d] float32 deltas. Every contraction is per-batch-row
    independent — a mixed-adapter batch computes bit-identically to
    each row running alone, which the identity gates rely on."""
    mm = dict(preferred_element_type=jnp.float32)
    x32 = x.astype(jnp.float32)

    def delta(a_pool, b_pool):
        # u: [b, s, P, pr] rank activations per page, then contract the
        # (page, rank-slice) pair back out through B
        u = jnp.einsum("bse,bper->bspr", x32, a_pool[tbl], **mm)
        return jnp.einsum("bspr,bprhd->bshd", u, b_pool[tbl], **mm)

    return delta(a_q, b_q), delta(a_k, b_k), delta(a_v, b_v)


def lora_delta_out(attn, tbl, a_o, b_o):
    """Paged LoRA delta for the output projection — the post-kernel
    epilogue: the attention core (dense or Pallas) runs unmodified and
    the delta applies to its [b, s, h, d] output. a_o: [NP+1, h, d, pr];
    b_o: [NP+1, pr, e]. Returns a [b, s, e] float32 delta with the same
    per-row independence as lora_delta_qkv."""
    mm = dict(preferred_element_type=jnp.float32)
    u = jnp.einsum(
        "bshd,bphdr->bspr", attn.astype(jnp.float32), a_o[tbl], **mm
    )
    return jnp.einsum("bspr,bpre->bse", u, b_o[tbl], **mm)


def _decode_pallas_hook(q, k_cache, v_cache, lengths, kernel="auto"):
    """Seam for the hand-tiled TPU decode kernel (single-query flash
    against the cache — pallas/decode_kernel.py, the serving analog of
    flash_kernel.py for training). `kernel` is the ServeConfig
    .decode_kernel mode: "auto" takes the kernel on TPU when the
    geometry supports() it, "pallas" forces it (interpret mode off-TPU
    — the CI/test path), "dense" pins the jnp path. None routes
    decode_attention to the dense path below; on CPU "auto" stays dense
    (one query row, no [s, s] score tensor to fear)."""
    from flexflow_tpu.ops.pallas import decode_kernel as dk

    if not dk.use_kernel(kernel, q.shape[1], k_cache.shape[1], q.shape[-1]):
        return None
    return dk.flash_decode(q, k_cache, v_cache, lengths)


def decode_attention(q, k_cache, v_cache, lengths, kernel="auto"):
    """Serving decode regime: one-query attention against a preallocated
    KV cache. q: [b, 1, h, d]; k_cache/v_cache: [b, max_len, h, d];
    lengths: [b] int32, the cache position the current token was written
    at — positions > lengths[i] (unwritten slots or another request's
    stale rows) are masked out, so a fixed-shape cache serves variable
    sequence lengths without recompiles.

    fp32 score accumulation like scaled_dot_product_attention; the mask
    uses the same -1e30 fill so decode softmax numerics line up with the
    causal prefill path."""
    out = _decode_pallas_hook(q, k_cache, v_cache, lengths, kernel)
    if out is not None:
        return out
    d = q.shape[-1]
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    klen = k_cache.shape[1]
    mask = jnp.arange(klen)[None, None, None, :] <= lengths[
        :, None, None, None
    ]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache)


def tree_ancestor_matrix(parents):
    """Ancestor-or-self closure of a draft tree, threaded AS DATA.

    parents: [b, w] int32 — parents[i, j] is the verify-row index of row
    j's parent within the same w-row window, -1 for the root (row 0, the
    last emitted token; padding rows may use j - 1, which degenerates to
    the linear chain). Parent indices must be < their child's index
    (topological order) — both proposers emit trees that way.

    Returns [b, w, w] bool with anc[i, j, a] = True iff row a is an
    ancestor of row j or j itself. Pointer doubling over the parent
    table: ceil(log2(w)) rounds cover any chain inside a w-row window,
    and the whole computation is data-dependent — one compiled verify
    program serves EVERY tree shape of width w (the mask is an operand,
    not a trace-time constant), which is what lets a future fused
    draft+verify device round rewrite the tree between iterations
    without recompiling."""
    b, w = parents.shape
    anc = jnp.broadcast_to(jnp.eye(w, dtype=bool), (b, w, w))
    if w == 1:
        return anc
    ptr = parents.astype(jnp.int32)
    for _ in range(max(1, math.ceil(math.log2(w)))):
        valid = ptr >= 0
        safe = jnp.clip(ptr, 0, w - 1)
        idx = jnp.broadcast_to(safe[:, :, None], (b, w, w))
        rows = jnp.take_along_axis(anc, idx, axis=1)
        anc = anc | (rows & valid[:, :, None])
        ptr = jnp.where(valid, jnp.take_along_axis(ptr, safe, axis=1), ptr)
    return anc


def tree_allowed_mask(tree_parents, lengths, w, klen):
    """[b, w, klen] bool verify visibility for a draft TREE: query row j
    of sequence i sees cache position p iff p < lengths[i] (the
    committed prefix) or p falls inside the w-row verify window at the
    offset of one of row j's ancestors (or j itself). With chain parents
    (parents[j] = j - 1) this reproduces the staircase
    `p <= lengths[i] + j` exactly, so the tree mask is a strict
    generalization of the linear verify mask."""
    b = tree_parents.shape[0]
    anc = tree_ancestor_matrix(tree_parents)  # [b, w, w]
    kpos = jnp.arange(klen)[None, None, :]
    base = lengths[:, None, None]
    rel = kpos - base  # window offset of each key position
    window = (rel >= 0) & (rel < w)
    idx = jnp.broadcast_to(jnp.clip(rel, 0, w - 1), (b, w, klen))
    in_tree = jnp.take_along_axis(anc, idx, axis=2)
    return (kpos < base) | (window & in_tree)


def _verify_pallas_hook(q, k_cache, v_cache, lengths, kernel="auto",
                        allowed=None):
    """Seam for the hand-tiled TPU verify kernel (w-query flash against
    the cache — the speculative-decoding scoring pass; decode is its
    w == 1 case, so pallas/decode_kernel.py serves both with one body).
    None routes verify_attention to the dense jnp path; mode semantics
    as in _decode_pallas_hook. `allowed` is the precomputed [b, w, klen]
    tree visibility mask (tree-verify); the tree kernel variant carries
    it as a data operand, gated separately by supports_tree() with the
    same dense fallback contract."""
    from flexflow_tpu.ops.pallas import decode_kernel as dk

    if not dk.use_kernel(kernel, q.shape[1], k_cache.shape[1], q.shape[-1]):
        return None
    if allowed is not None:
        if not dk.supports_tree(q.shape[1]):
            return None
        return dk.flash_verify_tree(
            q, k_cache, v_cache, lengths, allowed.astype(jnp.float32)
        )
    return dk.flash_verify(q, k_cache, v_cache, lengths)


def verify_attention(q, k_cache, v_cache, lengths, kernel="auto",
                     tree_parents=None):
    """Speculative-decoding verify regime: w query positions per sequence
    (the last emitted token plus the drafted continuation) attend
    against the cache in ONE call. q: [b, w, h, d]; k_cache/v_cache:
    [b, max_len, h, d] — already containing the w fresh K/V rows written
    at positions lengths[i]..lengths[i]+w-1; lengths: [b] int32, the
    cache position the FIRST of the w tokens was written at.

    Query j of sequence i may see cache positions <= lengths[i] + j —
    the staircase mask that makes the verify step causal over the draft
    while still reading the whole prefix. decode_attention is exactly
    the w == 1 special case, and the same fp32 accumulation / -1e30
    fill keeps verify softmax numerics aligned with prefill and decode
    (greedy spec decode must be token-identical to plain decode).

    tree_parents [b, w] int32 (optional) switches the staircase to the
    SpecInfer token-tree mask: row j then sees the prefix plus only its
    ancestor rows' window positions (tree_allowed_mask), so several
    draft branches share one verify call. The tree shape rides as data —
    no recompile per tree — and chain parents reproduce the staircase
    bit-for-bit."""
    allowed_tree = None
    if tree_parents is not None:
        allowed_tree = tree_allowed_mask(
            tree_parents, lengths, q.shape[1], k_cache.shape[1]
        )
    out = _verify_pallas_hook(
        q, k_cache, v_cache, lengths, kernel, allowed=allowed_tree
    )
    if out is not None:
        return out
    d = q.shape[-1]
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    w = q.shape[1]
    klen = k_cache.shape[1]
    if allowed_tree is not None:
        allowed = allowed_tree
    else:
        # [b, w, klen]: key position <= lengths + query offset
        allowed = (
            jnp.arange(klen)[None, None, :]
            <= lengths[:, None, None] + jnp.arange(w)[None, :, None]
        )
    logits = jnp.where(allowed[:, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache)


def _dequant_pages(pool, tbl, scale, b, heads, d):
    """Gather pages from an int8 pool and dequantize with the per-page
    per-head fp32 scales: pool[tbl] is [b, np_seq, page_size, h, d] and
    scale[tbl] is [b, np_seq, h], broadcast over page positions and
    head_dim. Unwritten pages carry scale 0 and dequantize to exact
    zeros at positions the length mask drops anyway."""
    pages = pool[tbl].astype(jnp.float32)  # [b, np_seq, ps, h, d]
    s = scale[tbl][:, :, None, :, None]  # [b, np_seq, 1, h, 1]
    return (pages * s).reshape(b, -1, heads, d)


def _paged_verify_pallas_hook(q, k_pool, v_pool, block_tables, lengths,
                              kernel="auto", k_scale=None, v_scale=None,
                              allowed=None):
    """Seam for the hand-tiled TPU paged-verify kernel (w-query flash
    walking the block table page by page — the fourth member of the
    pallas/decode_kernel.py family, completing the seam symmetry:
    every cache-attention path now has one). None routes
    paged_verify_attention to the dense gather path; mode semantics as
    in _decode_pallas_hook. int8 pools (scales given) route to the
    quantized kernel variant, gated separately by supports().
    `allowed` is the precomputed [b, w, np_seq * page_size] tree
    visibility mask over LOGICAL positions (the mask tile's index map
    needs no block-table lookup), routing to the tree kernel variants
    under the supports_tree() width gate."""
    from flexflow_tpu.ops.pallas import decode_kernel as dk

    quant = k_scale is not None
    if not dk.use_kernel(
        kernel, q.shape[1], 0, q.shape[-1], page_size=k_pool.shape[1],
        kv_dtype="int8" if quant else "fp32",
    ):
        return None
    if allowed is not None:
        if not dk.supports_tree(q.shape[1]):
            return None
        mask = allowed.astype(jnp.float32)
        if quant:
            return dk.paged_flash_verify_tree_quant(
                q, k_pool, v_pool, k_scale, v_scale, block_tables,
                lengths, mask,
            )
        return dk.paged_flash_verify_tree(
            q, k_pool, v_pool, block_tables, lengths, mask
        )
    if quant:
        return dk.paged_flash_verify_quant(
            q, k_pool, v_pool, k_scale, v_scale, block_tables, lengths
        )
    return dk.paged_flash_verify(q, k_pool, v_pool, block_tables, lengths)


def paged_verify_attention(q, k_pool, v_pool, block_tables, lengths,
                           kernel="auto", k_scale=None, v_scale=None,
                           tree_parents=None):
    """Verify attention against the block-paged cache. The dense path
    gathers each sequence's pages into a contiguous view (same
    dense-gather strategy as paged_decode_attention, same sentinel
    clamping) and runs the exact verify_attention math, so paged verify
    is token-identical to the slot layout; the kernel path walks the
    table with no gather. With int8 pools, k_scale/v_scale
    [num_pages, heads] fp32 dequantize the gathered pages in place —
    the fused-dequant chunk loop of the ISSUE. tree_parents [b, w]
    int32 switches the staircase to the token-tree ancestor mask
    exactly as in verify_attention (the mask is computed over logical
    positions, so it threads unchanged through the page gather)."""
    allowed_tree = None
    if tree_parents is not None:
        allowed_tree = tree_allowed_mask(
            tree_parents, lengths, q.shape[1],
            block_tables.shape[1] * k_pool.shape[1],
        )
    out = _paged_verify_pallas_hook(
        q, k_pool, v_pool, block_tables, lengths, kernel,
        k_scale=k_scale, v_scale=v_scale, allowed=allowed_tree,
    )
    if out is not None:
        return out
    b = q.shape[0]
    num_pages, page_size, heads, d = k_pool.shape
    tbl = jnp.minimum(block_tables, num_pages - 1)
    if k_scale is not None:
        k = _dequant_pages(k_pool, tbl, k_scale, b, heads, d)
        v = _dequant_pages(v_pool, tbl, v_scale, b, heads, d)
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    else:
        k = k_pool[tbl].reshape(b, -1, heads, d)
        v = v_pool[tbl].reshape(b, -1, heads, d)
    return verify_attention(q, k, v, lengths, tree_parents=tree_parents)


def _paged_decode_pallas_hook(q, k_pool, v_pool, block_tables, lengths,
                              kernel="auto", k_scale=None, v_scale=None):
    """Seam for the hand-tiled TPU paged-decode kernel (single-query
    flash that walks the block table page by page instead of gathering
    the pages into a contiguous [b, max_len] view first — the
    PagedAttention kernel shape, pallas/decode_kernel.py with its
    supports() gate and calibration-table tile sizes). None routes
    paged_decode_attention to the dense gather path below; mode
    semantics as in _decode_pallas_hook. int8 pools (scales given)
    route to the quantized kernel variant, gated separately by
    supports()."""
    from flexflow_tpu.ops.pallas import decode_kernel as dk

    quant = k_scale is not None
    if not dk.use_kernel(
        kernel, q.shape[1], 0, q.shape[-1], page_size=k_pool.shape[1],
        kv_dtype="int8" if quant else "fp32",
    ):
        return None
    if quant:
        return dk.paged_flash_decode_quant(
            q, k_pool, v_pool, k_scale, v_scale, block_tables, lengths
        )
    return dk.paged_flash_decode(q, k_pool, v_pool, block_tables, lengths)


def paged_decode_attention(q, k_pool, v_pool, block_tables, lengths,
                           kernel="auto", k_scale=None, v_scale=None):
    """Serving decode against a block-paged KV cache. q: [b, 1, h, d];
    k_pool/v_pool: [num_pages, page_size, h, d]; block_tables:
    [b, max_pages_per_seq] int32 page ids (sentinel num_pages for
    unallocated entries); lengths: [b] int32, the cache position the
    current token was written at.

    The dense path gathers each sequence's pages into a contiguous
    [b, max_pages_per_seq * page_size, h, d] view and runs the exact
    decode_attention math, so paged serving is token-identical to the
    slot layout: sentinel/unwritten pages land at positions > lengths
    and the same -1e30 mask drops them before softmax. (The gather is a
    per-step temp the size of ONE dense cache view; the capacity win is
    in the persistent pool allocation, not this working set.) With int8
    pools, k_scale/v_scale [num_pages, heads] fp32 dequantize the
    gathered pages in place."""
    out = _paged_decode_pallas_hook(
        q, k_pool, v_pool, block_tables, lengths, kernel,
        k_scale=k_scale, v_scale=v_scale,
    )
    if out is not None:
        return out
    b = q.shape[0]
    num_pages, page_size, heads, d = k_pool.shape
    # sentinel entries are clamped to a real page; whatever that page
    # holds sits at masked positions, so the clamp is numerically inert
    tbl = jnp.minimum(block_tables, num_pages - 1)
    if k_scale is not None:
        k = _dequant_pages(k_pool, tbl, k_scale, b, heads, d)
        v = _dequant_pages(v_pool, tbl, v_scale, b, heads, d)
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    else:
        k = k_pool[tbl].reshape(b, -1, heads, d)
        v = v_pool[tbl].reshape(b, -1, heads, d)
    return decode_attention(q, k, v, lengths)


def _q_mesh_axes(ctx):
    """Mesh axis names (batch_ax, seq_ax, head_ax) of the q input's
    partitioned dims — head sharding comes from a replica dim on q (the
    head-parallel rewrite). None per slot when unsharded; None overall
    when no 3D parallel shape is available. THE one place the
    ParallelDim→axis-name classification lives."""
    if ctx is None or ctx.mesh is None or not ctx.in_shapes:
        return None
    qshape = ctx.in_shapes[0]
    logical = [d for d in qshape.dims if not d.is_replica_dim]
    rep = [d for d in qshape.dims if d.is_replica_dim]
    if len(logical) != 3:
        return None
    b, s, _ = logical
    names = ctx.axis_names
    batch_ax = names[b.parallel_idx] if b.degree > 1 else None
    seq_ax = names[s.parallel_idx] if s.degree > 1 else None
    head_ax = (
        names[rep[0].parallel_idx] if rep and rep[0].degree > 1 else None
    )
    return batch_ax, seq_ax, head_ax


def _seq_parallel_axes(ctx):
    """If the q AND k/v sequence dims are partitioned the same way, return the
    mesh axis names (seq_axis, batch_axis, head_axis) for the ring/Ulysses
    paths; else None (the dense path handles mixed layouts via GSPMD)."""
    axes = _q_mesh_axes(ctx)
    if axes is None:
        return None
    batch_ax, seq_ax, head_ax = axes
    if seq_ax is None:
        return None
    s = [d for d in ctx.in_shapes[0].dims if not d.is_replica_dim][1]
    # cross-attention guard: the ring rotates K/V blocks, so the key/value
    # sequence dims must be sharded on the same axis with the same degree
    for kv in ctx.in_shapes[1:3]:
        kv_logical = [d for d in kv.dims if not d.is_replica_dim]
        if len(kv_logical) != 3:
            return None
        s_kv = kv_logical[1]
        if s_kv.degree != s.degree or s_kv.parallel_idx != s.parallel_idx:
            return None
    return seq_ax, batch_ax, head_ax


# "auto" flash selection: dense attention on TPU beats the blockwise path
# until the [b, h, sq, sk] f32 score tensor threatens HBM (measured on v5e:
# dense fwd+bwd is ~4-5x faster than blockwise at seq 512-2048), so the
# switch is on PER-DEVICE score-tensor BYTES, not sequence length.
_FLASH_SCORE_BYTES = 2 << 30

# Below the flash threshold, dense attention is still kernel-bound by the
# f32 score block's working set: on v5e the fwd+bwd goes superlinear once
# [b, h, sq, sk] f32 exceeds ~VMEM (measured at the flagship shape
# seq512/h16: bs8 0.997 ms -> bs16 2.66 ms -> bs32 5.16 ms monolithic,
# vs 0.783 / 1.98 / 3.89 ms scanned over batch chunks whose score block
# is ~67 MB; scripts/probe_attn_batch.py, probe_attn_chunked2.py). So the
# dense path scans over batch chunks keeping the chunk's score block
# under this cap: the scan engages past _DENSE_MONO_SCORE_BYTES and
# tiles to chunks whose score block is <= _DENSE_CHUNK_SCORE_BYTES (the
# measured-best 67 MB tile admits; the measured-worse 134 MB tile
# rejects). The flagship bs8 config (134 MB scores) chunks too:
# interleaved same-process A/B with the fixed difference-of-mins
# estimator measures the full train step at 16.36 ms chunked vs
# 23.82 ms monolithic (scripts/ab_attn_chunk2.py `8 160,80 1,80`), and a
# chain-length ladder confirms 16.4 ms/step at every burst length
# (scripts/probe_chain_lengths.py — earlier "mono wins at bs8" readings
# came from a biased estimator and a measurement script that traced
# AFTER its monkeypatch was restored). bs8/16/32 now scale linearly:
# 16.4 / 32.1 / 66.7 ms.
_DENSE_MONO_SCORE_BYTES = 96 << 20
_DENSE_CHUNK_SCORE_BYTES = 80 << 20


def set_dense_caps(mono_mb: int, chunk_mb: int) -> None:
    """Install measured dense-attention working-set caps (the calibration
    table's "attn_caps" entry, written by an on-chip probe). The built-in
    defaults are the v5e-measured values; a table measured on another
    chip generation replaces them at compile
    (runtime/model.py compile())."""
    global _DENSE_MONO_SCORE_BYTES, _DENSE_CHUNK_SCORE_BYTES
    _DENSE_MONO_SCORE_BYTES = int(mono_mb) << 20
    _DENSE_CHUNK_SCORE_BYTES = int(chunk_mb) << 20


def _dense_batch_chunk(batch, heads, sq, sk) -> int:
    """Batch-chunk size for the dense path: `batch` (no scan) while the
    monolithic score block stays under the mono cap, else the largest
    divisor of `batch` whose per-chunk score block fits the chunk cap.

    When NO divisor fits (long-seq/small-batch: one sample's score block
    already exceeds the cap), the scan degenerates to single-sample
    chunks — 10-60% slower than the one-shot kernel in ISOLATION
    (scripts/bench_longctx.py: 6.9 vs 6.3 ms at seq 2048, 26.5 vs
    16.4 ms at seq 4096 fwd+bwd) but its remat stores NO probabilities:
    a 24-layer model at seq 4096 would otherwise keep ~12 GB of bf16
    probs resident for the backward and OOM a 16 GB chip. Memory safety
    wins this band, the same reasoning that keeps the >=2 GiB flash
    threshold despite dense beating blockwise just past it."""
    if batch * heads * sq * sk * 4 <= _DENSE_MONO_SCORE_BYTES:
        return batch
    for c in range(batch, 0, -1):
        if batch % c == 0 and c * heads * sq * sk * 4 <= _DENSE_CHUNK_SCORE_BYTES:
            return c
    return 1


def _chunked_dense_attention(q, k, v, causal, chunk):
    """scaled_dot_product_attention scanned over batch chunks — bounds the
    per-step f32 score working set (VMEM) without changing numerics.

    The chunk body is rematerialized: the backward recomputes each
    chunk's scores/probs from its (VMEM-sized) inputs instead of
    streaming stored probabilities from HBM. Measured on v5e at the
    flagship shape (seq 512, 16 heads), full train step, exactly-equal
    losses: bs8 23.8 -> 16.4 ms, bs16 56.96 -> 32.14 ms, bs32 111 ->
    66.7 ms — linear in batch at ~66-70% of bf16 peak
    (scripts/ab_attn_chunk2.py, scripts/probe_chain_lengths.py). Remat
    of the MONOLITHIC kernel does not help — the win needs the chunked
    working set."""
    from jax import lax

    b = q.shape[0]
    n = b // chunk
    qs = q.reshape(n, chunk, *q.shape[1:])
    ks = k.reshape(n, chunk, *k.shape[1:])
    vs = v.reshape(n, chunk, *v.shape[1:])

    @jax.checkpoint
    def body_fn(qq, kk, vv):
        return scaled_dot_product_attention(qq, kk, vv, causal=causal)

    def body(_, blk):
        return _, body_fn(*blk)

    _, out = lax.scan(body, None, (qs, ks, vs))
    return out.reshape(b, *q.shape[1:])


def _q_degrees(ctx):
    """Partition degrees of the q input's (batch, seq, heads) — heads via
    the head-parallel replica dim. (1, 1, 1) when no parallel shape is
    available. Under jit array shapes are GLOBAL; callers divide these out
    to reason about per-device working sets."""
    if ctx is None or not ctx.in_shapes:
        return 1, 1, 1
    qshape = ctx.in_shapes[0]
    logical = [d for d in qshape.dims if not d.is_replica_dim]
    rep = [d for d in qshape.dims if d.is_replica_dim]
    if len(logical) != 3:
        return 1, 1, 1
    b_deg = max(1, logical[0].degree)
    s_deg = max(1, logical[1].degree)
    h_deg = max(1, rep[0].degree) if rep else 1
    return b_deg, s_deg, h_deg


def _auto_flash(batch, heads, sq, sk, ctx=None) -> bool:
    # divide out the sharding so a data-parallel pod doesn't get blockwise
    # where its per-chip slice is tiny
    b_deg, s_deg, h_deg = _q_degrees(ctx)
    batch //= b_deg
    sq //= s_deg
    heads //= h_deg
    # >= : a score tensor exactly AT the threshold must already
    # take the streaming path (a 2 GiB materialization is the
    # failure mode, not the last safe point)
    return batch * heads * sq * sk * 4 >= _FLASH_SCORE_BYTES


def _tiled_flash_sharded(q, k, v, ctx, causal, specs):
    """Run the hand-tiled Pallas kernel (flash_kernel.py) per device by
    wrapping it in shard_map — the GSPMD-compatible way to place an
    opaque pallas call inside a sharded step (jit alone has no
    partitioning rule for it). `specs` is the PartitionSpec for q/k/v
    and the output; GSPMD reshards inputs to match, so callers choose
    the layout (e.g. Ulysses' seq→head all-to-all is exactly the
    reshard this wrapper's in_specs induce). Returns None when the
    per-device block doesn't tile."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map
    from flexflow_tpu.ops.pallas.flash_kernel import (
        flash_attention_tpu,
        supports,
    )

    if jax.default_backend() != "tpu":
        return None
    mesh = ctx.mesh

    def deg(ax):
        return mesh.shape[ax] if ax else 1

    bs_ax, sq_ax, h_ax, _ = specs
    if sq_ax is not None:
        # a sharded seq dim inside shard_map would compute BLOCK-DIAGONAL
        # attention (each device only its own keys) — that layout belongs
        # to ring_attention, not this wrapper
        return None
    if bs_ax is None and h_ax is None:
        # nothing to shard over: a fully-replicated shard_map would
        # all-gather whatever sharding the inputs DO carry (e.g. a seq
        # sharding this call was asked to densify) and recompute the
        # whole attention on every device — let XLA partition the
        # blockwise path instead
        return None
    h_loc = q.shape[2] // deg(h_ax)
    if (
        h_loc == 0
        or q.shape[2] % max(1, deg(h_ax))
        or not supports(q.shape[1], k.shape[1], q.shape[-1])
    ):
        return None
    from jax.sharding import PartitionSpec as P

    spec = P(*specs)
    fn = shard_map(
        lambda a, b, c: flash_attention_tpu(a, b, c, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def _try_tiled(q, k, v, ctx, causal):
    """The one dispatch point for the hand-tiled kernel outside the
    seq-parallel paths: direct call on a single device, shard_map over the
    batch/head axes on a mesh. None when the shape/backend doesn't take it
    (callers fall back to dense/blockwise)."""
    single = ctx is None or ctx.mesh is None or ctx.mesh.size == 1
    if single:
        if jax.default_backend() != "tpu":
            return None
        from flexflow_tpu.ops.pallas.flash_kernel import (
            flash_attention_tpu,
            supports,
        )

        if not supports(q.shape[1], k.shape[1], q.shape[-1]):
            return None
        return flash_attention_tpu(q, k, v, causal=causal)
    axes = _q_mesh_axes(ctx)
    b_ax, _, h_ax = axes if axes else (None, None, None)
    return _tiled_flash_sharded(
        q, k, v, ctx, causal, (b_ax, None, h_ax, None)
    )


def _lower_mha(params):
    causal = params.get("causal", False)
    use_flash = params.get("use_flash", "auto")
    use_bias = params.get("bias", True)
    dropout = params.get("dropout", 0.0)
    # "ring" | "ulysses" | "auto" | "none" — how attention runs when the
    # sequence dim is partitioned (TPU-native addition; the reference cannot
    # shard the attention sequence dim at all, SURVEY §5)
    seq_parallel = params.get("seq_parallel", "auto")
    if seq_parallel not in ("auto", "ring", "ulysses", "none"):
        raise ValueError(
            f"seq_parallel must be auto|ring|ulysses|none, got {seq_parallel!r}"
        )

    def _ulysses(q, k, v, ctx, seq_ax, batch_ax):
        # Ulysses: all-to-all the seq sharding onto the head dim, attend
        # locally, all-to-all back. On TPU the local attend runs the
        # hand-tiled Pallas kernel under shard_map (whose head-sharded
        # in_specs themselves induce the seq→head all-to-all); otherwise
        # GSPMD emits the all-to-alls from the layout constraints around
        # a jnp core.
        from jax.sharding import NamedSharding, PartitionSpec

        # use_flash=False is an explicit request for the dense core —
        # don't override it with the tiled kernel (the "auto" policy DOES
        # prefer tiled: measured on v5e it beats dense from seq 2048 up
        # and the margin grows with sequence, scripts/bench_flash_kernel)
        tiled = (
            _tiled_flash_sharded(
                q, k, v, ctx, causal, (batch_ax, None, seq_ax, None)
            )
            if use_flash is not False
            else None
        )
        if tiled is not None:
            seq_sp = NamedSharding(
                ctx.mesh, PartitionSpec(batch_ax, seq_ax, None, None)
            )
            return jax.lax.with_sharding_constraint(tiled, seq_sp)

        head_spec = NamedSharding(
            ctx.mesh, PartitionSpec(batch_ax, None, seq_ax, None)
        )
        qh = jax.lax.with_sharding_constraint(q, head_spec)
        kh = jax.lax.with_sharding_constraint(k, head_spec)
        vh = jax.lax.with_sharding_constraint(v, head_spec)
        # per-device geometry after the seq→head reshard: full sequence,
        # heads divided by the seq-axis degree, batch by the data axis
        b, s, h, _ = qh.shape
        sp_deg = ctx.mesh.shape[seq_ax]
        b_local = b // (ctx.mesh.shape[batch_ax] if batch_ax else 1)
        if use_flash is True or (
            use_flash == "auto"
            and _auto_flash(b_local, h // sp_deg, s, kh.shape[1])
        ):
            from flexflow_tpu.ops.pallas.flash_attention import flash_attention

            single = ctx.mesh is None or ctx.mesh.size == 1
            attn = flash_attention(
                qh, kh, vh, causal=causal,
                # None = auto (backend + device checks inside); a sharded
                # mesh must force the partitionable blockwise path
                use_lib=None if single else False,
            )
        else:
            attn = scaled_dot_product_attention(qh, kh, vh, causal=causal)
        seq_spec = NamedSharding(
            ctx.mesh, PartitionSpec(batch_ax, seq_ax, None, None)
        )
        return jax.lax.with_sharding_constraint(attn, seq_spec)

    def fn(ins, ws, ctx):
        dt = ins[0].dtype
        q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
        seq = q.shape[1]
        dropping = dropout > 0.0 and ctx.train and ctx.rng is not None
        sp = None if seq_parallel == "none" else _seq_parallel_axes(ctx)
        if sp is not None and dropping:
            if seq_parallel in ("ring", "ulysses"):
                # don't silently densify an explicitly requested SP path —
                # dense attention materializes the [s, s] scores SP avoids
                raise ValueError(
                    f"seq_parallel={seq_parallel!r} does not support "
                    "attention-prob dropout; use dropout=0.0 or "
                    "seq_parallel='auto' (which falls back to dense)"
                )
            sp = None
        if sp is not None:
            seq_ax, batch_ax, head_ax = sp
            mode = "ring" if seq_parallel == "auto" else seq_parallel
            # Ulysses reshards seq→heads, so it needs the head dim free of
            # TP sharding and divisible by the seq-axis degree
            ulysses_ok = (
                head_ax is None and q.shape[2] % ctx.mesh.shape[seq_ax] == 0
            )
            if mode == "ulysses" and not ulysses_ok:
                raise ValueError(
                    "seq_parallel='ulysses' needs num_heads divisible by the "
                    f"seq-axis degree ({ctx.mesh.shape[seq_ax]}) and heads "
                    "free of tensor-parallel sharding; use 'ring'"
                )
            if mode == "ulysses":
                attn = _ulysses(q, k, v, ctx, seq_ax, batch_ax)
            else:
                from flexflow_tpu.ops.pallas.ring_attention import ring_attention

                attn = ring_attention(
                    q,
                    k,
                    v,
                    ctx.mesh,
                    seq_ax,
                    causal=causal,
                    batch_axis=batch_ax,
                    head_axis=head_ax,
                )
        else:
            flash = (
                use_flash is True
                or (
                    use_flash == "auto"
                    and _auto_flash(
                        q.shape[0], q.shape[2], seq, k.shape[1], ctx
                    )
                )
            ) and not dropping  # the blockwise kernel has no prob-dropout path
            if flash:
                from flexflow_tpu.ops.pallas.flash_attention import flash_attention

                # the hand-tiled kernel wherever it takes the shape (direct
                # single-device, shard_map over batch/head axes on a mesh);
                # else the library kernel (single-device) or the jnp
                # blockwise path, which XLA partitions over batch/heads
                single = ctx is None or ctx.mesh is None or ctx.mesh.size == 1
                attn = _try_tiled(q, k, v, ctx, causal)
                if attn is None:
                    attn = flash_attention(
                        q, k, v, causal=causal,
                        use_lib=None if single else False,
                    )
            else:
                # batch-chunked dense: only when the batch dim is unsharded
                # (a scan cannot iterate a GSPMD-sharded leading axis) and
                # no prob-dropout (keeps the rng path on the one-shot
                # kernel); size the chunk by the PER-DEVICE score block, so
                # seq/head sharding divides out like in _auto_flash
                b_deg, s_deg, h_deg = _q_degrees(ctx)
                chunk = (
                    _dense_batch_chunk(
                        q.shape[0],
                        max(1, q.shape[2] // h_deg),
                        max(1, seq // s_deg),
                        k.shape[1],
                    )
                    if (b_deg == 1 and not dropping)
                    else q.shape[0]
                )
                # when even ONE sample's score block overflows the chunk
                # cap (seq ~2048-8192, small batch), the chunked scan
                # degenerates to a stores-nothing single-sample remat —
                # measured 10-60% SLOWER than one-shot dense in isolation.
                # That band belongs to the hand-tiled kernel: 12.4 ms vs
                # 21.8 dense / ~52 blockwise at seq 2048 bs8h16 on v5e
                # (scripts/bench_flash_kernel.py). Below it, chunked dense
                # keeps the full-step crown (19.0 vs 23.6 ms flagship
                # A/B, scripts/ab_attn_tiled.py — the tiled kernel's
                # per-call layout transposes eat its margin at seq 512).
                single_fits = (
                    max(1, q.shape[2] // h_deg)
                    * max(1, seq // s_deg)
                    * k.shape[1]
                    * 4
                    <= _DENSE_CHUNK_SCORE_BYTES
                )
                tiled = (
                    _try_tiled(q, k, v, ctx, causal)
                    if (
                        not single_fits
                        and not dropping
                        and use_flash is not False
                    )
                    else None
                )
                if tiled is not None:
                    attn = tiled
                elif chunk < q.shape[0]:
                    attn = _chunked_dense_attention(q, k, v, causal, chunk)
                else:
                    attn = scaled_dot_product_attention(
                        q,
                        k,
                        v,
                        causal=causal,
                        dropout_rate=dropout if dropping else 0.0,
                        dropout_rng=ctx.rng if dropping else None,
                    )
        return [mha_project_out(attn, ws, ctx, dt, use_bias=use_bias)]

    return fn


def _flops_mha(input_shapes, params):
    q = input_shapes[0]
    b, s, e = q.logical_sizes[-3:]
    proj = 4 * 2.0 * b * s * e * e
    attn = 2 * 2.0 * b * s * s * e
    return proj + attn


register_op(OperatorType.MULTIHEAD_ATTENTION, _infer_mha, _lower_mha, _flops_mha)
