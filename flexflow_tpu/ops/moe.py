"""Mixture-of-Experts op family: TopK, GroupBy, Aggregate(Spec), Cache.

Re-design of the reference's MoE ops (reference: src/ops/{topk,group_by,
aggregate,aggregate_spec,cache}.{cc,cu}; SURVEY §2.2): expert routing is
topk → group_by (scatter samples per expert) → expert ops → aggregate
(gather + gate-weighted sum), with a `lambda_bal` load-balancing loss.

TPU-native differences:
  * group_by/aggregate use fixed `capacity = ceil(alpha * k * batch / n)`
    slots per expert so shapes stay static under XLA (the reference sizes
    buffers the same way, group_by.cc), with one-hot-matmul dispatch —
    MXU-friendly, the GShard/Mesh-TF formulation — instead of scatter
    kernels;
  * dropped tokens (over capacity) contribute zeros, matching the
    reference's capacity-overflow behavior.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from flexflow_tpu.core.parallel_tensor import ParallelDim, ParallelTensorShape
from flexflow_tpu.core.types import DataType, OperatorType
from flexflow_tpu.ops.registry import register_op


# ---------------------------------------------------------------------------
# TopK (reference: src/ops/topk.cc)
# ---------------------------------------------------------------------------


def _infer_topk(input_shapes, params):
    (x,) = input_shapes
    k = params["k"]
    last = x.dims[-1]
    if last.degree > 1:
        raise ValueError("topk: topk dim may not be partitioned")
    out_dims = x.dims[:-1] + (ParallelDim(k),)
    values = ParallelTensorShape(out_dims, x.dtype)
    indices = ParallelTensorShape(out_dims, DataType.INT32)
    return (values, indices), ()


def _lower_topk(params):
    k = params["k"]

    def fn(ins, ws, ctx):
        (x,) = ins
        values, indices = jax.lax.top_k(x, k)
        return [values, indices.astype(jnp.int32)]

    return fn


register_op(OperatorType.TOPK, _infer_topk, _lower_topk)


# ---------------------------------------------------------------------------
# GroupBy (reference: src/ops/group_by.cc) — scatter samples to experts
# ---------------------------------------------------------------------------


def _capacity(batch, k, n_experts, alpha):
    return max(1, int(math.ceil(alpha * k * batch / n_experts)))


def _infer_group_by(input_shapes, params):
    # data [*lead, d], assign [*lead, k] int — leading dims are flattened
    # into one token axis (sequence MoE feeds [b, s, d], moe.cc encoder)
    data, assign = input_shapes
    n = params["n"]
    alpha = params.get("alpha", 1.0)
    d = data.dims[-1].size
    tokens = data.volume() // d
    k = assign.dims[-1].size
    cap = _capacity(tokens, k, n, alpha)
    if params.get("stacked", False):
        # one [n, cap, d] tensor whose expert dim may shard (EP)
        out = ParallelTensorShape(
            (ParallelDim(n), ParallelDim(cap), ParallelDim(d)), data.dtype
        )
        return (out,), ()
    out = ParallelTensorShape(
        (ParallelDim(cap), ParallelDim(d)), data.dtype
    )
    return tuple(out for _ in range(n)), ()


def dispatch_slots(assign, n_experts, capacity):
    """Slot assignment shared by group_by and aggregate.

    assign: [b, k] int expert ids. Returns slot_onehot [b*k, n, cap] 0/1
    float: entry (i*k+j, e, c) == 1 iff sample i's j-th choice is expert e
    and it got queue slot c. Tokens past capacity are dropped (all-zero
    row), like the reference's fixed-size expert batches.
    """
    flat = assign.reshape(-1)  # [b*k], sample i -> entries i*k..i*k+k-1
    onehot = jax.nn.one_hot(flat, n_experts, dtype=jnp.int32)  # [b*k, n]
    # position of each (sample, slot) within its expert queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # [b*k, n], -1 where absent
    keep = (pos >= 0) & (pos < capacity)
    pos = jnp.where(keep, pos, 0)
    return jax.nn.one_hot(pos, capacity, dtype=jnp.float32) * keep[..., None]


def dispatch_mask(assign, n_experts, capacity):
    """dispatch [n, cap, b]: dispatch[e, c, i] == 1 iff sample i holds slot
    c of expert e (summed over the k choices)."""
    b, k = assign.shape
    d = dispatch_slots(assign, n_experts, capacity).reshape(
        b, k, n_experts, capacity
    )
    return jnp.transpose(d, (2, 3, 0, 1)).sum(axis=-1)  # [n, cap, b]


def _lower_group_by(params):
    n = params["n"]
    alpha = params.get("alpha", 1.0)
    stacked = params.get("stacked", False)

    def fn(ins, ws, ctx):
        data, assign = ins
        feat = data.shape[-1]
        k = assign.shape[-1]
        data2 = data.reshape(-1, feat)  # [tokens, d]
        assign2 = assign.reshape(-1, k)
        tokens = data2.shape[0]
        cap = _capacity(tokens, k, n, alpha)
        d = dispatch_mask(assign2, n, cap)  # [n, cap, tokens]
        outs = jnp.einsum("ncb,bd->ncd", d.astype(data.dtype), data2)
        if stacked:
            return [outs]
        return [outs[e] for e in range(n)]

    return fn


register_op(OperatorType.GROUP_BY, _infer_group_by, _lower_group_by)


# ---------------------------------------------------------------------------
# ExpertFFN — batched per-expert two-layer MLP, EP-shardable (TPU-native;
# the reference's experts are separate Linear ops the search places on
# different GPUs — here the expert dim shards over the mesh like GShard)
# ---------------------------------------------------------------------------


def _infer_expert_ffn(input_shapes, params):
    (x,) = input_shapes  # [n, cap, d], expert dim may be partitioned
    hidden = params["hidden"]
    e, cap, d = x.dims
    out = ParallelTensorShape(
        (e, cap, ParallelDim(hidden)), x.dtype
    )
    # weights carry the expert dim's partitioning (each chip holds only
    # its experts' parameters — the point of EP)
    w1 = ParallelTensorShape(
        (e, ParallelDim(d.size), ParallelDim(hidden)), x.dtype
    )
    b1 = ParallelTensorShape((e, ParallelDim(hidden)), x.dtype)
    w2 = ParallelTensorShape(
        (e, ParallelDim(hidden), ParallelDim(hidden)), x.dtype
    )
    b2 = ParallelTensorShape((e, ParallelDim(hidden)), x.dtype)
    return (out,), (w1, b1, w2, b2)


def _lower_expert_ffn(params):
    from flexflow_tpu.ops.registry import mm_operands

    def fn(ins, ws, ctx):
        (x,) = ins
        w1, b1, w2, b2 = ws
        dt = x.dtype
        x, w1, w2 = mm_operands(ctx, x, w1, w2)
        h = jnp.einsum(
            "ecd,edh->ech", x, w1, preferred_element_type=jnp.float32
        ).astype(dt)
        h = jax.nn.relu(h + b1[:, None, :])
        (hm, w2m) = mm_operands(ctx, h, w2)
        y = jnp.einsum(
            "ech,ehf->ecf", hm, w2m, preferred_element_type=jnp.float32
        ).astype(dt)
        return [y + b2[:, None, :]]

    return fn


def _flops_expert_ffn(input_shapes, params):
    (x,) = input_shapes
    n, cap, d = x.logical_sizes
    h = params["hidden"]
    return 2.0 * n * cap * (d * h + h * h)


register_op(
    OperatorType.EXPERT_FFN, _infer_expert_ffn, _lower_expert_ffn,
    _flops_expert_ffn,
)


# ---------------------------------------------------------------------------
# Aggregate (reference: src/ops/aggregate.cc) — gate-weighted gather
# ---------------------------------------------------------------------------


def _infer_aggregate(input_shapes, params):
    # inputs: gate_values [*lead,k], gate_assign [*lead,k], then either
    # exp_pred_0..n-1 [cap, d] or one stacked [n, cap, d] -> [*lead, d]
    gate_values = input_shapes[0]
    exp0 = input_shapes[2]
    d_dim = exp0.dims[-1]
    lead = gate_values.dims[:-1]
    out_dims = []
    if params.get("stacked", False):
        e = exp0.dims[0]
        if e.degree > 1:
            # EP: each shard sums only its experts' contributions — the
            # output carries a replica dim a downstream Reduction folds
            # (exactly the Linear contraction-dim protocol)
            out_dims.append(ParallelDim(e.degree, e.degree, e.parallel_idx, True))
    out_dims.extend(lead)
    out_dims.append(ParallelDim(d_dim.size))
    out = ParallelTensorShape(tuple(out_dims), exp0.dtype)
    return (out,), ()


def _lower_aggregate(params):
    n = params["n"]
    stacked = params.get("stacked", False)

    def fn(ins, ws, ctx):
        gate_values, assign = ins[0], ins[1]
        exp_preds = ins[2] if stacked else jnp.stack(ins[2:], axis=0)
        lead = assign.shape[:-1]
        k = assign.shape[-1]
        assign2 = assign.reshape(-1, k)
        b = assign2.shape[0]
        cap = exp_preds.shape[1]
        # combine weights: gate value of the (token, slot) that owns each slot
        slot_onehot = dispatch_slots(assign2, n, cap)  # [b*k, n, cap]
        gates = gate_values.reshape(-1)[:, None, None]  # [b*k,1,1]
        combine = (slot_onehot * gates).reshape(b, k, n, cap).sum(axis=1)
        # combine: [b, n, cap]; output = sum over experts/slots
        y = jnp.einsum("bnc,ncd->bd", combine.astype(exp_preds.dtype), exp_preds)
        return [y.reshape(lead + (y.shape[-1],))]

    return fn


register_op(OperatorType.AGGREGATE, _infer_aggregate, _lower_aggregate)


def _infer_aggregate_spec(input_shapes, params):
    return _infer_aggregate(input_shapes, params)


def _lower_aggregate_spec(params):
    """AggregateSpec = Aggregate that does NOT backprop into the gate
    network (reference: aggregate_spec.cc — the speculative variant's
    backward sends expert gradients but no gate gradient; the reference
    MoE example pairs it with a plain Aggregate that trains the gate)."""
    inner = _lower_aggregate(params)

    def fn(ins, ws, ctx):
        ins2 = [jax.lax.stop_gradient(ins[0])] + list(ins[1:])
        return inner(ins2, ws, ctx)

    return fn


register_op(
    OperatorType.AGGREGATE_SPEC, _infer_aggregate_spec, _lower_aggregate_spec
)


# ---------------------------------------------------------------------------
# load-balancing auxiliary loss (reference: group_by lambda_bal)
# ---------------------------------------------------------------------------


def load_balance_loss(gate_probs, assign, n_experts):
    """GShard-style aux loss: n * sum_e (fraction_tokens_e * mean_prob_e).
    gate_probs [*lead, n] is the FULL gate distribution; assign [*lead, k]."""
    gp = gate_probs.reshape(-1, gate_probs.shape[-1])
    asg = assign.reshape(-1, assign.shape[-1])
    tokens = gp.shape[0]
    counts = jnp.sum(jax.nn.one_hot(asg[:, 0], n_experts), axis=0)
    frac = counts / tokens
    mean_prob = jnp.mean(gp, axis=0)
    return n_experts * jnp.sum(frac * mean_prob)


# ---------------------------------------------------------------------------
# Cache (reference: src/ops/cache.cc) — activation memoization
# ---------------------------------------------------------------------------


def _infer_cache(input_shapes, params):
    return (input_shapes[0],), ()


def _lower_cache(params):
    # In-graph the cache is an identity (reusing stale activations inside
    # a jitted step would silently change training math); the
    # MEMOIZATION lives host-side: the executor surfaces every cache
    # node's input each training step, FFModel keeps the last
    # `num_batches` of them and scores fresh-vs-cached drift with the
    # node's score function (reference: cache.cc score_f), and the score
    # feeds recompile_on_condition triggers — the moe.cc:65-99 pattern of
    # cached expert assignments driving re-sharding.
    def fn(ins, ws, ctx):
        return [ins[0]]

    return fn


def default_cache_score(cached, fresh):
    """Relative L1 drift of the fresh batch vs the rolling cached mean
    (reference: the moe example's score_f compares cached vs new expert
    assignments, moe.cc)."""
    import numpy as np

    if not cached:
        return 1.0
    ref = np.mean([np.asarray(c, dtype=np.float64) for c in cached], axis=0)
    fresh = np.asarray(fresh, dtype=np.float64)
    denom = np.abs(ref).sum() + 1e-12
    return float(np.abs(fresh - ref).sum() / denom)


register_op(OperatorType.CACHE, _infer_cache, _lower_cache)
