"""Core compute operators: parallel-shape inference + JAX lowerings.

Covers the reference's op set (SURVEY §2.2; reference: src/ops/*.cc):
linear, conv2d, pool2d, batch/layer-norm, embedding, dropout, element-wise
unary/binary, batch-matmul, softmax, concat/split/reshape/transpose/reverse/
flat/cast, reduce/mean. Attention and MoE ops live in sibling modules.

Layout conventions (TPU-idiomatic, diverging from the reference's NCHW):
  * images are NHWC, conv kernels are HWIO — XLA's native TPU layouts;
  * linear kernels are [in_features, out_features].

Tensor-parallel semantics follow the reference's replica-dim trick
(reference: linear.cc:969 LinearParams::solve_dims):
  * a replica dim on a Linear/Conv/Embedding *input* (inserted by a
    Replicate parallel op) becomes output-channel partitioning of the
    weight and a partitioned feature dim on the output;
  * partitioning the contraction dim of the input shards the weight's
    input dim and yields a replica dim on the *output* that a downstream
    Reduction parallel op must sum.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from flexflow_tpu.core.parallel_tensor import ParallelDim, ParallelTensorShape
from flexflow_tpu.core.types import ActiMode, AggrMode, DataType, OperatorType, PoolType
from flexflow_tpu.ops.registry import mm_operands, mm_out_dtype, register_op


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _split_replica(shape: ParallelTensorShape):
    """Split leading replica dims from logical dims."""
    rep = [d for d in shape.dims if d.is_replica_dim]
    logical = [d for d in shape.dims if not d.is_replica_dim]
    return rep, logical


def _apply_activation(x, act: ActiMode):
    if act is None or act == ActiMode.NONE:
        return x
    return {
        ActiMode.RELU: jax.nn.relu,
        ActiMode.SIGMOID: jax.nn.sigmoid,
        ActiMode.TANH: jnp.tanh,
        ActiMode.GELU: lambda v: jax.nn.gelu(v, approximate=False),
    }[act](x)


# ---------------------------------------------------------------------------
# graph sources
# ---------------------------------------------------------------------------


def _infer_noop(input_shapes, params):
    if input_shapes:
        return tuple(input_shapes), ()
    return (params["shape"],), ()


register_op(OperatorType.NOOP, _infer_noop, lambda p: lambda ins, ws, ctx: list(ins))
register_op(OperatorType.INPUT, _infer_noop, lambda p: lambda ins, ws, ctx: list(ins))
register_op(OperatorType.WEIGHT, _infer_noop, lambda p: lambda ins, ws, ctx: list(ins))


# ---------------------------------------------------------------------------
# Linear (reference: src/ops/linear.cc, kernels/linear_kernels.cu)
# ---------------------------------------------------------------------------


def _infer_linear(input_shapes, params):
    (x,) = input_shapes
    out_features = params["out_features"]
    use_bias = params.get("use_bias", True)
    dtype = params.get("dtype", x.dtype)

    rep, logical = _split_replica(x)
    if len(rep) > 1:
        raise ValueError("linear: at most one input replica dim supported")
    in_dim = logical[-1]
    batch_dims = logical[:-1]

    r_deg = rep[0].degree if rep else 1          # -> out-channel parallelism
    r_idx = rep[0].parallel_idx if rep else -1
    k_deg = in_dim.degree                        # -> contraction parallelism
    k_idx = in_dim.parallel_idx

    if out_features % r_deg != 0:
        raise ValueError("linear: replica degree must divide out_features")

    out_dims = []
    if k_deg > 1:
        # partial sums: replica dim a downstream Reduction must fold
        out_dims.append(ParallelDim(k_deg, k_deg, k_idx, True))
    out_dims.extend(batch_dims)
    out_dims.append(ParallelDim(out_features, r_deg, r_idx))
    out = ParallelTensorShape(tuple(out_dims), dtype)

    kernel = ParallelTensorShape(
        (
            ParallelDim(in_dim.size, k_deg, k_idx),
            ParallelDim(out_features, r_deg, r_idx),
        ),
        dtype,
    )
    weights = [kernel]
    if use_bias:
        weights.append(
            ParallelTensorShape((ParallelDim(out_features, r_deg, r_idx),), dtype)
        )
    return (out,), tuple(weights)


def _lower_linear(params):
    act = params.get("activation", ActiMode.NONE)
    use_bias = params.get("use_bias", True)

    def fn(ins, ws, ctx):
        (x,) = ins
        kernel = ws[0]
        xm, km = mm_operands(ctx, x, kernel)
        y = jnp.matmul(xm, km, preferred_element_type=jnp.float32)
        y = y.astype(mm_out_dtype(ctx, kernel.dtype))
        if use_bias:
            y = y + ws[1].astype(y.dtype)
        return [_apply_activation(y, act)]

    return fn


def _flops_linear(input_shapes, params):
    x = input_shapes[0]
    batch = x.volume() // x.logical_sizes[-1]
    return 2.0 * batch * x.logical_sizes[-1] * params["out_features"]


register_op(OperatorType.LINEAR, _infer_linear, _lower_linear, _flops_linear)


# ---------------------------------------------------------------------------
# Conv2D (reference: src/ops/conv_2d.cc) — NHWC / HWIO
# ---------------------------------------------------------------------------


def _pad2(pad):
    """Normalize a padding param: int (symmetric) or (lo, hi) tuple."""
    if isinstance(pad, (tuple, list)):
        lo, hi = pad
        return int(lo), int(hi)
    return int(pad), int(pad)


def _conv_out_size(in_size, kernel, stride, pad):
    lo, hi = _pad2(pad)
    return (in_size + lo + hi - kernel) // stride + 1


def _infer_conv2d(input_shapes, params):
    (x,) = input_shapes
    rep, logical = _split_replica(x)
    n, h, w, c = logical
    kh, kw = params["kernel_h"], params["kernel_w"]
    sh, sw = params["stride_h"], params["stride_w"]
    ph, pw = params["padding_h"], params["padding_w"]
    out_channels = params["out_channels"]
    groups = params.get("groups", 1)
    use_bias = params.get("use_bias", True)
    dtype = params.get("dtype", x.dtype)

    r_deg = rep[0].degree if rep else 1
    r_idx = rep[0].parallel_idx if rep else -1
    if c.degree > 1:
        raise ValueError(
            "conv2d: partitioned input channels need a Reduction rewrite"
        )

    oh = _conv_out_size(h.size, kh, sh, ph)
    ow = _conv_out_size(w.size, kw, sw, pw)
    out = ParallelTensorShape(
        (
            n,
            ParallelDim(oh, h.degree, h.parallel_idx),
            ParallelDim(ow, w.degree, w.parallel_idx),
            ParallelDim(out_channels, r_deg, r_idx),
        ),
        dtype,
    )
    kernel = ParallelTensorShape(
        (
            ParallelDim(kh),
            ParallelDim(kw),
            ParallelDim(c.size // groups),
            ParallelDim(out_channels, r_deg, r_idx),
        ),
        dtype,
    )
    weights = [kernel]
    if use_bias:
        weights.append(
            ParallelTensorShape((ParallelDim(out_channels, r_deg, r_idx),), dtype)
        )
    return (out,), tuple(weights)


def _lower_conv2d(params):
    sh, sw = params["stride_h"], params["stride_w"]
    ph, pw = _pad2(params["padding_h"]), _pad2(params["padding_w"])
    groups = params.get("groups", 1)
    act = params.get("activation", ActiMode.NONE)
    use_bias = params.get("use_bias", True)

    def fn(ins, ws, ctx):
        (x,) = ins
        kernel = ws[0]
        xm, km = mm_operands(ctx, x, kernel)
        # bf16 operands skip preferred_element_type=f32: the conv VJP
        # transposes a f32 cotangent onto the bf16 operand and dies on the
        # dtype mismatch (unlike dot_general's). MXU conv accumulation is
        # f32 internally either way; only the pre-upcast rounding differs.
        pet = jnp.float32 if xm.dtype == jnp.float32 else None
        y = jax.lax.conv_general_dilated(
            xm,
            km,
            window_strides=(sh, sw),
            padding=[ph, pw],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups,
            preferred_element_type=pet,
        ).astype(mm_out_dtype(ctx, kernel.dtype))
        if use_bias:
            y = y + ws[1].astype(y.dtype)
        return [_apply_activation(y, act)]

    return fn


def _flops_conv2d(input_shapes, params):
    (x,) = input_shapes
    n, h, w, c = x.logical_sizes
    oh = _conv_out_size(h, params["kernel_h"], params["stride_h"], params["padding_h"])
    ow = _conv_out_size(w, params["kernel_w"], params["stride_w"], params["padding_w"])
    groups = params.get("groups", 1)
    return (
        2.0 * n * oh * ow * params["out_channels"]
        * params["kernel_h"] * params["kernel_w"] * (c // groups)
    )


register_op(OperatorType.CONV2D, _infer_conv2d, _lower_conv2d, _flops_conv2d)


# ---------------------------------------------------------------------------
# Pool2D (reference: src/ops/pool_2d.cc)
# ---------------------------------------------------------------------------


def _infer_pool2d(pool_type):
    def infer(input_shapes, params):
        (x,) = input_shapes
        rep, logical = _split_replica(x)
        n, h, w, c = logical
        kh, kw = params["kernel_h"], params["kernel_w"]
        sh, sw = params["stride_h"], params["stride_w"]
        ph, pw = params["padding_h"], params["padding_w"]
        oh = _conv_out_size(h.size, kh, sh, ph)
        ow = _conv_out_size(w.size, kw, sw, pw)
        out = ParallelTensorShape(
            tuple(rep)
            + (n, ParallelDim(oh), ParallelDim(ow), c),
            x.dtype,
        )
        return (out,), ()

    return infer


def _lower_pool2d(pool_type):
    def lower(params):
        kh, kw = params["kernel_h"], params["kernel_w"]
        sh, sw = params["stride_h"], params["stride_w"]
        ph, pw = _pad2(params["padding_h"]), _pad2(params["padding_w"])
        act = params.get("activation", ActiMode.NONE)

        def fn(ins, ws, ctx):
            (x,) = ins
            pad = [(0, 0), ph, pw, (0, 0)]
            window = (1, kh, kw, 1)
            strides = (1, sh, sw, 1)
            if pool_type == PoolType.MAX:
                init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
                y = jax.lax.reduce_window(
                    x, init, jax.lax.max, window, strides, pad,
                )
            else:
                s = jax.lax.reduce_window(
                    x, 0.0, jax.lax.add, window, strides, pad,
                )
                include_pad = params.get("count_include_pad", True)
                if not include_pad and any(p != (0, 0) for p in (ph, pw)):
                    # divide by the in-bounds count only (keras/TF 'same'
                    # and ONNX default avg-pool semantics)
                    ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
                    cnt = jax.lax.reduce_window(
                        ones, 0.0, jax.lax.add, window, strides, pad,
                    )
                    y = s / cnt
                else:
                    # full-kernel-area divisor (torch AvgPool2d default)
                    y = s / (kh * kw)
            return [_apply_activation(y, act)]

        return fn

    return lower


register_op(OperatorType.POOL2D_MAX, _infer_pool2d(PoolType.MAX), _lower_pool2d(PoolType.MAX))
register_op(OperatorType.POOL2D_AVG, _infer_pool2d(PoolType.AVG), _lower_pool2d(PoolType.AVG))


# ---------------------------------------------------------------------------
# Normalization (reference: src/ops/batch_norm.cc, layer_norm.cc)
# ---------------------------------------------------------------------------


def _infer_batchnorm(input_shapes, params):
    (x,) = input_shapes
    c = x.dims[-1]
    dtype = x.dtype
    scale = ParallelTensorShape((ParallelDim(c.size, c.degree, c.parallel_idx),), dtype)
    return (x,), (scale, scale)  # gamma, beta


def _lower_batchnorm(params):
    eps = params.get("eps", 1e-5)
    act = params.get("activation", ActiMode.NONE)

    def fn(ins, ws, ctx):
        (x,) = ins
        gamma, beta = ws
        axes = tuple(range(x.ndim - 1))
        # stats accumulate in f32 even when activations flow bf16 (mixed
        # precision): bf16 mean/var over big reductions loses too much.
        # One-pass moments: var = E[(x-c)^2] - E[x-c]^2 with a CHEAP
        # per-channel anchor c (the first sample's mean). Both sums
        # accumulate in a single pass over the activation, where the
        # textbook E[(x-mean)^2] chains a second full HBM read behind the
        # mean (measured on ResNet-50 bs16, one v5e, interleaved A/B with
        # warmed alternating bursts: ~6% whole-step win,
        # scripts/ab_resnet_bn.py). The raw E[x^2]-E[x]^2 form would
        # cancel catastrophically for |mean| >> std inputs; anchoring at
        # c (within a few std of the true mean for any data whose first
        # sample resembles the batch) bounds the cancellation to
        # ((mean-c)/std)^2 relative — exactness vs the two-pass form is
        # pinned by tests/test_alignment.py and the large-offset case in
        # test_bn_large_mean_numerics.
        xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
        c = jax.lax.stop_gradient(
            jnp.mean(xf[:1], axis=axes[1:], keepdims=True)
            if xf.ndim > 1
            else jnp.zeros((1,) * xf.ndim, jnp.float32)
        )
        xs = xf - c
        mean_s = jnp.mean(xs, axis=axes, keepdims=True)
        ex2 = jnp.mean(jnp.square(xs), axis=axes, keepdims=True)
        var = jnp.maximum(ex2 - jnp.square(mean_s), 0.0)
        y = (xs - mean_s) * jax.lax.rsqrt(var + eps) * gamma + beta
        return [_apply_activation(y.astype(x.dtype), act)]

    return fn


register_op(OperatorType.BATCHNORM, _infer_batchnorm, _lower_batchnorm)


def _infer_layernorm(input_shapes, params):
    (x,) = input_shapes
    axes = params.get("axes", (x.ndim - 1,))
    elementwise_affine = params.get("elementwise_affine", True)
    for a in axes:
        if x.dims[a].degree > 1:
            raise ValueError("layernorm: normalized dim may not be partitioned")
    weights = ()
    if elementwise_affine:
        wdims = tuple(ParallelDim(x.dims[a].size) for a in axes)
        w = ParallelTensorShape(wdims, x.dtype)
        weights = (w, w)
    return (x,), weights


def _lower_layernorm(params):
    eps = params.get("eps", 1e-5)
    elementwise_affine = params.get("elementwise_affine", True)

    def fn(ins, ws, ctx):
        (x,) = ins
        axes = params.get("axes", (x.ndim - 1,))
        # f32 statistics under bf16 activation flow (mixed precision)
        xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + eps)
        if elementwise_affine:
            y = y * ws[0] + ws[1]
        return [y.astype(x.dtype)]

    return fn


register_op(OperatorType.LAYERNORM, _infer_layernorm, _lower_layernorm)


# ---------------------------------------------------------------------------
# Embedding (reference: src/ops/embedding.cc) — key DLRM op
# ---------------------------------------------------------------------------


def _infer_embedding(input_shapes, params):
    (x,) = input_shapes  # int ids [*batch] or [*batch, bag]
    num_entries = params["num_entries"]
    out_dim = params["out_dim"]
    aggr = params.get("aggr", AggrMode.NONE)
    dtype = params.get("dtype", DataType.FLOAT)

    rep, logical = _split_replica(x)
    r_deg = rep[0].degree if rep else 1
    r_idx = rep[0].parallel_idx if rep else -1

    out_batch = list(logical)
    if aggr != AggrMode.NONE:
        out_batch = out_batch[:-1]  # bag dim folded
    out = ParallelTensorShape(
        tuple(out_batch) + (ParallelDim(out_dim, r_deg, r_idx),), dtype
    )
    weight = ParallelTensorShape(
        (ParallelDim(num_entries), ParallelDim(out_dim, r_deg, r_idx)), dtype
    )
    return (out,), (weight,)


def _lower_embedding(params):
    aggr = params.get("aggr", AggrMode.NONE)

    def fn(ins, ws, ctx):
        (ids,) = ins
        (table,) = ws
        y = jnp.take(table, ids, axis=0)
        if aggr == AggrMode.SUM:
            y = jnp.sum(y, axis=-2)
        elif aggr == AggrMode.AVG:
            y = jnp.mean(y, axis=-2)
        return [y]

    return fn


register_op(OperatorType.EMBEDDING, _infer_embedding, _lower_embedding)


# ---------------------------------------------------------------------------
# Dropout (reference: src/ops/dropout.cc)
# ---------------------------------------------------------------------------


def _infer_same(input_shapes, params):
    return (input_shapes[0],), ()


def _lower_dropout(params):
    rate = params.get("rate", 0.5)
    seed = params.get("seed", 0)

    def fn(ins, ws, ctx):
        (x,) = ins
        if not ctx.train or rate == 0.0 or ctx.rng is None:
            return [x]
        keep = 1.0 - rate
        rng = jax.random.fold_in(ctx.rng, seed) if seed else ctx.rng
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)]

    return fn


register_op(OperatorType.DROPOUT, _infer_same, _lower_dropout)


# ---------------------------------------------------------------------------
# Element-wise unary (reference: src/ops/element_unary.cc)
# ---------------------------------------------------------------------------

_UNARY_FNS = {
    OperatorType.RELU: lambda x, p: jax.nn.relu(x),
    OperatorType.SIGMOID: lambda x, p: jax.nn.sigmoid(x),
    OperatorType.TANH: lambda x, p: jnp.tanh(x),
    OperatorType.ELU: lambda x, p: jax.nn.elu(x),
    # exact (erf) form: matches torch's default and keeps frontend
    # alignment tests tight; XLA lowers erf natively on TPU
    OperatorType.GELU: lambda x, p: jax.nn.gelu(x, approximate=False),
    OperatorType.IDENTITY: lambda x, p: x,
    OperatorType.EXP: lambda x, p: jnp.exp(x),
    OperatorType.SIN: lambda x, p: jnp.sin(x),
    OperatorType.COS: lambda x, p: jnp.cos(x),
    OperatorType.POW: lambda x, p: jnp.power(x, p.get("exponent", 1.0)),
    OperatorType.RSQRT: lambda x, p: jax.lax.rsqrt(x),
    OperatorType.SCALAR_MULTIPLY: lambda x, p: x * p["scalar"],
    OperatorType.SCALAR_ADD: lambda x, p: x + p["scalar"],
    OperatorType.SCALAR_SUB: lambda x, p: x - p["scalar"],
    OperatorType.SCALAR_TRUE_DIV: lambda x, p: x / p["scalar"],
}


def _make_unary_lower(op_type):
    def lower(params):
        f = _UNARY_FNS[op_type]

        def fn(ins, ws, ctx):
            return [f(ins[0], params)]

        return fn

    return lower


for _ut in _UNARY_FNS:
    register_op(_ut, _infer_same, _make_unary_lower(_ut))


# ---------------------------------------------------------------------------
# Element-wise binary (reference: src/ops/element_binary.cc) with broadcast
# ---------------------------------------------------------------------------

_BINARY_FNS = {
    OperatorType.EW_ADD: jnp.add,
    OperatorType.EW_SUB: jnp.subtract,
    OperatorType.EW_MUL: jnp.multiply,
    OperatorType.EW_DIV: jnp.divide,
    OperatorType.EW_MAX: jnp.maximum,
    OperatorType.EW_MIN: jnp.minimum,
}


def _infer_binary(input_shapes, params):
    a, b = input_shapes
    # output shape = numpy broadcast of logical shapes; degrees from the
    # larger-ranked operand (degrees must agree where both partitioned).
    la, lb = list(a.dims), list(b.dims)
    if any(d.is_replica_dim for d in la + lb):
        raise ValueError("binary op on replica-dim tensors not supported")
    out_sizes = tuple(
        jnp.broadcast_shapes(tuple(d.size for d in la), tuple(d.size for d in lb))
    )
    big = la if len(la) >= len(lb) else lb
    small = lb if len(la) >= len(lb) else la
    offset = len(big) - len(small)
    out_dims = []
    for i, size in enumerate(out_sizes):
        d_big = big[i]
        d_small = small[i - offset] if i >= offset else None
        src = d_big
        if d_big.size != size and d_small is not None and d_small.size == size:
            src = d_small
        if (
            d_small is not None
            and d_big.size == d_small.size == size
            and d_big.degree != d_small.degree
        ):
            raise ValueError("binary op: mismatched partition degrees")
        out_dims.append(ParallelDim(size, src.degree, src.parallel_idx))
    return (ParallelTensorShape(tuple(out_dims), a.dtype),), ()


def _make_binary_lower(op_type):
    def lower(params):
        f = _BINARY_FNS[op_type]

        def fn(ins, ws, ctx):
            return [f(ins[0], ins[1])]

        return fn

    return lower


for _bt in _BINARY_FNS:
    register_op(_bt, _infer_binary, _make_binary_lower(_bt))


# ---------------------------------------------------------------------------
# BatchMatmul (reference: src/ops/batch_matmul.cc)
# ---------------------------------------------------------------------------


def _infer_batchmatmul(input_shapes, params):
    a, b = input_shapes
    *ab, m, k1 = a.dims
    *bb, k2, n = b.dims
    if k1.size != k2.size:
        raise ValueError(f"batchmatmul: contraction mismatch {k1.size} vs {k2.size}")
    if tuple(d.size for d in ab) != tuple(d.size for d in bb):
        raise ValueError("batchmatmul: batch dims mismatch")
    out = ParallelTensorShape(
        tuple(ab) + (ParallelDim(m.size, m.degree, m.parallel_idx),
                     ParallelDim(n.size, n.degree, n.parallel_idx)),
        a.dtype,
    )
    return (out,), ()


def _lower_batchmatmul(params):
    # per-iteration dynamic sequence truncation (reference: BatchMatmul's
    # a_seq_length_dim/b_seq_length_dim + FFIterationConfig.seq_length,
    # model.h:461-465; a static slice at trace time — each distinct
    # seq_length is one XLA recompile, the analog of a new Legion trace)
    a_seq_dim = params.get("a_seq_length_dim", -1)
    b_seq_dim = params.get("b_seq_length_dim", -1)

    def _truncate(x, dim, length):
        if dim < 0 or length is None or length >= x.shape[dim]:
            return x
        idx = [slice(None)] * x.ndim
        idx[dim] = slice(0, length)
        return x[tuple(idx)]

    def fn(ins, ws, ctx):
        a, b = ins
        if ctx is not None and ctx.seq_length is not None:
            a = _truncate(a, a_seq_dim, ctx.seq_length)
            b = _truncate(b, b_seq_dim, ctx.seq_length)
        am, bm = mm_operands(ctx, a, b)
        y = jnp.matmul(am, bm, preferred_element_type=jnp.float32)
        return [y.astype(mm_out_dtype(ctx, a.dtype))]

    return fn


def _flops_batchmatmul(input_shapes, params):
    a, b = input_shapes
    return 2.0 * a.volume() * b.logical_sizes[-1]


register_op(
    OperatorType.BATCHMATMUL, _infer_batchmatmul, _lower_batchmatmul, _flops_batchmatmul
)


# ---------------------------------------------------------------------------
# Softmax (reference: src/ops/softmax.cc)
# ---------------------------------------------------------------------------


def _infer_softmax(input_shapes, params):
    (x,) = input_shapes
    dim = params.get("dim", -1) % x.ndim
    if x.dims[dim].degree > 1:
        raise ValueError("softmax: softmax dim may not be partitioned")
    return (x,), ()


def _lower_softmax(params):
    def fn(ins, ws, ctx):
        dim = params.get("dim", -1)
        return [jax.nn.softmax(ins[0], axis=dim)]

    return fn


register_op(OperatorType.SOFTMAX, _infer_softmax, _lower_softmax)


# ---------------------------------------------------------------------------
# Layout ops: concat / split / reshape / transpose / reverse / flat / cast
# ---------------------------------------------------------------------------


def _infer_concat(input_shapes, params):
    axis = params["axis"] % input_shapes[0].ndim
    base = input_shapes[0]
    total = 0
    deg0 = base.dims[axis].degree
    pidx0 = base.dims[axis].parallel_idx
    for s in input_shapes:
        d = s.dims[axis]
        if d.degree != deg0 or (deg0 > 1 and d.parallel_idx != pidx0):
            # a MIX of shardings on the concat axis is not representable;
            # uniform sharding is (the combine-sink rewrite's inception
            # pattern: channel-concat of channel-sharded branches — the
            # executor lowers global arrays, GSPMD realizes the layout)
            raise ValueError(
                "concat: concat-axis sharding must match across inputs"
            )
        if deg0 > 1 and d.size % deg0 != 0:
            raise ValueError(
                "concat: sharded concat axis must divide evenly"
            )
        total += d.size
    out = base.with_dim(axis, ParallelDim(total, deg0, pidx0))
    return (out,), ()


def _lower_concat(params):
    def fn(ins, ws, ctx):
        return [jnp.concatenate(ins, axis=params["axis"])]

    return fn


register_op(OperatorType.CONCAT, _infer_concat, _lower_concat)


def _infer_split(input_shapes, params):
    (x,) = input_shapes
    axis = params["axis"] % x.ndim
    sizes = params["sizes"]
    if x.dims[axis].degree > 1:
        raise ValueError("split: split axis may not be partitioned")
    if sum(sizes) != x.dims[axis].size:
        raise ValueError("split: sizes must sum to axis size")
    outs = tuple(x.with_dim(axis, ParallelDim(s)) for s in sizes)
    return outs, ()


def _lower_split(params):
    def fn(ins, ws, ctx):
        (x,) = ins
        axis = params["axis"]
        idxs = []
        acc = 0
        for s in params["sizes"][:-1]:
            acc += s
            idxs.append(acc)
        return list(jnp.split(x, idxs, axis=axis))

    return fn


register_op(OperatorType.SPLIT, _infer_split, _lower_split)


def _infer_reshape(input_shapes, params):
    (x,) = input_shapes
    new_sizes = tuple(params["shape"])
    if math.prod(new_sizes) != x.volume():
        raise ValueError(
            f"reshape: volume mismatch {x.logical_sizes} -> {new_sizes}"
        )
    dims = []
    for i, s in enumerate(new_sizes):
        # degree survives only on a leading dim of unchanged size
        if i == 0 and x.dims and x.dims[0].size == s and not x.dims[0].is_replica_dim:
            dims.append(ParallelDim(s, x.dims[0].degree, x.dims[0].parallel_idx))
        else:
            dims.append(ParallelDim(s))
    return (ParallelTensorShape(tuple(dims), x.dtype),), ()


def _lower_reshape(params):
    def fn(ins, ws, ctx):
        return [jnp.reshape(ins[0], tuple(params["shape"]))]

    return fn


register_op(OperatorType.RESHAPE, _infer_reshape, _lower_reshape)


def _infer_transpose(input_shapes, params):
    (x,) = input_shapes
    perm = params["perm"]
    dims = tuple(x.dims[p] for p in perm)
    return (ParallelTensorShape(dims, x.dtype),), ()


def _lower_transpose(params):
    def fn(ins, ws, ctx):
        return [jnp.transpose(ins[0], axes=tuple(params["perm"]))]

    return fn


register_op(OperatorType.TRANSPOSE, _infer_transpose, _lower_transpose)


def _infer_reverse(input_shapes, params):
    return (input_shapes[0],), ()


def _lower_reverse(params):
    def fn(ins, ws, ctx):
        return [jnp.flip(ins[0], axis=params["axis"])]

    return fn


register_op(OperatorType.REVERSE, _infer_reverse, _lower_reverse)


def _infer_flat(input_shapes, params):
    (x,) = input_shapes
    n = x.dims[0]
    rest = 1
    for d in x.dims[1:]:
        rest *= d.size
    out = ParallelTensorShape(
        (ParallelDim(n.size, n.degree, n.parallel_idx), ParallelDim(rest)), x.dtype
    )
    return (out,), ()


def _lower_flat(params):
    def fn(ins, ws, ctx):
        (x,) = ins
        return [jnp.reshape(x, (x.shape[0], -1))]

    return fn


register_op(OperatorType.FLAT, _infer_flat, _lower_flat)


def _infer_cast(input_shapes, params):
    (x,) = input_shapes
    return (ParallelTensorShape(x.dims, params["dtype"]),), ()


def _lower_cast(params):
    def fn(ins, ws, ctx):
        return [ins[0].astype(params["dtype"].to_jnp())]

    return fn


register_op(OperatorType.CAST, _infer_cast, _lower_cast)


# ---------------------------------------------------------------------------
# Reductions (reference: src/ops/reduce.cc, mean.cc)
# ---------------------------------------------------------------------------


def _infer_reduce(input_shapes, params):
    (x,) = input_shapes
    axes = tuple(a % x.ndim for a in params["axes"])
    keepdims = params.get("keepdims", False)
    dims = []
    for i, d in enumerate(x.dims):
        if i in axes:
            if d.degree > 1:
                raise ValueError("reduce: reduced dim may not be partitioned")
            if keepdims:
                dims.append(ParallelDim(1))
        else:
            dims.append(d)
    if not dims:
        dims = [ParallelDim(1)]
    return (ParallelTensorShape(tuple(dims), x.dtype),), ()


def _make_reduce_lower(reducer):
    def lower(params):
        def fn(ins, ws, ctx):
            return [
                reducer(
                    ins[0],
                    axis=tuple(params["axes"]),
                    keepdims=params.get("keepdims", False),
                )
            ]

        return fn

    return lower


register_op(OperatorType.REDUCE_SUM, _infer_reduce, _make_reduce_lower(jnp.sum))
register_op(OperatorType.MEAN, _infer_reduce, _make_reduce_lower(jnp.mean))


# ---------------------------------------------------------------------------
# Gather (used by frontends)
# ---------------------------------------------------------------------------


def _infer_gather(input_shapes, params):
    x, idx = input_shapes
    axis = params.get("axis", 0) % x.ndim
    out = x.with_dim(axis, ParallelDim(idx.dims[axis].size))
    return (out,), ()


def _lower_gather(params):
    def fn(ins, ws, ctx):
        x, idx = ins
        return [jnp.take_along_axis(x, idx, axis=params.get("axis", 0))]

    return fn


register_op(OperatorType.GATHER, _infer_gather, _lower_gather)


# ---------------------------------------------------------------------------
# FusedOp (reference: src/ops/fused.cc:437 + fused.cu:918 — one task
# dispatching many inner kernels through indirection tables). Here the
# sub-op list lives in params["sub_ops"]; infer/lower chain the inner
# OpDefs, slicing the flattened weight list per sub-op.
# ---------------------------------------------------------------------------


def _infer_fused(input_shapes, params):
    from flexflow_tpu.ops.registry import infer_shapes as _infer

    shapes = list(input_shapes)
    weights = []
    for sub in params["sub_ops"]:
        outs, ws = _infer(sub["op_type"], shapes, sub["params"])
        if len(outs) != 1:
            raise ValueError("fused sub-ops must be single-output")
        shapes = [outs[0]]
        weights.extend(ws)
    return (shapes[0],), tuple(weights)


def _lower_fused(params):
    import dataclasses as _dc

    from flexflow_tpu.ops.registry import lower_op as _lower

    subs = [
        (_lower(sub["op_type"], sub["params"]), sub["num_weights"])
        for sub in params["sub_ops"]
    ]

    def fn(ins, ws, ctx):
        x = ins[0]
        off = 0
        for i, (sub_fn, nw) in enumerate(subs):
            sub_ctx = ctx
            if ctx is not None and ctx.rng is not None:
                # each sub-op gets an independent stream — the executor
                # folds rng per NODE, and fusion must not make two dropouts
                # in one chain draw identical masks
                sub_ctx = _dc.replace(ctx, rng=jax.random.fold_in(ctx.rng, i))
            (x,) = sub_fn([x], ws[off : off + nw], sub_ctx)
            off += nw
        return [x]

    return fn


def _flops_fused(input_shapes, params):
    from flexflow_tpu.ops.registry import infer_shapes as _infer
    from flexflow_tpu.ops.registry import op_flops as _flops

    shapes = list(input_shapes)
    total = 0.0
    for sub in params["sub_ops"]:
        total += _flops(sub["op_type"], shapes, sub["params"])
        outs, _ = _infer(sub["op_type"], shapes, sub["params"])
        shapes = [outs[0]]
    return total


register_op(OperatorType.FUSED, _infer_fused, _lower_fused, _flops_fused)
