"""Hand-tiled Pallas TPU flash attention: forward + custom-VJP backward.

This is the framework's own MXU-tiled attention kernel — the piece the
reference implements as one opaque cudnnMultiHeadAttnForward call per shard
(reference: src/ops/attention.cu:35). Design:

  * **Forward** — grid (batch, heads, q_blocks, k_blocks), k innermost.
    Each (q_block, k_block) step computes an MXU matmul `q @ k^T` on
    VMEM-resident tiles and folds it into online-softmax accumulators
    (m, l, acc) held in VMEM scratch across the k iterations; the output
    tile and the row log-sum-exp are written once, on the last k step.
    The [s, s] score matrix never exists in HBM.
  * **Backward** — two kernels, both recomputing probabilities from
    (q, k, lse) instead of loading them (flash attention's defining
    trade): a dq kernel accumulating over k blocks and a dk/dv kernel
    accumulating over q blocks. Residuals are just (q, k, v, o, lse) —
    O(s·d), not O(s²).
  * **LSE is a public output** (`return_lse=True`): partial results from
    different key ranges merge exactly via log-sum-exp algebra, which is
    what lets ring attention (pallas/ring_attention.py) run this kernel
    per ppermute step under shard_map and combine blocks across devices —
    the multi-device long-context path runs MXU-tiled compute.
  * **Causal** skips fully-masked k blocks (the index maps redirect the
    skipped block's DMA to a useful one, after the library kernel's
    prefetch idiom) — ~2x at long sequence.

Block sizes default to the v5e-measured 512x1024 (a ~2 MB f32 score tile
plus ~128 KB operand tiles at head_dim 64 — comfortable in VMEM) and can
be overridden per-call or process-wide from a measured calibration table
(`set_tuned_blocks`, wired from scripts/calibrate.py --tune-flash).

Shapes are [b, s, h, d] at the API boundary (the layout ops/attention.py
produces); the kernel works on [b, h, s, d].
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flexflow_tpu.ops.pallas import compiler_params as _compiler_params

LANES = 128
_MASK = -1e30  # finite mask value: keeps exp()=0 without inf-inf NaNs

# process-wide tuned defaults (overridden by set_tuned_blocks). The
# built-ins are the v5e-measured winner of scripts/calibrate.py
# --tune-flash at seq 4096 (4.01 ms vs 5.49 for 512x512: a wider k block
# amortizes each q tile's revisits into more MXU work per program).
_TUNED = {"block_q": 512, "block_k": 1024}


def set_tuned_blocks(block_q: int, block_k: int) -> None:
    """Install measured-best block sizes (scripts/calibrate.py
    --tune-flash persists them to the calibration table; the executor
    installs them at compile when a calibration file is configured)."""
    _TUNED["block_q"] = int(block_q)
    _TUNED["block_k"] = int(block_k)


def _pick_block(pref: int, seq: int) -> Optional[int]:
    """Largest block <= pref that divides seq and is lane-aligned."""
    b = min(pref, seq)
    while b >= LANES:
        if seq % b == 0 and b % LANES == 0:
            return b
        b //= 2
    return None


def supports(sq: int, sk: int, d: int) -> bool:
    """Whether the hand-tiled kernel can run this shape (callers fall
    back to the jnp blockwise formulation otherwise)."""
    return (
        _pick_block(_TUNED["block_q"], sq) is not None
        and _pick_block(_TUNED["block_k"], sk) is not None
        and d % 8 == 0
    )


class _Cfg(NamedTuple):
    causal: bool
    sm_scale: float
    block_q: int
    block_k: int
    interpret: bool


def _below_or_on_diag(iq, block_q, ik, block_k):
    """True when k block `ik` holds at least one key visible to q block
    `iq` under a causal mask (global positions, same origin)."""
    return ik * block_k < (iq + 1) * block_q


def _causal_guard(cfg, iq, ik):
    """Decorator running the body only on visible blocks: non-causal
    visits every block; causal skips fully-masked ones (their DMAs are
    redirected by the index maps)."""

    def guard(body):
        if cfg.causal:
            pl.when(_below_or_on_diag(iq, cfg.block_q, ik, cfg.block_k))(body)
        else:
            body()

    return guard


def _mask_causal(s, cfg, iq, ik):
    """Apply the causal mask to a (block_q, block_k) score tile at block
    coordinates (iq, ik)."""
    if not cfg.causal:
        return s
    qpos = iq * cfg.block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ik * cfg.block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, _MASK)


# -- forward ----------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *, cfg, nk
):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @_causal_guard(cfg, iq, ik)
    def _body():
        q = q_ref[0, 0]  # (bq, d)
        k = k_ref[0, 0]  # (bk, d)
        v = v_ref[0, 0]  # (bk, d)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale  # (bq, bk) f32
        s = _mask_causal(s, cfg, iq, ik)
        m_prev = m_scr[:, :1]  # (bq, 1)
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)  # masked entries: exp(~-1e30) == 0
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        lnz = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (acc_scr[...] / lnz).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(lnz), lse_ref.shape[2:]
        )


def _fwd(cfg: _Cfg, q, k, v):
    """q,k,v: [b, h, s, d] -> (o [b,h,sq,d], lse [b,h,sq] f32)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq = sq // cfg.block_q
    nk = sk // cfg.block_k
    grid = (b, h, nq, nk)

    def q_map(ib, ih, iq, ik):
        return (ib, ih, iq, 0)

    def kv_map(ib, ih, iq, ik):
        if cfg.causal:
            # skipped (fully-masked) block: prefetch block 0, the first
            # one the NEXT q row-block will need
            ik = lax.select(
                _below_or_on_diag(iq, cfg.block_q, ik, cfg.block_k), ik, 0
            )
        return (ib, ih, ik, 0)

    out_shape = [
        jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        jax.ShapeDtypeStruct((b, h, sq, LANES), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg=cfg, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, cfg.block_q, d), q_map),
                pl.BlockSpec((1, 1, cfg.block_k, d), kv_map),
                pl.BlockSpec((1, 1, cfg.block_k, d), kv_map),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, cfg.block_q, d), q_map),
                pl.BlockSpec((1, 1, cfg.block_q, LANES), q_map),
            ],
            scratch_shapes=[
                pltpu.VMEM((cfg.block_q, LANES), jnp.float32),
                pltpu.VMEM((cfg.block_q, LANES), jnp.float32),
                pltpu.VMEM((cfg.block_q, d), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        compiler_params=_compiler_params(
            ("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=cfg.interpret,
    )(q, k, v)
    return o, lse[..., 0]


# -- backward ---------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, dq_scr, *, cfg, nk
):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @_causal_guard(cfg, iq, ik)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]  # (bq, 1)
        delta = dl_ref[0, 0][:, :1]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale
        s = _mask_causal(s, cfg, iq, ik)
        p = jnp.exp(s - lse)  # normalized probabilities
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * cfg.sm_scale
        dq_scr[...] += lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
    dk_ref, dv_ref, dk_scr, dv_scr, *, cfg, nq,
):
    ik = pl.program_id(2)  # kv outer
    iq = pl.program_id(3)  # q inner (accumulated)

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @_causal_guard(cfg, iq, ik)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = dl_ref[0, 0][:, :1]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale
        s = _mask_causal(s, cfg, iq, ik)
        p = jnp.exp(s - lse)  # (bq, bk)
        # dv += p^T @ do  — contract the q (sublane) dim of both
        dv_scr[...] += lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * cfg.sm_scale
        dk_scr[...] += lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


# -- custom-VJP wrapper ------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Cfg, q, k, v):
    o, _ = _fwd(cfg, q, k, v)
    return o


def _flash_fwd_rule(cfg, q, k, v):
    o, lse = _fwd(cfg, q, k, v)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(cfg, res, do):
    q, k, v, o, lse = res
    # delta_i = rowsum(dO * O) — the softmax-jacobian correction
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    return _bwd_from_delta(cfg, q, k, v, lse, do, delta)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_with_lse(cfg: _Cfg, q, k, v):
    return _fwd(cfg, q, k, v)


def _flash_with_lse_fwd(cfg, q, k, v):
    o, lse = _fwd(cfg, q, k, v)
    return (o, lse), (q, k, v, o, lse)


def _flash_with_lse_bwd(cfg, res, cts):
    """Backward of the (o, lse) pair. The lse cotangent needs no extra
    kernel: d lse / ds_j = p_j (softmax probabilities), so g_lse enters
    ds = p * (dp - delta + g_lse) — i.e. it shifts the delta correction
    stream by -g_lse. dv = p^T dO is unaffected. Ring attention's
    log-sum-exp combine produces exactly this cotangent structure."""
    q, k, v, o, lse = res
    do, dlse = cts
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ) - dlse.astype(jnp.float32)
    return _bwd_from_delta(cfg, q, k, v, lse, do, delta)


def _bwd_from_delta(cfg, q, k, v, lse, do, delta):
    """The two backward pallas_calls, parameterized by an explicit delta
    stream (shared by the plain and with-lse VJPs)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq = sq // cfg.block_q
    nk = sk // cfg.block_k
    lse_b = jnp.broadcast_to(lse[..., None], (b, h, sq, LANES))
    delta_b = jnp.broadcast_to(delta[..., None], (b, h, sq, LANES))

    def q_map(ib, ih, iq, ik):
        return (ib, ih, iq, 0)

    def kv_map(ib, ih, iq, ik):
        if cfg.causal:
            ik = lax.select(
                _below_or_on_diag(iq, cfg.block_q, ik, cfg.block_k), ik, 0
            )
        return (ib, ih, ik, 0)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg=cfg, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(b, h, nq, nk),
            in_specs=[
                pl.BlockSpec((1, 1, cfg.block_q, d), q_map),
                pl.BlockSpec((1, 1, cfg.block_k, d), kv_map),
                pl.BlockSpec((1, 1, cfg.block_k, d), kv_map),
                pl.BlockSpec((1, 1, cfg.block_q, d), q_map),
                pl.BlockSpec((1, 1, cfg.block_q, LANES), q_map),
                pl.BlockSpec((1, 1, cfg.block_q, LANES), q_map),
            ],
            out_specs=[pl.BlockSpec((1, 1, cfg.block_q, d), q_map)],
            scratch_shapes=[pltpu.VMEM((cfg.block_q, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        compiler_params=_compiler_params(
            ("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=cfg.interpret,
    )(q, k, v, do, lse_b, delta_b)[0]

    def q_map2(ib, ih, ik, iq):
        if cfg.causal:
            iq = lax.select(
                _below_or_on_diag(iq, cfg.block_q, ik, cfg.block_k),
                iq,
                lax.div(ik * cfg.block_k, cfg.block_q),
            )
        return (ib, ih, iq, 0)

    def kv_map2(ib, ih, ik, iq):
        return (ib, ih, ik, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg=cfg, nq=nq),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(b, h, nk, nq),
            in_specs=[
                pl.BlockSpec((1, 1, cfg.block_q, d), q_map2),
                pl.BlockSpec((1, 1, cfg.block_k, d), kv_map2),
                pl.BlockSpec((1, 1, cfg.block_k, d), kv_map2),
                pl.BlockSpec((1, 1, cfg.block_q, d), q_map2),
                pl.BlockSpec((1, 1, cfg.block_q, LANES), q_map2),
                pl.BlockSpec((1, 1, cfg.block_q, LANES), q_map2),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, cfg.block_k, d), kv_map2),
                pl.BlockSpec((1, 1, cfg.block_k, d), kv_map2),
            ],
            scratch_shapes=[
                pltpu.VMEM((cfg.block_k, d), jnp.float32),
                pltpu.VMEM((cfg.block_k, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        compiler_params=_compiler_params(
            ("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=cfg.interpret,
    )(q, k, v, do, lse_b, delta_b)
    return dq, dk, dv


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


# -- public API --------------------------------------------------------------


def flash_attention_tpu(
    q,
    k,
    v,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    return_lse: bool = False,
    interpret: Optional[bool] = None,
):
    """Hand-tiled flash attention. q, k, v: [b, s, h, d].

    Returns [b, s, h, d] (and, with return_lse, the row log-sum-exp
    [b, h, s] in f32 — the residual that makes per-device partial results
    mergeable, ring_attention.py). interpret=None auto-selects the Pallas
    interpreter off-TPU so the same code path is testable on CPU."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    bq = block_q or _pick_block(_TUNED["block_q"], sq)
    bk = block_k or _pick_block(_TUNED["block_k"], sk)
    if bq is None or bk is None or sq % bq or sk % bk:
        raise ValueError(
            f"flash_attention_tpu: seq ({sq}, {sk}) not tileable by "
            f"({bq}, {bk}); use supports() and fall back to blockwise"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _Cfg(causal, sm_scale, bq, bk, interpret)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if return_lse:
        o, lse = _flash_with_lse(cfg, qt, kt, vt)
        return o.transpose(0, 2, 1, 3), lse
    o = _flash(cfg, qt, kt, vt)
    return o.transpose(0, 2, 1, 3)
