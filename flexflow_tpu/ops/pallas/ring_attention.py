"""Ring attention: exact attention under sequence sharding, over ICI.

The reference has no sequence-parallel attention at all — its MHA is one
cudnnMultiHeadAttnForward per shard and the sequence dim of attention is
never partitioned by any substitution (reference: src/ops/attention.cu:35;
SURVEY §5 "no ring attention, no Ulysses, no blockwise"). This module is the
TPU-native capability upgrade: each device holds a `[b, s/N, h, d]` block of
q/k/v; key/value blocks rotate around the mesh's sequence axis with
`jax.lax.ppermute` (one ICI hop per step) while an online-softmax
accumulator folds each visiting block into the local queries' result. The
full `[s, s]` score matrix never exists and no device ever holds more than
`1/N` of the sequence.

Communication pattern: N-1 ppermute steps of the local K/V blocks
(2·b·s/N·h·d elements each) over the ring — bandwidth-optimal for exact
attention, and XLA's latency-hiding scheduler overlaps each hop with the
previous block's compute.

Differentiable as-is: `shard_map` + `ppermute` + `lax.scan` all have
transposes, so `jax.grad` of a ring-attention call yields the matching
reverse ring.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from flexflow_tpu.parallel._shardmap_compat import shard_map_unchecked


def _local_ring_attention(q, k, v, axis_name: str, n_shards: int, causal: bool):
    """Per-device body. q, k, v: local [b, s_loc, h, d] blocks."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    # bf16 inputs keep bf16 MATMUL OPERANDS (MXU-native) with f32
    # accumulation; f32 inputs stay f32 end-to-end for exactness (same
    # scheme as the blockwise kernel, flash_attention.py)
    cdt = q.dtype if q.dtype == jnp.bfloat16 else jnp.float32
    qs = (q.astype(jnp.float32) * scale).astype(cdt)
    my_idx = lax.axis_index(axis_name)
    qpos = my_idx * sq + jnp.arange(sq)  # global query positions [sq]

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    # each step sends the held K/V block to the next device on the ring;
    # after step t device i holds the block that started on (i - t) mod N
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def attend(m, l, acc, kc, vc, t):
        src = jnp.mod(my_idx - t, n_shards)
        kpos = src * sk + jnp.arange(sk)  # global key positions [sk]
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", qs, kc.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        if causal:
            mask = kpos[None, None, None, :] <= qpos[None, None, :, None]
            logits = jnp.where(mask, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(cdt), vc.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    # fold the local block first, then N-1 rotate+attend steps (permuting
    # before the attend keeps the final rotation out of the loop — no dead
    # ICI hop on the last iteration)
    m, l, acc = attend(m0, l0, acc0, k, v, 0)

    def body(carry, t):
        m, l, acc, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        m, l, acc = attend(m, l, acc, kc, vc, t)
        return (m, l, acc, kc, vc), None

    (m, l, acc, _, _), _ = lax.scan(
        body, (m, l, acc, k, v), jnp.arange(1, n_shards)
    )
    # causal rows always see at least key 0 <= qpos, so l > 0; guard anyway
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _local_ring_attention_pallas(
    q, k, v, axis_name: str, n_shards: int, causal: bool
):
    """Per-device ring body where each visiting K/V block is consumed by
    the hand-tiled Pallas flash kernel (flash_kernel.py) instead of jnp
    einsums — the local compute runs MXU-tiled with VMEM accumulators.

    Per-block partial results (o_t, lse_t) merge exactly by log-sum-exp
    algebra; causality never needs dynamic offsets inside the kernel
    because each visiting block is wholly before (visible), wholly after
    (skipped — no kernel launch, no ICI-wasting compute), or exactly the
    local diagonal block (the kernel's static causal mask)."""
    from flexflow_tpu.ops.pallas.flash_kernel import flash_attention_tpu

    b, sq, h, d = q.shape
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def flash(qq, kk, vv, diag):
        return flash_attention_tpu(
            qq, kk, vv, causal=diag, return_lse=True
        )

    def skip(qq, kk, vv):
        return (
            jnp.zeros((b, sq, h, d), qq.dtype),
            jnp.full((b, h, sq), -1e30, jnp.float32),
        )

    def attend(kc, vc, src):
        if not causal:
            return flash(q, kc, vc, False)
        return lax.cond(
            src == my_idx,
            lambda: flash(q, kc, vc, True),
            lambda: lax.cond(
                src < my_idx,
                lambda: flash(q, kc, vc, False),
                lambda: skip(q, kc, vc),
            ),
        )

    def merge(o_run, lse_run, o_t, lse_t):
        # exact combine of partial attentions over disjoint key ranges:
        # softmax(concat) = sum_i softmax_i * exp(lse_i - LSE)
        m = jnp.maximum(lse_run, lse_t)
        w_run = jnp.exp(lse_run - m)
        w_t = jnp.exp(lse_t - m)
        denom = w_run + w_t  # >= 1: the max's weight is exactly 1
        a_run = (w_run / denom).transpose(0, 2, 1)[..., None]
        a_t = (w_t / denom).transpose(0, 2, 1)[..., None]
        o = o_run * a_run + o_t.astype(jnp.float32) * a_t
        return o, m + jnp.log(denom)

    o0, lse0 = attend(k, v, my_idx)

    def body(carry, t):
        o_run, lse_run, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        src = jnp.mod(my_idx - t, n_shards)
        o_t, lse_t = attend(kc, vc, src)
        o_run, lse_run = merge(o_run, lse_run, o_t, lse_t)
        return (o_run, lse_run, kc, vc), None

    (o_run, _, _, _), _ = lax.scan(
        body,
        (o0.astype(jnp.float32), lse0, k, v),
        jnp.arange(1, n_shards),
    )
    return o_run.astype(q.dtype)


def _pallas_ok(q, k, n_shards: int) -> bool:
    from flexflow_tpu.ops.pallas.flash_kernel import supports

    if q.shape[1] % n_shards or k.shape[1] % n_shards:
        return False
    return supports(
        q.shape[1] // n_shards, k.shape[1] // n_shards, q.shape[-1]
    )


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    seq_axis: str,
    causal: bool = False,
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    use_pallas: Optional[bool] = None,
):
    """Exact attention with q/k/v sequence-sharded over `mesh[seq_axis]`.

    q, k, v: global [b, s, h, d] arrays (sequence dim sharded on `seq_axis`;
    optionally batch on `batch_axis` and heads on `head_axis`). Returns the
    attention output with the same layout as q.

    use_pallas=None (auto): on TPU, tileable per-device blocks run the
    hand-tiled flash kernel per ring step (MXU-tiled, VMEM accumulators);
    otherwise the jnp online-softmax body (which XLA still fuses well,
    and which CPU tests exercise). True forces the kernel path (the
    Pallas interpreter runs it off-TPU).
    """
    n_shards = mesh.shape[seq_axis]
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and _pallas_ok(
            q, k, n_shards
        )
    body = (
        _local_ring_attention_pallas if use_pallas else _local_ring_attention
    )
    spec = P(batch_axis, seq_axis, head_axis, None)
    # replication checking off (the scan carry mixes locally-created
    # accumulators with ring-permuted blocks) via the version-compat
    # shim: check_vma on jax >= 0.8, check_rep before
    inner = shard_map_unchecked(
        functools.partial(
            body,
            axis_name=seq_axis,
            n_shards=n_shards,
            causal=causal,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return inner(q, k, v)
