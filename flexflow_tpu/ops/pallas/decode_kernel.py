"""Hand-tiled Pallas TPU flash-decode kernels against the serving KV cache.

The serving engine's decode regime (flexflow_tpu/serving/engine.py) is
memory-bound on the KV-cache read: one (decode) or a handful (verify)
of query positions per sequence attend against up to max_len cached
rows, so the dense jnp paths in ops/attention.py pay for a full
[b, h, w, max_len] f32 score tensor — and, on the block-paged layout,
for gathering every page into a contiguous cache view first. This
module is the kernel family that fills the Pallas hook seams there,
Flash-Decoding style (Dao et al., 2023):

  * **Split-KV online softmax** — grid (batch, heads, kv_chunks) with
    the KV-chunk dim innermost ("arbitrary", i.e. sequential): each
    chunk folds an MXU `q @ k^T` score tile into running
    max / sum-exp / weighted-V accumulators held in VMEM scratch, and
    the output tile is written once on the last chunk. No score tensor
    ever exists in HBM — the same trade flash_kernel.py makes for
    training, restricted to the w-query forward (no backward: serving
    never differentiates through the cache).
  * **Length gating per chunk** — `lengths` rides in as a
    scalar-prefetch argument, so whole chunks past
    `lengths[i] + w - 1` are skipped (pl.when) and their DMAs
    redirected to chunk 0, the split-KV analog of the causal-block
    skip in flash_kernel.py.
  * **Decode is the w == 1 case of verify** — one kernel body computes
    the staircase mask `key_pos <= lengths[i] + query_offset`
    (ops/attention.verify_attention's semantics); with w = 1 the
    staircase degenerates to decode_attention's `key_pos <= lengths[i]`
    mask. Sharing the body is what keeps greedy speculative decoding
    token-identical to plain decode on the kernel path.
  * **The paged variant walks the block table** — grid
    (batch, heads, pages): the K/V BlockSpec index maps read the
    scalar-prefetched block table to DMA each logical page straight
    from the pool (PagedAttention, Kwon et al., SOSP'23), so the
    per-step contiguous gather the dense paged path pays disappears.
    Sentinel entries (num_pages) are clamped for the DMA and masked in
    the score tile, so unallocated pages are numerically inert exactly
    like the dense path's clamp-and-mask.

Tile size: the contiguous kernel's KV chunk defaults to the
v5e-calibrated 512 rows (calibration/v5e.json "decode_blocks", installed
at compile like the training kernel's flash_blocks) shrunk to the
largest sublane-aligned divisor of max_len; the paged kernel's chunk is
one page (the block table gives no contiguity beyond a page).

`supports()` gates geometry (callers fall back to the dense paths), and
`interpret=None` auto-selects the Pallas interpreter off-TPU so the
exact kernel code path runs under JAX_PLATFORMS=cpu — tier-1 tests
(tests/test_decode_kernel.py) assert parity against the dense paths
there.

Shapes at the API boundary match ops/attention.py: q [b, w, h, d],
contiguous cache [b, max_len, h, d], paged pools
[num_pages, page_size, h, d] with block_tables [b, max_pages_per_seq].

Multi-LoRA posture (serving/tenancy/adapters.py): the kernels are
adapter-oblivious by design. Per-slot LoRA deltas land OUTSIDE the
kernel seam — the QKV delta is applied before the cache row write (so
the pool already holds adapted K/V by the time a kernel reads it) and
the output delta is a post-kernel epilogue on the attention result.
Fusing the rank-r gather into the kernel body would add a second
scalar-prefetch table and a per-slot DMA for a few-percent bandwidth
term (see CostModel.adapter_delta_cost); not worth forking the kernel
family. This is why the adapter identity tests can assert bit-identical
kernel-path tokens with a pool attached but no adapters in use.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flexflow_tpu.ops.pallas import compiler_params as _compiler_params

LANES = 128
SUBLANES = 8
_MASK = -1e30  # finite mask fill: exp()=0 without inf-inf NaNs (matches
#               the dense paths' fill, so softmax numerics line up)

# modes the ServeConfig.decode_kernel toggle takes (threaded through
# engine hooks into use_kernel below)
MODES = ("auto", "pallas", "dense")

# draft widths past this don't belong to the decode regime (a verify
# step that wide is prefill-shaped; the training kernel serves it)
_MAX_W = 64

# tree-verify widths past this fall back to the dense path: the
# ancestor mask rides as a [b, w, kv] data operand, so its DMA traffic
# grows with w where the staircase was computed from two iotas in-core
_MAX_TREE_W = 32

# process-wide tuned KV-chunk rows for the contiguous kernel, overridden
# from a measured calibration table ("decode_blocks" entry, installed by
# runtime/model.py compile() like flash_kernel's flash_blocks). The
# built-in default mirrors the flash kernel's v5e-measured preference
# for wide K blocks: 512 rows is a 128 KB f32 chunk at head_dim 64 —
# small next to VMEM, wide enough to amortize the per-chunk rescale.
_TUNED = {"block_k": 512}


def set_tuned_decode_blocks(block_k: int) -> None:
    """Install the measured-best KV chunk size (calibration-table
    "decode_blocks" entry; runtime/model.py installs it at compile when
    a calibration file is configured)."""
    _TUNED["block_k"] = int(block_k)


def _pick_chunk(kv_len: int, pref: Optional[int] = None) -> Optional[int]:
    """Largest KV chunk <= pref that divides kv_len and is
    sublane-aligned (the chunk is the second-minor dim of the (bk, d)
    K tile, so 8-row granularity, not the 128-lane rule the training
    kernel's seq-minor layout needs)."""
    b = min(pref or _TUNED["block_k"], kv_len)
    while b >= SUBLANES:
        if kv_len % b == 0 and b % SUBLANES == 0:
            return b
        b -= SUBLANES
    return None


# int8 native tiles are (32, 128) sublane x lane on TPU — a quantized
# page must pack whole int8 sublanes, so the paged quant variant needs
# 32-row page alignment where fp32 needs only 8
_INT8_SUBLANES = 32


def supports(
    w: int, kv_len: int, head_dim: int, page_size: int = 0,
    kv_dtype: str = "fp32",
) -> bool:
    """Whether the kernel family takes this cache geometry. False routes
    the caller to the dense jnp paths (ops/attention.py) — the explicit
    fallback contract, like flash_kernel.supports for training shapes.

    w: query positions per sequence (1 = decode, k+1 = verify);
    kv_len: max_len of the contiguous cache; page_size > 0 checks the
    paged variant instead (its chunk is one page, so the page must be
    sublane-aligned; kv_len is ignored — the walk is table-driven).
    kv_dtype "int8" selects the quantized paged variant's gate: pages
    must pack whole (32, 128) int8 tiles, and only the paged layout
    carries the per-page scale side pools."""
    if not 1 <= w <= _MAX_W or head_dim % SUBLANES:
        return False
    if kv_dtype == "int8":
        # quantized pools exist only on the paged layout; the page must
        # be int8-sublane-aligned or the dense dequant path takes over
        return page_size > 0 and page_size % _INT8_SUBLANES == 0
    if page_size > 0:
        return page_size % SUBLANES == 0
    return kv_len >= 1 and _pick_chunk(kv_len) is not None


def use_kernel(
    mode: str, w: int, kv_len: int, head_dim: int, page_size: int = 0,
    kv_dtype: str = "fp32",
) -> bool:
    """Resolve a ServeConfig.decode_kernel mode for one geometry:
    "dense" never takes the kernel, "pallas" takes it whenever
    supports() passes (interpret mode runs it off-TPU — the CI/test
    path), "auto" additionally requires a real TPU backend (on CPU the
    dense one-query path is the measured-fast choice; interpreting the
    kernel there is a correctness tool, not a serving config)."""
    if mode not in MODES:
        raise ValueError(f"decode_kernel must be one of {MODES}, got {mode!r}")
    if mode == "dense" or not supports(
        w, kv_len, head_dim, page_size, kv_dtype=kv_dtype
    ):
        return False
    return mode == "pallas" or jax.default_backend() == "tpu"


def supports_tree(w: int) -> bool:
    """Width gate for the tree-verify kernel variants, ON TOP of the
    use_kernel()/supports() geometry gate the caller already passed:
    the tree mask is a per-(query, key) data operand, so wide trees pay
    w x the staircase's mask bandwidth — past _MAX_TREE_W the caller
    falls back to the dense tree path (ops/attention.tree_allowed_mask
    under jnp.where), the explicit fallback contract of the family."""
    return 1 <= w <= _MAX_TREE_W


class _Cfg(NamedTuple):
    w: int
    sm_scale: float
    block_k: int
    interpret: bool


def _stair_mask(s, cfg, length, k_start):
    """Apply the staircase mask to a (w, bk) score tile whose keys start
    at global cache position k_start: query row j sees key positions
    <= length + j. With w == 1 this is exactly decode_attention's
    `key_pos <= lengths[i]` mask."""
    kpos = k_start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    qoff = lax.broadcasted_iota(jnp.int32, s.shape, 0)
    return jnp.where(kpos <= length + qoff, s, _MASK)


def _online_softmax_step(s, v, m_scr, l_scr, acc_scr):
    """Fold one masked score tile (w, bk) and its V chunk (bk, d) into
    the running (m, l, acc) accumulators — the flash_kernel.py forward
    update, minus the LSE output serving never needs."""
    m_prev = m_scr[:, :1]  # (w, 1)
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)  # masked entries: exp(~-1e30) == 0
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[...] = acc_scr[...] * corr + lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)


def _finish(o_ref, l_scr, acc_scr):
    # position 0 is visible to every query row (lengths >= 0), so l > 0
    # for live rows; the max guards the padded scratch lanes
    l = jnp.maximum(l_scr[:, :1], 1e-30)
    o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)


# -- contiguous cache ---------------------------------------------------------


def _decode_kernel(
    len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, cfg, nk
):
    ib = pl.program_id(0)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[ib]

    # chunk visible iff it holds at least one key some query row sees
    @pl.when(ik * cfg.block_k <= length + (cfg.w - 1))
    def _body():
        q = q_ref[0, 0]  # (w, d)
        k = k_ref[0, 0]  # (bk, d)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale  # (w, bk) f32
        s = _stair_mask(s, cfg, length, ik * cfg.block_k)
        _online_softmax_step(s, v_ref[0, 0], m_scr, l_scr, acc_scr)

    @pl.when(ik == nk - 1)
    def _done():
        _finish(o_ref, l_scr, acc_scr)


def flash_verify(
    q,
    k_cache,
    v_cache,
    lengths,
    sm_scale: Optional[float] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """w-query flash attention against the contiguous cache with the
    staircase mask — ops/attention.verify_attention's semantics on the
    split-KV kernel. q: [b, w, h, d]; k_cache/v_cache:
    [b, max_len, h, d]; lengths: [b] int32. Returns [b, w, h, d].
    interpret=None auto-selects the Pallas interpreter off-TPU."""
    b, w, h, d = q.shape
    kv_len = k_cache.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    bk = block_k or _pick_chunk(kv_len)
    if bk is None or kv_len % bk:
        raise ValueError(
            f"flash decode: cache length {kv_len} not tileable "
            f"(chunk {bk}); use supports() and fall back to dense"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _Cfg(w, sm_scale, bk, interpret)
    nk = kv_len // bk
    qt = q.transpose(0, 2, 1, 3)  # [b, h, w, d]
    kt = k_cache.transpose(0, 2, 1, 3)
    vt = v_cache.transpose(0, 2, 1, 3)

    def q_map(ib, ih, ik, lens):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ik, lens):
        # skipped (past-length) chunk: redirect the DMA to chunk 0,
        # which the next (ib, ih) program always needs
        ik = lax.select(ik * bk <= lens[ib] + (w - 1), ik, 0)
        return (ib, ih, ik, 0)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, cfg=cfg, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, h, nk),
            in_specs=[
                pl.BlockSpec((1, 1, w, d), q_map),
                pl.BlockSpec((1, 1, bk, d), kv_map),
                pl.BlockSpec((1, 1, bk, d), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, w, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, w, d), q.dtype),
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


def flash_decode(q, k_cache, v_cache, lengths, **kw):
    """Single-query flash decode — the w == 1 case of flash_verify
    (ops/attention.decode_attention's semantics)."""
    return flash_verify(q, k_cache, v_cache, lengths, **kw)


# -- block-paged cache --------------------------------------------------------


def _paged_kernel(
    len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, cfg, num_pages, page_size, np_seq,
):
    ib = pl.program_id(0)
    ip = pl.program_id(2)

    @pl.when(ip == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[ib]

    # a page contributes iff it is inside the staircase AND allocated
    # (sentinel entries sit past the length gate whenever the engine's
    # allocator invariants hold — the table check is defensive, for
    # standalone callers handing the kernel ragged tables)
    @pl.when(
        (ip * page_size <= length + (cfg.w - 1))
        & (tbl_ref[ib, ip] < num_pages)
    )
    def _body():
        q = q_ref[0, 0]  # (w, d)
        k = k_ref[0, :, 0, :]  # (page_size, d)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale  # (w, page_size)
        s = _stair_mask(s, cfg, length, ip * page_size)
        _online_softmax_step(s, v_ref[0, :, 0, :], m_scr, l_scr, acc_scr)

    @pl.when(ip == np_seq - 1)
    def _done():
        _finish(o_ref, l_scr, acc_scr)


def paged_flash_verify(
    q,
    k_pool,
    v_pool,
    block_tables,
    lengths,
    sm_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
):
    """w-query flash attention that walks the block table page by page —
    ops/attention.paged_verify_attention's semantics with NO contiguous
    gather (the PagedAttention kernel shape). q: [b, w, h, d];
    k_pool/v_pool: [num_pages, page_size, h, d]; block_tables:
    [b, max_pages_per_seq] int32 (sentinel num_pages = unallocated);
    lengths: [b] int32. Returns [b, w, h, d].

    Rows whose VISIBLE positions point at sentinel pages return zeros
    (no page contributes), where the dense path softmaxes over the
    clamped page's stale rows instead. Both only happens for dead
    slots — the engine allocates every page inside a live slot's
    lengths + w before the step, so live rows agree exactly — and dead
    rows' outputs are discarded by the scheduler either way."""
    b, w, h, d = q.shape
    num_pages, page_size = k_pool.shape[0], k_pool.shape[1]
    np_seq = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if page_size % SUBLANES:
        raise ValueError(
            f"paged flash decode: page_size {page_size} is not "
            f"sublane-aligned ({SUBLANES}); use supports() and fall "
            "back to dense"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _Cfg(w, sm_scale, page_size, interpret)
    qt = q.transpose(0, 2, 1, 3)  # [b, h, w, d]

    def q_map(ib, ih, ip, lens, tbl):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ip, lens, tbl):
        # skipped pages prefetch the sequence's first page; sentinel
        # entries clamp to a real page (their scores are masked)
        ip = lax.select(ip * page_size <= lens[ib] + (w - 1), ip, 0)
        page = jnp.minimum(tbl[ib, ip], num_pages - 1)
        return (page, 0, ih, 0)

    out = pl.pallas_call(
        functools.partial(
            _paged_kernel,
            cfg=cfg,
            num_pages=num_pages,
            page_size=page_size,
            np_seq=np_seq,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, np_seq),
            in_specs=[
                pl.BlockSpec((1, 1, w, d), q_map),
                pl.BlockSpec((1, page_size, 1, d), kv_map),
                pl.BlockSpec((1, page_size, 1, d), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, w, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, w, d), q.dtype),
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        qt,
        k_pool,
        v_pool,
    )
    return out.transpose(0, 2, 1, 3)


def paged_flash_decode(q, k_pool, v_pool, block_tables, lengths, **kw):
    """Single-query paged flash decode — the w == 1 case of
    paged_flash_verify (ops/attention.paged_decode_attention's
    semantics)."""
    return paged_flash_verify(q, k_pool, v_pool, block_tables, lengths, **kw)


# -- int8-quantized block-paged cache -----------------------------------------


def _paged_kernel_quant(
    len_ref, tbl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
    m_scr, l_scr, acc_scr, *, cfg, num_pages, page_size, np_seq,
):
    """_paged_kernel with fused per-page dequant: the K/V tiles arrive
    int8 and the (1, 1) scale tiles — one fp32 scalar per (page, head),
    DMA'd through the same table-driven index map — multiply them back
    to fp32 INSIDE the chunk loop, so no dequantized cache view ever
    exists outside VMEM."""
    ib = pl.program_id(0)
    ip = pl.program_id(2)

    @pl.when(ip == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[ib]

    @pl.when(
        (ip * page_size <= length + (cfg.w - 1))
        & (tbl_ref[ib, ip] < num_pages)
    )
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)  # (w, d)
        k = k_ref[0, :, 0, :].astype(jnp.float32) * ks_ref[0, 0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale  # (w, page_size)
        s = _stair_mask(s, cfg, length, ip * page_size)
        v = v_ref[0, :, 0, :].astype(jnp.float32) * vs_ref[0, 0]
        _online_softmax_step(s, v, m_scr, l_scr, acc_scr)

    @pl.when(ip == np_seq - 1)
    def _done():
        _finish(o_ref, l_scr, acc_scr)


def paged_flash_verify_quant(
    q,
    k_pool,
    v_pool,
    k_scale,
    v_scale,
    block_tables,
    lengths,
    sm_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
):
    """paged_flash_verify over int8 pools with fp32 per-(page, head)
    scale side pools [num_pages, h]: dequant fuses into the page walk
    (each page's scale rides the same scalar-prefetched table lookup as
    its K/V tile). Semantics match paged_verify_attention's dense
    dequant path bit-for-bit on the visible positions."""
    b, w, h, d = q.shape
    num_pages, page_size = k_pool.shape[0], k_pool.shape[1]
    np_seq = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if page_size % _INT8_SUBLANES:
        raise ValueError(
            f"paged flash decode (int8): page_size {page_size} is not "
            f"int8-sublane-aligned ({_INT8_SUBLANES}); use supports() "
            "and fall back to dense"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _Cfg(w, sm_scale, page_size, interpret)
    qt = q.transpose(0, 2, 1, 3)  # [b, h, w, d]

    def q_map(ib, ih, ip, lens, tbl):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ip, lens, tbl):
        ip = lax.select(ip * page_size <= lens[ib] + (w - 1), ip, 0)
        page = jnp.minimum(tbl[ib, ip], num_pages - 1)
        return (page, 0, ih, 0)

    def scale_map(ib, ih, ip, lens, tbl):
        ip = lax.select(ip * page_size <= lens[ib] + (w - 1), ip, 0)
        page = jnp.minimum(tbl[ib, ip], num_pages - 1)
        return (page, ih)

    out = pl.pallas_call(
        functools.partial(
            _paged_kernel_quant,
            cfg=cfg,
            num_pages=num_pages,
            page_size=page_size,
            np_seq=np_seq,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, np_seq),
            in_specs=[
                pl.BlockSpec((1, 1, w, d), q_map),
                pl.BlockSpec((1, page_size, 1, d), kv_map),
                pl.BlockSpec((1, page_size, 1, d), kv_map),
                pl.BlockSpec((1, 1), scale_map),
                pl.BlockSpec((1, 1), scale_map),
            ],
            out_specs=pl.BlockSpec((1, 1, w, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, w, d), q.dtype),
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        qt,
        k_pool,
        v_pool,
        k_scale.astype(jnp.float32),
        v_scale.astype(jnp.float32),
    )
    return out.transpose(0, 2, 1, 3)


def paged_flash_decode_quant(
    q, k_pool, v_pool, k_scale, v_scale, block_tables, lengths, **kw
):
    """Single-query int8 paged flash decode — the w == 1 case of
    paged_flash_verify_quant."""
    return paged_flash_verify_quant(
        q, k_pool, v_pool, k_scale, v_scale, block_tables, lengths, **kw
    )


# -- token-tree verify (SpecInfer ancestor mask as a data operand) ------------
#
# The tree variants replace the iota-computed staircase with a
# precomputed [b, w, kv] visibility mask (ops/attention.tree_allowed_mask)
# DMA'd chunk by chunk alongside K — the tree SHAPE is data, so one
# compiled program serves every tree of width w and a future fused
# draft+verify device round can rewrite the tree without recompiling.
# Everything else (online softmax, chunk-skip gate, sentinel clamping)
# is the staircase kernel verbatim: the chunk gate
# `ik * bk <= length + (w - 1)` still holds because every tree row lives
# inside the w-row window at positions lengths..lengths + w - 1.


def _tree_kernel(
    len_ref, q_ref, k_ref, v_ref, mask_ref, o_ref, m_scr, l_scr, acc_scr,
    *, cfg, nk,
):
    ib = pl.program_id(0)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[ib]

    @pl.when(ik * cfg.block_k <= length + (cfg.w - 1))
    def _body():
        q = q_ref[0, 0]  # (w, d)
        k = k_ref[0, 0]  # (bk, d)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale  # (w, bk) f32
        s = jnp.where(mask_ref[0] > 0.0, s, _MASK)
        _online_softmax_step(s, v_ref[0, 0], m_scr, l_scr, acc_scr)

    @pl.when(ik == nk - 1)
    def _done():
        _finish(o_ref, l_scr, acc_scr)


def flash_verify_tree(
    q,
    k_cache,
    v_cache,
    lengths,
    allowed,
    sm_scale: Optional[float] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """w-query flash attention against the contiguous cache under an
    arbitrary tree-ancestor mask — ops/attention.verify_attention's
    tree_parents semantics on the split-KV kernel. allowed:
    [b, w, max_len] float32, 1.0 where query row j may see the key
    position (tree_allowed_mask over the dispatch's parent table).
    Other shapes as flash_verify. Gate with supports() AND
    supports_tree() before calling."""
    b, w, h, d = q.shape
    kv_len = k_cache.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    bk = block_k or _pick_chunk(kv_len)
    if bk is None or kv_len % bk:
        raise ValueError(
            f"flash decode: cache length {kv_len} not tileable "
            f"(chunk {bk}); use supports() and fall back to dense"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _Cfg(w, sm_scale, bk, interpret)
    nk = kv_len // bk
    qt = q.transpose(0, 2, 1, 3)  # [b, h, w, d]
    kt = k_cache.transpose(0, 2, 1, 3)
    vt = v_cache.transpose(0, 2, 1, 3)

    def q_map(ib, ih, ik, lens):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ik, lens):
        ik = lax.select(ik * bk <= lens[ib] + (w - 1), ik, 0)
        return (ib, ih, ik, 0)

    def mask_map(ib, ih, ik, lens):
        # the mask tile follows K's chunk redirect so a skipped chunk's
        # DMA still lands on resident rows
        ik = lax.select(ik * bk <= lens[ib] + (w - 1), ik, 0)
        return (ib, 0, ik)

    out = pl.pallas_call(
        functools.partial(_tree_kernel, cfg=cfg, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, h, nk),
            in_specs=[
                pl.BlockSpec((1, 1, w, d), q_map),
                pl.BlockSpec((1, 1, bk, d), kv_map),
                pl.BlockSpec((1, 1, bk, d), kv_map),
                pl.BlockSpec((1, w, bk), mask_map),
            ],
            out_specs=pl.BlockSpec((1, 1, w, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, w, d), q.dtype),
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qt, kt, vt, allowed.astype(jnp.float32))
    return out.transpose(0, 2, 1, 3)


def _paged_tree_kernel(
    len_ref, tbl_ref, q_ref, k_ref, v_ref, mask_ref, o_ref,
    m_scr, l_scr, acc_scr, *, cfg, num_pages, page_size, np_seq,
):
    ib = pl.program_id(0)
    ip = pl.program_id(2)

    @pl.when(ip == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[ib]

    @pl.when(
        (ip * page_size <= length + (cfg.w - 1))
        & (tbl_ref[ib, ip] < num_pages)
    )
    def _body():
        q = q_ref[0, 0]  # (w, d)
        k = k_ref[0, :, 0, :]  # (page_size, d)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale  # (w, page_size)
        s = jnp.where(mask_ref[0] > 0.0, s, _MASK)
        _online_softmax_step(s, v_ref[0, :, 0, :], m_scr, l_scr, acc_scr)

    @pl.when(ip == np_seq - 1)
    def _done():
        _finish(o_ref, l_scr, acc_scr)


def paged_flash_verify_tree(
    q,
    k_pool,
    v_pool,
    block_tables,
    lengths,
    allowed,
    sm_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
):
    """Tree-masked w-query flash attention walking the block table —
    ops/attention.paged_verify_attention's tree_parents semantics with
    no contiguous gather. allowed: [b, w, max_pages_per_seq * page_size]
    float32 over LOGICAL positions, so its index map is just the page
    index — no table lookup, no redirect needed (every logical tile is
    resident). Other shapes as paged_flash_verify."""
    b, w, h, d = q.shape
    num_pages, page_size = k_pool.shape[0], k_pool.shape[1]
    np_seq = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if page_size % SUBLANES:
        raise ValueError(
            f"paged flash decode: page_size {page_size} is not "
            f"sublane-aligned ({SUBLANES}); use supports() and fall "
            "back to dense"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _Cfg(w, sm_scale, page_size, interpret)
    qt = q.transpose(0, 2, 1, 3)  # [b, h, w, d]

    def q_map(ib, ih, ip, lens, tbl):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ip, lens, tbl):
        ip = lax.select(ip * page_size <= lens[ib] + (w - 1), ip, 0)
        page = jnp.minimum(tbl[ib, ip], num_pages - 1)
        return (page, 0, ih, 0)

    def mask_map(ib, ih, ip, lens, tbl):
        return (ib, 0, ip)

    out = pl.pallas_call(
        functools.partial(
            _paged_tree_kernel,
            cfg=cfg,
            num_pages=num_pages,
            page_size=page_size,
            np_seq=np_seq,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, np_seq),
            in_specs=[
                pl.BlockSpec((1, 1, w, d), q_map),
                pl.BlockSpec((1, page_size, 1, d), kv_map),
                pl.BlockSpec((1, page_size, 1, d), kv_map),
                pl.BlockSpec((1, w, page_size), mask_map),
            ],
            out_specs=pl.BlockSpec((1, 1, w, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, w, d), q.dtype),
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        qt,
        k_pool,
        v_pool,
        allowed.astype(jnp.float32),
    )
    return out.transpose(0, 2, 1, 3)


def _paged_tree_kernel_quant(
    len_ref, tbl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, mask_ref,
    o_ref, m_scr, l_scr, acc_scr, *, cfg, num_pages, page_size, np_seq,
):
    """_paged_tree_kernel with the fused per-page dequant of
    _paged_kernel_quant — the int8 member of the tree family."""
    ib = pl.program_id(0)
    ip = pl.program_id(2)

    @pl.when(ip == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[ib]

    @pl.when(
        (ip * page_size <= length + (cfg.w - 1))
        & (tbl_ref[ib, ip] < num_pages)
    )
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)  # (w, d)
        k = k_ref[0, :, 0, :].astype(jnp.float32) * ks_ref[0, 0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.sm_scale  # (w, page_size)
        s = jnp.where(mask_ref[0] > 0.0, s, _MASK)
        v = v_ref[0, :, 0, :].astype(jnp.float32) * vs_ref[0, 0]
        _online_softmax_step(s, v, m_scr, l_scr, acc_scr)

    @pl.when(ip == np_seq - 1)
    def _done():
        _finish(o_ref, l_scr, acc_scr)


def paged_flash_verify_tree_quant(
    q,
    k_pool,
    v_pool,
    k_scale,
    v_scale,
    block_tables,
    lengths,
    allowed,
    sm_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
):
    """paged_flash_verify_tree over int8 pools with fp32 per-(page,
    head) scale side pools — dequant fuses into the page walk exactly
    as in paged_flash_verify_quant, the tree mask rides as in
    paged_flash_verify_tree."""
    b, w, h, d = q.shape
    num_pages, page_size = k_pool.shape[0], k_pool.shape[1]
    np_seq = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if page_size % _INT8_SUBLANES:
        raise ValueError(
            f"paged flash decode (int8): page_size {page_size} is not "
            f"int8-sublane-aligned ({_INT8_SUBLANES}); use supports() "
            "and fall back to dense"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _Cfg(w, sm_scale, page_size, interpret)
    qt = q.transpose(0, 2, 1, 3)  # [b, h, w, d]

    def q_map(ib, ih, ip, lens, tbl):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ip, lens, tbl):
        ip = lax.select(ip * page_size <= lens[ib] + (w - 1), ip, 0)
        page = jnp.minimum(tbl[ib, ip], num_pages - 1)
        return (page, 0, ih, 0)

    def scale_map(ib, ih, ip, lens, tbl):
        ip = lax.select(ip * page_size <= lens[ib] + (w - 1), ip, 0)
        page = jnp.minimum(tbl[ib, ip], num_pages - 1)
        return (page, ih)

    def mask_map(ib, ih, ip, lens, tbl):
        return (ib, 0, ip)

    out = pl.pallas_call(
        functools.partial(
            _paged_tree_kernel_quant,
            cfg=cfg,
            num_pages=num_pages,
            page_size=page_size,
            np_seq=np_seq,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, np_seq),
            in_specs=[
                pl.BlockSpec((1, 1, w, d), q_map),
                pl.BlockSpec((1, page_size, 1, d), kv_map),
                pl.BlockSpec((1, page_size, 1, d), kv_map),
                pl.BlockSpec((1, 1), scale_map),
                pl.BlockSpec((1, 1), scale_map),
                pl.BlockSpec((1, w, page_size), mask_map),
            ],
            out_specs=pl.BlockSpec((1, 1, w, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, LANES), jnp.float32),
                pltpu.VMEM((w, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, w, d), q.dtype),
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        qt,
        k_pool,
        v_pool,
        k_scale.astype(jnp.float32),
        v_scale.astype(jnp.float32),
        allowed.astype(jnp.float32),
    )
    return out.transpose(0, 2, 1, 3)
