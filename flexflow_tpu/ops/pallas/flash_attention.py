"""Flash attention for TPU.

The reference's attention is one cudnnMultiHeadAttnForward call per shard
(reference: src/ops/attention.cu:35) with no long-context story (SURVEY §5
"no ring attention, no blockwise"). This module provides the TPU-native
upgrade: blockwise-tiled attention that never materializes the [s, s] score
matrix, written with Pallas when running on TPU.

Three lowerings, selected by `use_lib` / shape support:
  * the hand-tiled Pallas kernel (flash_kernel.py — VMEM accumulators,
    custom-VJP backward, lse output for ring merging) on TPU;
  * the library `jax.experimental.pallas.ops.tpu.flash_attention` kernel,
    kept as an A/B reference;
  * the jnp blockwise formulation (online-softmax over key blocks via
    lax.scan, fp32 accumulators) as the portable fallback — CPU tests and
    shapes the tiled kernels cannot take.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _blockwise_attention(q, k, v, causal: bool, block_k: int):
    """Online-softmax attention over key blocks. q,k,v: [b, s, h, d]."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    nk = (sk + block_k - 1) // block_k
    pad = nk * block_k - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    scale = 1.0 / math.sqrt(d)
    # bf16 inputs keep bf16 MATMUL OPERANDS (MXU-native) with f32
    # accumulation; f32 inputs stay f32 end-to-end for exactness
    cdt = q.dtype if q.dtype == jnp.bfloat16 else jnp.float32
    qs = (q.astype(jnp.float32) * scale).astype(cdt)
    kb = k.reshape(b, nk, block_k, h, d).astype(cdt)
    vb = v.reshape(b, nk, block_k, h, d).astype(cdt)
    kpos = jnp.arange(nk * block_k).reshape(nk, block_k)
    qpos = jnp.arange(sq)

    def body(carry, blk):
        m, l, acc = carry
        kblk, vblk, kp = blk
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", qs, kblk,
            preferred_element_type=jnp.float32,
        )
        mask = kp[None, None, None, :] < sk
        if causal:
            mask = mask & (kp[None, None, None, :] <= qpos[None, None, :, None])
        logits = jnp.where(mask, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        # guard fully-masked rows (m_new == -inf)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        correction = jnp.where(jnp.isfinite(m), correction, 0.0)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(cdt), vblk,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body,
        (m0, l0, acc0),
        (
            jnp.moveaxis(kb, 1, 0),
            jnp.moveaxis(vb, 1, 0),
            kpos,
        ),
    )
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _lib_flash(q, k, v, causal: bool):
    """The public JAX Pallas TPU flash kernel ([b, h, s, d] layout) — a
    hand-written fwd+bwd that beats the autodiff'd blockwise scan at long
    sequence (measured on v5e, BENCH_LONGCTX.json: fwd+bwd 60 vs 75 ms at
    seq 8192, and it compiles at 16384 where the scan formulation does
    not)."""
    import math as _math

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as pl_flash,
    )

    o = pl_flash(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=1.0 / _math.sqrt(q.shape[-1]),
    )
    return o.transpose(0, 2, 1, 3)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_k", "use_lib")
)
def flash_attention(
    q, k, v, causal: bool = False, block_k: int = 512, use_lib=None
):
    """q, k, v: [batch, seq, heads, head_dim] -> [batch, seq, heads, head_dim].

    use_lib=None ("auto"): on SINGLE-device TPU the hand-tiled kernel
    (flash_kernel.py) runs when the shape tiles, with the library Pallas
    kernel as the shape fallback (use_lib="library" forces it for A/B).
    Under a multi-device mesh an opaque pallas custom call inside plain
    jit has no GSPMD partitioning rule, so callers either wrap the tiled
    kernel in shard_map themselves (ring/Ulysses, ops/attention.py) or
    pass use_lib=False for the jnp blockwise formulation, which XLA
    shards cleanly over batch/heads. `block_k` tunes only the blockwise
    path; the tiled kernels use their own (calibratable) block sizes."""
    if use_lib is None:
        use_lib = (
            jax.default_backend() == "tpu" and jax.device_count() == 1
        )
    if use_lib:
        from flexflow_tpu.ops.pallas.flash_kernel import (
            flash_attention_tpu,
            supports,
        )

        if use_lib != "library" and supports(
            q.shape[1], k.shape[1], q.shape[-1]
        ):
            return flash_attention_tpu(q, k, v, causal=causal)
        try:
            return _lib_flash(q, k, v, causal)
        except Exception:  # noqa: BLE001 — trace-time shape/support errors
            pass
    return _blockwise_attention(q, k, v, causal, block_k)
