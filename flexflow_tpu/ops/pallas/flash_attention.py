"""Flash attention for TPU.

The reference's attention is one cudnnMultiHeadAttnForward call per shard
(reference: src/ops/attention.cu:35) with no long-context story (SURVEY §5
"no ring attention, no blockwise"). This module provides the TPU-native
upgrade: blockwise-tiled attention that never materializes the [s, s] score
matrix, written with Pallas when running on TPU.

Current status: the jnp blockwise formulation below is numerically exact
(online-softmax over key blocks via lax.scan, fp32 accumulators) and XLA
compiles it into a fused streaming loop; a hand-tiled Pallas kernel can
replace `_blockwise_attention` without changing callers.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _blockwise_attention(q, k, v, causal: bool, block_k: int):
    """Online-softmax attention over key blocks. q,k,v: [b, s, h, d]."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    nk = (sk + block_k - 1) // block_k
    pad = nk * block_k - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    scale = 1.0 / math.sqrt(d)
    q32 = q.astype(jnp.float32) * scale
    kb = k.reshape(b, nk, block_k, h, d).astype(jnp.float32)
    vb = v.reshape(b, nk, block_k, h, d).astype(jnp.float32)
    kpos = jnp.arange(nk * block_k).reshape(nk, block_k)
    qpos = jnp.arange(sq)

    def body(carry, blk):
        m, l, acc = carry
        kblk, vblk, kp = blk
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32, kblk)
        mask = kp[None, None, None, :] < sk
        if causal:
            mask = mask & (kp[None, None, None, :] <= qpos[None, None, :, None])
        logits = jnp.where(mask, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        # guard fully-masked rows (m_new == -inf)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        correction = jnp.where(jnp.isfinite(m), correction, 0.0)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vblk
        )
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body,
        (m0, l0, acc0),
        (
            jnp.moveaxis(kb, 1, 0),
            jnp.moveaxis(vb, 1, 0),
            kpos,
        ),
    )
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_k"))
def flash_attention(q, k, v, causal: bool = False, block_k: int = 512):
    """q, k, v: [batch, seq, heads, head_dim] -> [batch, seq, heads, head_dim]."""
    return _blockwise_attention(q, k, v, causal, block_k)
