"""Hand-tiled Pallas TPU kernels (training flash attention, ring
attention, serving decode/verify kernels)."""


def compiler_params(dimension_semantics):
    """pltpu compiler params across jax versions: newer jax spells the
    class `CompilerParams`, 0.4.x spells it `TPUCompilerParams` — the
    kernels only ever pass dimension_semantics, so one shim covers
    both."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(dimension_semantics=tuple(dimension_semantics))
