"""Operator registry.

Each OperatorType registers:
  * `infer`  — parallel-shape inference: (input shapes, params) ->
               (output shapes, weight shapes). Degree-aware: it propagates
               input partitioning to outputs the way the reference's
               ParallelDimMappingRecord solver does (reference:
               model.cc:494-647), and raises if an illegal dim is
               partitioned (e.g. the reduction dim of a Linear without a
               Reduction parallel op downstream).
  * `lower`  — returns a pure function over *global logical* jnp arrays:
               fn(inputs, weights, ctx) -> outputs. GSPMD handles the
               distribution; sharding constraints are applied by the
               executor, not here.
  * `flops`  — analytic forward-FLOP estimate for the simulator.

The reference implements these as per-op C++ classes with
init/forward/backward Legion tasks (reference: include/flexflow/operator.h:51,
operator.h:187-193); here backward is `jax.grad` of the lowered function, so
only the forward lowering exists.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.core.parallel_tensor import ParallelTensorShape
from flexflow_tpu.core.types import OperatorType


@dataclasses.dataclass
class LowerCtx:
    """Execution context threaded through lowered ops."""

    train: bool = True
    rng: object = None  # jax PRNG key or None
    seq_length: Optional[int] = None  # reference: FFIterationConfig.seq_length
    # distribution context: ops whose lowering is sharding-aware (ring
    # attention under a partitioned sequence dim) read the mesh and the
    # node's parallel shapes; plain ops ignore these.
    mesh: object = None  # jax.sharding.Mesh or None
    axis_names: Tuple[str, ...] = ()
    in_shapes: Optional[Sequence[ParallelTensorShape]] = None
    # bf16 matmul operands with f32 accumulation — the MXU-native analog of
    # the reference's --allow-tensor-op-math-conversion (TF32/FP16 tensor
    # cores, model.cc:3668); set from FFConfig.allow_mixed_precision.
    bf16_matmul: bool = False


def mm_operands(ctx, *arrays):
    """Cast f32 matmul operands to bf16 when mixed precision is on.

    Accumulation stays f32 (every call site passes
    preferred_element_type=f32), so this trades mantissa bits on the
    operands for the MXU's native bf16 throughput."""
    if ctx is not None and getattr(ctx, "bf16_matmul", False):
        import jax.numpy as jnp

        return tuple(
            a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a
            for a in arrays
        )
    return arrays


def mm_out_dtype(ctx, default_dtype):
    """Matmul OUTPUT dtype: bf16 when mixed precision is on, else the
    weight/input dtype. Keeping activations bf16 between ops halves the
    HBM traffic of every layer boundary (weights stay f32 master copies;
    the operand-cast VJP returns f32 gradients). The loss upcasts logits
    to f32 (runtime/loss.py), so training numerics stay AMP-standard."""
    if ctx is not None and getattr(ctx, "bf16_matmul", False):
        import jax.numpy as jnp

        return jnp.bfloat16
    return default_dtype


@dataclasses.dataclass
class OpDef:
    op_type: OperatorType
    infer: Callable[
        [Sequence[ParallelTensorShape], dict],
        Tuple[Tuple[ParallelTensorShape, ...], Tuple[ParallelTensorShape, ...]],
    ]
    lower: Callable[[dict], Callable]
    flops: Callable[[Sequence[ParallelTensorShape], dict], float] = None
    # dims of each input that may legally carry partitioning through this op
    # without a parallel-op rewrite; None = all dims partitionable.
    partitionable_dims: Optional[Callable] = None


_REGISTRY: Dict[OperatorType, OpDef] = {}


def register_op(
    op_type: OperatorType,
    infer,
    lower,
    flops=None,
):
    _REGISTRY[op_type] = OpDef(op_type, infer, lower, flops or (lambda s, p: 0.0))


def get_op_def(op_type: OperatorType) -> OpDef:
    if op_type not in _REGISTRY:
        raise KeyError(f"no OpDef registered for {op_type}")
    return _REGISTRY[op_type]


def has_op_def(op_type: OperatorType) -> bool:
    return op_type in _REGISTRY


def infer_shapes(op_type, input_shapes, params):
    return get_op_def(op_type).infer(input_shapes, params)


def lower_op(op_type, params) -> Callable:
    return get_op_def(op_type).lower(params)


def op_flops(op_type, input_shapes, params) -> float:
    return get_op_def(op_type).flops(input_shapes, params)


def _ensure_registered():
    """Import op implementation modules for their registration side effects."""
    from flexflow_tpu.ops import core_ops  # noqa: F401
    from flexflow_tpu.ops import attention  # noqa: F401
    from flexflow_tpu.ops import moe  # noqa: F401
    from flexflow_tpu.parallel import parallel_ops  # noqa: F401
