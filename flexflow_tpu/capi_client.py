"""Alternative Python binding: a ctypes client over the C ABI.

The reference ships TWO Python bindings over one C API — cffi
(`python/flexflow/core/flexflow_cffi.py`) and pybind11
(`python/bindings.cc`), selected by FF_USE_CFFI
(`flexflow/config.py:19-30`). This module is the rebuild's second
binding: instead of importing `flexflow_tpu` directly, it loads
`libflexflow_c` (native/src/flexflow_c.cc) with ctypes and drives the
same flat `flexflow_*` handle API a C program uses — proving the C ABI
is complete enough to host a full Python client, and exercising it from
Python tests without a C toolchain at test time (the library embeds
CPython; inside an already-running interpreter `Py_IsInitialized()` is
true and the host interpreter is reused).

    from flexflow_tpu.capi_client import CModel
    m = CModel(batch_size=64)
    x = m.tensor([64, 32], name="x")
    t = m.dense(x, 64, activation="relu")
    m.dense(t, 4)
    m.compile(loss="sparse_categorical_crossentropy", lr=0.05)
    loss = m.fit(X, y, epochs=2)
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence

import numpy as np

_ACTIVATIONS = {None: 0, "none": 0, "relu": 1, "sigmoid": 2, "tanh": 3, "gelu": 4}
_DTYPES = {"float32": 0, "int32": 1, "int64": 2}

_LIB: Optional[ctypes.CDLL] = None


def _lib_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # the wheel ships only libffnative.so; the C API lib stays a
    # `make -C native capi` target (setup.py), so a source checkout is
    # the one supported location
    path = os.path.join(root, "native", "build", "libflexflow_c.so")
    if os.path.exists(path):
        return path
    raise FileNotFoundError(
        f"libflexflow_c.so not found at {path}; build it with "
        "`make -C native capi`"
    )


def load_library() -> ctypes.CDLL:
    """Load + initialize libflexflow_c once per process.

    PyDLL, not CDLL: every flexflow_* entry point runs CPython API calls
    (the library embeds the interpreter; in-process it reuses ours), so
    the GIL must stay HELD across the foreign call — CDLL would release
    it and the first Py* call inside would segfault."""
    global _LIB
    if _LIB is not None:
        return _LIB
    lib = ctypes.PyDLL(_lib_path())
    for destroy in (
        "flexflow_model_destroy",
        "flexflow_config_destroy",
        "flexflow_tensor_destroy",
    ):
        getattr(lib, destroy).restype = None
        getattr(lib, destroy).argtypes = [ctypes.c_void_p]
    lib.flexflow_init.restype = ctypes.c_int
    lib.flexflow_init.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p),
    ]
    lib.flexflow_config_create.restype = ctypes.c_void_p
    lib.flexflow_config_create.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p),
    ]
    lib.flexflow_model_create.restype = ctypes.c_void_p
    lib.flexflow_model_create.argtypes = [ctypes.c_void_p]
    lib.flexflow_tensor_create_ex.restype = ctypes.c_void_p
    lib.flexflow_tensor_create_ex.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
        ctypes.c_char_p,
    ]
    lib.flexflow_model_add_dense.restype = ctypes.c_void_p
    lib.flexflow_model_add_dense.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.flexflow_model_add_embedding_ex.restype = ctypes.c_void_p
    lib.flexflow_model_add_embedding_ex.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_void_p,
    ]
    lib.flexflow_model_compile.restype = ctypes.c_int
    lib.flexflow_model_compile.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_double,
    ]
    lib.flexflow_model_fit.restype = ctypes.c_double
    lib.flexflow_model_fit.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    import sys as _sys

    # flexflow_init runs sys.path.insert(0, os.getcwd()) for the
    # embedded-interpreter case; in-process that is a process-wide
    # import-resolution mutation — undo it if it was not there before
    before = list(_sys.path)
    rc = lib.flexflow_init(0, None)
    if rc != 0:
        raise RuntimeError("flexflow_init failed")
    if _sys.path != before and _sys.path[1:] == before:
        _sys.path.pop(0)
    _LIB = lib
    return lib


def _argv(args: Sequence[str]):
    arr = (ctypes.c_char_p * (len(args) or 1))()
    for i, a in enumerate(args):
        arr[i] = a.encode()
    return len(args), arr


class CModel:
    """Minimal FFModel mirror over the C ABI (the cffi-binding analog,
    reference: flexflow_cffi.py:815 FFModel)."""

    def __init__(self, batch_size: int = 64, extra_args: Sequence[str] = ()):
        # initialize handle slots BEFORE any C call: a failing create must
        # leave close()/__del__ able to release what was allocated
        self.config = None
        self.model = None
        self._tensors = []
        self.lib = load_library()
        argc, argv = _argv(["capi_client", "-b", str(batch_size), *extra_args])
        self.config = self.lib.flexflow_config_create(argc, argv)
        if not self.config:
            raise RuntimeError("flexflow_config_create failed")
        self.model = self.lib.flexflow_model_create(self.config)
        if not self.model:
            raise RuntimeError("flexflow_model_create failed")

    def close(self):
        """Release the C handles (each is a new PyObject reference owned
        by this client; a sweep building many CModels would otherwise
        leak every model/config/tensor)."""
        for t in self._tensors:
            self.lib.flexflow_tensor_destroy(t)
        self._tensors = []
        if self.model:
            self.lib.flexflow_model_destroy(self.model)
            self.model = None
        if self.config:
            self.lib.flexflow_config_destroy(self.config)
            self.config = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # interpreter teardown: lib may be gone
            pass

    def tensor(self, dims, dtype: str = "float32", name: Optional[str] = None):
        arr = (ctypes.c_int * len(dims))(*dims)
        t = self.lib.flexflow_tensor_create_ex(
            self.model,
            len(dims),
            arr,
            _DTYPES[dtype],
            None if name is None else name.encode(),
        )
        if not t:
            raise RuntimeError("tensor_create failed")
        self._tensors.append(t)
        return t

    def dense(self, x, out_features: int, activation=None, use_bias=True):
        t = self.lib.flexflow_model_add_dense(
            self.model,
            x,
            out_features,
            _ACTIVATIONS[activation],
            int(use_bias),
        )
        if not t:
            raise RuntimeError("add_dense failed")
        self._tensors.append(t)
        return t

    def embedding(self, ids, num_entries: int, out_dim: int, aggr: int = 1):
        t = self.lib.flexflow_model_add_embedding_ex(
            self.model, ids, num_entries, out_dim, aggr, None
        )
        if not t:
            raise RuntimeError("add_embedding failed")
        self._tensors.append(t)
        return t

    def compile(
        self,
        loss: str = "sparse_categorical_crossentropy",
        metrics: str = "accuracy",
        lr: float = 0.01,
    ):
        rc = self.lib.flexflow_model_compile(
            self.model, loss.encode(), metrics.encode(), lr
        )
        if rc != 0:
            raise RuntimeError("compile failed")

    def fit(self, x: np.ndarray, y: np.ndarray, epochs: int = 1) -> float:
        x = np.ascontiguousarray(x, np.float32)
        y_is_int = np.issubdtype(y.dtype, np.integer)
        y = np.ascontiguousarray(y, np.int32 if y_is_int else np.float32)
        xs = (ctypes.c_int64 * x.ndim)(*x.shape)
        ys = (ctypes.c_int64 * y.ndim)(*y.shape)
        loss = self.lib.flexflow_model_fit(
            self.model,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            xs,
            x.ndim,
            y.ctypes.data_as(ctypes.c_void_p),
            ys,
            y.ndim,
            int(y_is_int),
            epochs,
        )
        if loss != loss:  # NaN
            raise RuntimeError("fit failed")
        return float(loss)
