"""Model zoo: builders for the reference's example workloads
(reference: SURVEY §2.8, examples/cpp/* and examples/python/*).

Each builder takes an FFModel + config kwargs, adds layers, and returns the
logits Tensor; compilation/training stays with the caller (the examples/
scripts and bench.py)."""

from flexflow_tpu.models.vision import (
    build_alexnet,
    build_inception_v3,
    build_resnet50,
    build_resnext50,
)
from flexflow_tpu.models.nlp import (
    build_bert_proxy,
    build_decoder_lm,
    build_mt5_encoder,
    build_transformer_encoder,
)
from flexflow_tpu.models.recommender import build_candle_uno, build_dlrm, build_xdl
from flexflow_tpu.models.mixture import build_moe_mlp, build_moe_encoder
from flexflow_tpu.models.mlp import build_mlp_unify

__all__ = [
    "build_alexnet",
    "build_resnet50",
    "build_resnext50",
    "build_inception_v3",
    "build_transformer_encoder",
    "build_bert_proxy",
    "build_decoder_lm",
    "build_mt5_encoder",
    "build_dlrm",
    "build_xdl",
    "build_candle_uno",
    "build_moe_mlp",
    "build_moe_encoder",
    "build_mlp_unify",
]
