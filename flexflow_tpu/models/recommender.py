"""Recommender / tabular workloads: DLRM, XDL, CANDLE-Uno."""

from __future__ import annotations

from typing import List, Sequence

from flexflow_tpu.core.types import ActiMode, AggrMode, DataType


def _mlp(ff, t, dims: Sequence[int], sigmoid_layer: int = -1):
    """reference: examples/cpp/DLRM/dlrm.cc create_mlp — dense stack,
    relu except a designated sigmoid layer, no bias."""
    for i, d in enumerate(dims):
        act = (
            ActiMode.SIGMOID if i == sigmoid_layer else ActiMode.RELU
        )
        t = ff.dense(t, d, activation=act, use_bias=False)
    return t


def build_dlrm(
    ff,
    dense_input,
    sparse_inputs: Sequence,
    embedding_sizes: Sequence[int] = (1000000,) * 4,
    sparse_feature_size: int = 64,
    mlp_bot: Sequence[int] = (64, 64),
    mlp_top: Sequence[int] = (64, 64, 2),
    interaction: str = "cat",
):
    """reference: examples/cpp/DLRM/dlrm.cc — default config
    (DLRMConfig ctor: 4x 1M-row embedding tables, feature 64, bot [4,64,64],
    top [64,64,2], cat interaction, final sigmoid)."""
    embs = []
    for i, (tbl, vocab) in enumerate(zip(sparse_inputs, embedding_sizes)):
        e = ff.embedding(
            tbl, vocab, sparse_feature_size, aggr=AggrMode.SUM,
            name=f"emb_table_{i}",
        )
        embs.append(e)
    x = _mlp(ff, dense_input, mlp_bot)
    if interaction == "cat":
        t = ff.concat(embs + [x], axis=-1)
    else:
        raise NotImplementedError(f"interaction {interaction!r}")
    t = _mlp(ff, t, mlp_top, sigmoid_layer=len(mlp_top) - 1)
    return t


def build_xdl(
    ff,
    sparse_inputs: Sequence,
    embedding_size: int = 1000000,
    sparse_feature_size: int = 64,
    mlp_dims: Sequence[int] = (4096, 2048, 1024, 2),
):
    """reference: examples/cpp/XDL/xdl.cc — embedding-dominated click model:
    N embedding bags concatenated into a deep MLP."""
    embs = [
        ff.embedding(
            t, embedding_size, sparse_feature_size, aggr=AggrMode.SUM,
            name=f"xdl_emb_{i}",
        )
        for i, t in enumerate(sparse_inputs)
    ]
    t = ff.concat(embs, axis=-1)
    t = _mlp(ff, t, mlp_dims, sigmoid_layer=len(mlp_dims) - 1)
    return t


def build_candle_uno(
    ff,
    feature_inputs: Sequence,
    feature_dims: Sequence[int] = (942, 5270, 2048),
    tower_dims: Sequence[int] = (1000, 1000, 1000),
    final_dims: Sequence[int] = (1000, 1000, 1000),
):
    """reference: examples/cpp/candle_uno/candle_uno.cc — per-feature dense
    towers concatenated, shared trunk, dense(1) regression head."""
    towers = []
    for x in feature_inputs:
        t = x
        for d in tower_dims:
            t = ff.dense(t, d, activation=ActiMode.RELU, use_bias=False)
        towers.append(t)
    t = ff.concat(towers, axis=-1) if len(towers) > 1 else towers[0]
    for d in final_dims:
        t = ff.dense(t, d, activation=ActiMode.RELU, use_bias=False)
    return ff.dense(t, 1, use_bias=False)
