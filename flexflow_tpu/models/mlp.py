"""MLP_Unify workload (reference: examples/cpp/MLP_Unify/mlp.cc)."""

from __future__ import annotations

from typing import Sequence

from flexflow_tpu.core.types import ActiMode


def build_mlp_unify(
    ff,
    input1,
    input2,
    hidden_dims: Sequence[int] = (8192,) * 8,
):
    """reference: mlp.cc:36-53 — two 1024-dim inputs through twin 8x8192
    dense stacks (relu except last), added, softmax."""
    t1, t2 = input1, input2
    for i, d in enumerate(hidden_dims):
        act = ActiMode.NONE if i + 1 == len(hidden_dims) else ActiMode.RELU
        t1 = ff.dense(t1, d, activation=act, use_bias=False)
        t2 = ff.dense(t2, d, activation=act, use_bias=False)
    t = ff.add(t1, t2)
    return ff.softmax(t)
