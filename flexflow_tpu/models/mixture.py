"""Mixture-of-Experts workloads (reference: examples/cpp/mixture_of_experts/
moe.cc)."""

from __future__ import annotations

from flexflow_tpu.core.types import ActiMode


def build_moe_mlp(
    ff,
    input_tensor,
    num_classes: int = 10,
    num_exp: int = 5,
    num_select: int = 2,
    hidden_size: int = 784,
    alpha: float = 2.0,
    lambda_bal: float = 0.04,
):
    """reference: moe.cc:158-166 — ff.moe(input, 5, 2, hidden, 2.0, 0.04)
    then dense(OUT_DIM=10, relu); MNIST dims (moe.h:23-25,34-42)."""
    t = ff.moe(input_tensor, num_exp, num_select, hidden_size, alpha, lambda_bal)
    return ff.dense(t, num_classes, activation=ActiMode.RELU)


def build_moe_encoder(
    ff,
    input_tensor,
    num_layers: int = 6,
    hidden_size: int = 784,
    num_heads: int = 16,
    num_exp: int = 5,
    num_select: int = 2,
    alpha: float = 2.0,
    lambda_bal: float = 0.04,
):
    """reference: moe.cc:100-130 create_moe_encoder — per layer:
    LN(x + MHA(x)) then LN(x + moe(x))."""
    x = input_tensor
    for _ in range(num_layers):
        a = ff.multihead_attention(x, x, x, hidden_size, num_heads)
        x = ff.layer_norm(ff.add(a, x))
        m = ff.moe(x, num_exp, num_select, hidden_size, alpha, lambda_bal)
        x = ff.layer_norm(ff.add(m, x))
    return x
