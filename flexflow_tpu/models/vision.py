"""Vision workloads: AlexNet, ResNet-50, ResNeXt-50, Inception-v3.

Topologies mirror the reference examples (cited per builder); layout is NHWC
(TPU-native) instead of the reference's NCHW — dims [N, H, W, C].
"""

from __future__ import annotations

from flexflow_tpu.core.types import ActiMode


def build_alexnet(ff, input_tensor, num_classes: int = 10):
    """reference: examples/cpp/AlexNet/alexnet.cc:69-84 (229x229 input,
    conv 64/11x11 s4 ... dense 4096x2, dense num_classes, softmax)."""
    t = ff.conv2d(input_tensor, 64, 11, 11, 4, 4, 2, 2, activation=ActiMode.RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation=ActiMode.RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 4096, activation=ActiMode.RELU)
    t = ff.dense(t, 4096, activation=ActiMode.RELU)
    t = ff.dense(t, num_classes)
    return ff.softmax(t)


def _bottleneck(ff, t, out_channels: int, stride: int):
    """reference: examples/cpp/ResNet/resnet.cc:39-57 BottleneckBlock —
    1x1 -> bn+relu -> 3x3 stride -> bn+relu -> 1x1 4x -> bn, projection
    shortcut when stride != 1, add, relu via final bn."""
    inp = t
    t = ff.conv2d(t, out_channels, 1, 1, 1, 1, 0, 0)
    t = ff.batch_norm(t)
    t = ff.conv2d(t, out_channels, 3, 3, stride, stride, 1, 1)
    t = ff.batch_norm(t)
    t = ff.conv2d(t, 4 * out_channels, 1, 1, 1, 1, 0, 0)
    t = ff.batch_norm(t, relu=False)
    if stride > 1 or inp.dims[-1] != 4 * out_channels:
        inp = ff.conv2d(inp, 4 * out_channels, 1, 1, stride, stride, 0, 0,
                        activation=ActiMode.RELU)
    t = ff.add(t, inp)
    return ff.relu(t)


def build_resnet50(ff, input_tensor, num_classes: int = 10):
    """reference: examples/cpp/ResNet/resnet.cc:89-112 — conv7x7/64 s2,
    maxpool3 s2, bottleneck stacks [3,4,6,3] @ 64/128/256/512, avgpool,
    dense(num_classes)."""
    t = ff.conv2d(input_tensor, 64, 7, 7, 2, 2, 3, 3)
    t = ff.batch_norm(t)
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1)
    for _ in range(3):
        t = _bottleneck(ff, t, 64, 1)
    for i in range(4):
        t = _bottleneck(ff, t, 128, 2 if i == 0 else 1)
    for i in range(6):
        t = _bottleneck(ff, t, 256, 2 if i == 0 else 1)
    for i in range(3):
        t = _bottleneck(ff, t, 512, 2 if i == 0 else 1)
    h, w = t.dims[1], t.dims[2]
    t = ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type="avg")
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return ff.softmax(t)


def _resnext_block(ff, t, out_channels: int, stride: int, groups: int = 32):
    """reference: examples/cpp/resnext50/resnext.cc — grouped 3x3 conv
    bottleneck (cardinality 32)."""
    inp = t
    t = ff.conv2d(t, out_channels, 1, 1, 1, 1, 0, 0, activation=ActiMode.RELU)
    t = ff.conv2d(t, out_channels, 3, 3, stride, stride, 1, 1,
                  activation=ActiMode.RELU, groups=groups)
    t = ff.conv2d(t, 2 * out_channels, 1, 1, 1, 1, 0, 0)
    if stride > 1 or inp.dims[-1] != 2 * out_channels:
        inp = ff.conv2d(inp, 2 * out_channels, 1, 1, stride, stride, 0, 0)
    t = ff.add(t, inp)
    return ff.relu(t)


def build_resnext50(ff, input_tensor, num_classes: int = 10):
    """reference: examples/cpp/resnext50/resnext.cc — stacks [3,4,6,3] at
    128/256/512/1024 with cardinality 32."""
    t = ff.conv2d(input_tensor, 64, 7, 7, 2, 2, 3, 3, activation=ActiMode.RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1)
    for _ in range(3):
        t = _resnext_block(ff, t, 128, 1)
    for i in range(4):
        t = _resnext_block(ff, t, 256, 2 if i == 0 else 1)
    for i in range(6):
        t = _resnext_block(ff, t, 512, 2 if i == 0 else 1)
    for i in range(3):
        t = _resnext_block(ff, t, 1024, 2 if i == 0 else 1)
    h, w = t.dims[1], t.dims[2]
    t = ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type="avg")
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return ff.softmax(t)


def _inception_a(ff, t, pool_features: int):
    """reference: examples/cpp/InceptionV3/inception.cc InceptionA."""
    b1 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, activation=ActiMode.RELU)
    b2 = ff.conv2d(t, 48, 1, 1, 1, 1, 0, 0, activation=ActiMode.RELU)
    b2 = ff.conv2d(b2, 64, 5, 5, 1, 1, 2, 2, activation=ActiMode.RELU)
    b3 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, activation=ActiMode.RELU)
    b3 = ff.conv2d(b3, 96, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU)
    b3 = ff.conv2d(b3, 96, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU)
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg")
    b4 = ff.conv2d(b4, pool_features, 1, 1, 1, 1, 0, 0, activation=ActiMode.RELU)
    return ff.concat([b1, b2, b3, b4], axis=3)


def build_inception_v3(ff, input_tensor, num_classes: int = 10):
    """reference: examples/cpp/InceptionV3/inception.cc — stem + InceptionA
    stack (abridged: the A blocks capture the concat-heavy search shape)."""
    t = ff.conv2d(input_tensor, 32, 3, 3, 2, 2, 0, 0, activation=ActiMode.RELU)
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 0, 0, activation=ActiMode.RELU)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 80, 1, 1, 1, 1, 0, 0, activation=ActiMode.RELU)
    t = ff.conv2d(t, 192, 3, 3, 1, 1, 0, 0, activation=ActiMode.RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = _inception_a(ff, t, 32)
    t = _inception_a(ff, t, 64)
    t = _inception_a(ff, t, 64)
    h, w = t.dims[1], t.dims[2]
    t = ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type="avg")
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return ff.softmax(t)
