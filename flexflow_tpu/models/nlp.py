"""NLP workloads: Transformer encoder (the flagship bench), BERT proxy,
mT5-style encoder."""

from __future__ import annotations

from flexflow_tpu.core.types import ActiMode, DataType


def build_transformer_encoder(
    ff,
    input_tensor,
    hidden: int = 1024,
    num_heads: int = 16,
    num_layers: int = 12,
    dropout: float = 0.0,
):
    """reference: examples/cpp/Transformer/transformer.cc:33-45 — per layer:
    MHA then dense(relu)+dense, no residuals/LN in the reference benchmark;
    final dense(1)."""
    t = input_tensor
    for _ in range(num_layers):
        t = ff.multihead_attention(t, t, t, hidden, num_heads, dropout=dropout,
                                   bias=False)
        t = ff.dense(t, hidden, activation=ActiMode.RELU, use_bias=False)
        t = ff.dense(t, hidden, use_bias=False)
    return ff.dense(t, 1, use_bias=False)


def build_bert_proxy(
    ff,
    input_tensor,
    hidden: int = 768,
    num_heads: int = 12,
    num_layers: int = 12,
    ff_dim: int = 3072,
):
    """reference: examples/python/native/bert_proxy_native.py — BERT-base
    proxy blocks: pre-built embedding output [b, seq, hidden]; per layer
    MHA + add&norm + GELU MLP + add&norm."""
    t = input_tensor
    for _ in range(num_layers):
        a = ff.multihead_attention(t, t, t, hidden, num_heads)
        t = ff.layer_norm(ff.add(a, t))
        m = ff.dense(t, ff_dim, activation=ActiMode.GELU, use_bias=False)
        m = ff.dense(m, hidden, use_bias=False)
        t = ff.layer_norm(ff.add(m, t))
    return t


def build_mt5_encoder(
    ff,
    token_ids,
    vocab_size: int = 32128,
    hidden: int = 512,
    num_heads: int = 8,
    num_layers: int = 8,
    ff_dim: int = 1024,
):
    """reference: align/mt5_encoder/align_mt5_encoder_ff.py — embedding +
    pre-LN attention/MLP blocks (T5-style: RMS-ish LN approximated by LN,
    gated GELU feed-forward)."""
    t = ff.embedding(token_ids, vocab_size, hidden)
    for _ in range(num_layers):
        h = ff.layer_norm(t)
        a = ff.multihead_attention(h, h, h, hidden, num_heads, bias=False)
        t = ff.add(t, a)
        h = ff.layer_norm(t)
        wi0 = ff.dense(h, ff_dim, activation=ActiMode.GELU, use_bias=False)
        wi1 = ff.dense(h, ff_dim, use_bias=False)
        m = ff.multiply(wi0, wi1)
        m = ff.dense(m, hidden, use_bias=False)
        t = ff.add(t, m)
    return ff.layer_norm(t)


def build_decoder_lm(
    ff,
    token_ids,
    vocab_size: int = 256,
    hidden: int = 64,
    num_heads: int = 4,
    num_layers: int = 2,
    ff_dim: int = 128,
):
    """Decoder-only LM — the serving subsystem's workload (GPT-style
    pre-LN blocks with causal self-attention; flexflow_tpu.serving needs
    causal=True and a single token-id input to build its KV cache). Ends
    in vocab logits, not softmax, so generate() argmaxes raw logits."""
    t = ff.embedding(token_ids, vocab_size, hidden)
    for _ in range(num_layers):
        h = ff.layer_norm(t)
        a = ff.multihead_attention(
            h, h, h, hidden, num_heads, bias=False, causal=True
        )
        t = ff.add(t, a)
        h = ff.layer_norm(t)
        m = ff.dense(h, ff_dim, activation=ActiMode.GELU, use_bias=False)
        m = ff.dense(m, hidden, use_bias=False)
        t = ff.add(t, m)
    return ff.dense(ff.layer_norm(t), vocab_size, use_bias=False)
