"""Iteration-level request scheduling (Orca, OSDI'22).

The unit of scheduling is one model *iteration*, not one request: every
iteration the scheduler (a) admits queued requests into free KV-cache
slots — strictly FIFO, so admission is starvation-free by construction —
running one prefill batch for the newcomers, then (b) runs one decode
step over ALL in-flight slots. A request leaving (EOS or max-new-tokens)
frees its slot at that same iteration boundary, so the next iteration's
admission can refill it. That is the continuous-batching loop; the
throughput win over request-level ("static") batching comes from never
holding finished requests' slots hostage to the longest request in a
batch.

`StaticBatchingScheduler` is the deliberately-worse baseline the bench
and the comparison test measure against: admit a batch, decode until the
WHOLE batch finishes, only then admit the next batch (the reference
FFModel::generate shape, and every pre-Orca serving stack).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class Request:
    """One generation request. `generated` accumulates post-prompt tokens
    (the first comes from the admission prefill itself)."""

    rid: int
    prompt: List[int]
    max_new_tokens: int = 16
    eos_token: Optional[int] = None

    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    submit_iter: int = -1
    admit_iter: int = -1
    finish_iter: int = -1
    submit_time: float = 0.0
    finish_time: float = 0.0

    @property
    def finished(self) -> bool:
        return self.finish_iter >= 0

    @property
    def latency_s(self) -> float:
        return self.finish_time - self.submit_time

    def _done_after(self, token: int) -> bool:
        return (
            self.eos_token is not None and token == self.eos_token
        ) or len(self.generated) >= self.max_new_tokens


@dataclasses.dataclass
class SchedulerStats:
    iterations: int = 0
    decode_steps: int = 0
    prefill_batches: int = 0
    tokens_generated: int = 0
    slot_steps: int = 0  # Σ over decode iterations of max_seqs (capacity)
    busy_slot_steps: int = 0  # Σ of actually-active slots
    peak_in_flight: int = 0  # max concurrent running requests observed
    elapsed_s: float = 0.0

    @property
    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def occupancy(self) -> float:
        """Fraction of decode slot-steps that carried a live request — the
        metric continuous batching exists to push toward 1.0."""
        return self.busy_slot_steps / self.slot_steps if self.slot_steps else 0.0


class _SchedulerBase:
    def __init__(self, engine, params=None):
        self.engine = engine
        self.cache = engine.cache
        self.params = params if params is not None else engine.model.params
        self.queue: deque = deque()
        self.running: Dict[int, Request] = {}  # slot -> request
        self.finished: List[Request] = []
        self.stats = SchedulerStats()
        self._iter = 0

    # -- submission ----------------------------------------------------------

    def submit(self, request: Request) -> None:
        if not request.prompt:
            raise ValueError("empty prompt")
        need = len(request.prompt) + request.max_new_tokens
        if need > self.cache.spec.max_len:
            raise ValueError(
                f"request {request.rid}: prompt+max_new_tokens {need} "
                f"exceeds cache max_len {self.cache.spec.max_len}"
            )
        request.submit_iter = self._iter
        request.submit_time = time.perf_counter()
        self.queue.append(request)

    # -- shared pieces -------------------------------------------------------

    def _admit(self, limit: Optional[int] = None) -> List[Request]:
        """FIFO admission into free slots (never reorders the queue —
        starvation-free: the head either admits or blocks everyone
        behind it) + ONE prefill batch for the admitted set. Admission
        asks the cache, so the gate is layout-specific: the slot layout
        admits while a slot is free; the paged layout also requires
        enough free PAGES to cover the request's worst case
        (prompt + max_new_tokens) on top of every in-flight request's
        outstanding reserve — the preemption-free policy that lets a
        mid-flight decode always claim its next page."""
        admitted: List[Request] = []
        while self.queue:
            if limit is not None and len(admitted) >= limit:
                break
            req = self.queue[0]
            slot = self.cache.alloc(
                len(req.prompt), len(req.prompt) + req.max_new_tokens
            )
            if slot is None:
                break
            self.queue.popleft()
            req.slot = slot
            req.admit_iter = self._iter
            self.running[req.slot] = req
            admitted.append(req)
        self.stats.peak_in_flight = max(
            self.stats.peak_in_flight, len(self.running)
        )
        if admitted:
            nxt, _ = self.engine.prefill(
                self.params,
                [r.prompt for r in admitted],
                [r.slot for r in admitted],
                step=self._iter,
            )
            self.stats.prefill_batches += 1
            for tok, req in zip(nxt, admitted):
                self._emit(req, int(tok))
        return admitted

    def _emit(self, req: Request, token: int) -> None:
        req.generated.append(token)
        self.stats.tokens_generated += 1
        if req._done_after(token):
            self._retire(req)

    def _retire(self, req: Request) -> None:
        req.finish_iter = self._iter
        req.finish_time = time.perf_counter()
        self.cache.free(req.slot)
        del self.running[req.slot]
        self.finished.append(req)

    def _decode_once(self) -> None:
        spec = self.cache.spec
        tokens = np.zeros(spec.max_seqs, dtype=np.int32)
        active = np.zeros(spec.max_seqs, dtype=bool)
        for slot, req in self.running.items():
            tokens[slot] = req.generated[-1]
            active[slot] = True
        nxt, _ = self.engine.decode(
            self.params, tokens, active, step=self._iter
        )
        self.stats.decode_steps += 1
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += int(active.sum())
        for slot in [s for s, a in enumerate(active) if a]:
            req = self.running.get(slot)
            if req is not None:
                self._emit(req, int(nxt[slot]))

    def run(self, requests: Optional[Sequence[Request]] = None) -> List[Request]:
        """Drain the queue (plus `requests`, submitted first) to completion;
        returns finished requests in completion order."""
        for r in requests or ():
            self.submit(r)
        t0 = time.perf_counter()
        while self.queue or self.running:
            self.step()
        self.stats.elapsed_s += time.perf_counter() - t0
        return self.finished


class ContinuousBatchingScheduler(_SchedulerBase):
    """Orca-style: every iteration joins new prefills with in-flight
    decodes; slots recycle the moment a request retires."""

    def step(self) -> None:
        self._iter += 1
        self.stats.iterations += 1
        self._admit()
        if self.running:
            self._decode_once()


class StaticBatchingScheduler(_SchedulerBase):
    """Request-level batching baseline: a batch runs until every member
    finishes; freed slots stay idle until the batch drains."""

    def step(self) -> None:
        self._iter += 1
        self.stats.iterations += 1
        if not self.running:
            self._admit()
        if self.running:
            self._decode_once()


def latency_percentiles(requests: Sequence[Request], pcts=(50, 95)):
    """{pct: seconds} over finished requests' submit→finish latency."""
    lats = [r.latency_s for r in requests if r.finished]
    if not lats:
        return {p: 0.0 for p in pcts}
    return {p: float(np.percentile(lats, p)) for p in pcts}
