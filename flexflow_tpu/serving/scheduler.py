"""Iteration-level request scheduling (Orca, OSDI'22) with per-request
fault isolation and preemption-by-recompute (vLLM / PagedAttention,
SOSP'23).

The unit of scheduling is one model *iteration*, not one request: every
iteration the scheduler (a) admits queued requests into free KV-cache
slots — strictly FIFO, so admission is starvation-free by construction —
running one prefill batch for the newcomers, then (b) runs one decode
step over ALL in-flight slots. A request leaving (EOS or max-new-tokens)
frees its slot at that same iteration boundary, so the next iteration's
admission can refill it. That is the continuous-batching loop; the
throughput win over request-level ("static") batching comes from never
holding finished requests' slots hostage to the longest request in a
batch.

Speculative decoding (SpecInfer, ASPLOS'24; serving/spec.py) is a mode
of the same loop: when a scheduler carries a `DraftProposer`, step (b)
becomes draft → one batched verify call → accept/rollback, emitting
1..spec_k+1 tokens per slot per iteration instead of exactly one. The
iteration-level frame is unchanged — a verify is just a wider decode —
so admission, retirement, and slot recycling all work as before.

**Request lifecycle.** Every request ends in exactly one terminal
status: FINISHED (EOS / token budget), FAILED (bad input, non-finite
logits, an engine fault, or too many preemptions — the error is captured
on the request), CANCELLED (`scheduler.cancel(rid)`), or TIMED_OUT
(`Request.deadline_s` elapsed, whether queued or running). PREEMPTED is
the one transient status: an optimistic-admission victim whose pages
were reclaimed goes back to the queue head and re-enters RUNNING via
prefill-from-recompute. The resilience contract — proved by
tests/test_resilience.py under a seeded FaultInjector — is that a fault
retires only the requests it touches: every other slot's greedy token
stream is identical to a fault-free run, because greedy decode is a pure
function of a slot's own context, never of which neighbors share the
iteration.

**Admission policies** (paged layout): the default `reserve` policy
admits only when the free pool covers a request's worst case on top of
every in-flight reservation — preemption-free by construction. The
opt-in `optimistic` policy admits on the pages a request needs NOW;
when the pool later runs dry mid-decode (PagePoolExhausted from
`ensure_position`), the scheduler preempts the youngest-by-admission
victims — frees their pages and requeues them at the queue head for
prefill-from-recompute over prompt + tokens generated so far — up to
`max_preemptions` times per request before hard FAILED. Recompute (not
swap) is the right recovery here for the same reason vLLM defaults to
it: a preempted sequence's KV is recomputable from its token history in
one prefill-shaped step, so no swap-space subsystem is needed.

`StaticBatchingScheduler` is the deliberately-worse baseline the bench
and the comparison test measure against: admit a batch, decode until the
WHOLE batch finishes, only then admit the next batch (the reference
FFModel::generate shape, and every pre-Orca serving stack).

**Async double-buffered loop** (`AsyncContinuousBatchingScheduler`,
`--serve-async`): every decode/verify is split into a dispatch phase
(live-state reads, snapshot taken, step enqueued) and a reconcile
phase (device outputs committed against the snapshot) run one
iteration apart, so host scheduling overlaps device execution. The
synchronous schedulers run the same two phases back-to-back — ONE
implementation, proved token-identical across both timings.

**Chunked prefill** (`--token-budget`, Sarathi-Serve-style): with a
token budget set, admission claims a slot but runs NO monolithic
prefill — the prompt streams into the cache in `--chunk-size`-aligned
chunks over the following iterations, interleaved with the in-flight
decode/verify work, so no single iteration processes more than
~token_budget tokens and a long prompt can no longer head-of-line
block every in-flight decode. Chunk grants are fair-share round-robin
over the prefill-pending slots (FIFO-ordered passes of one chunk
each), so short prompts finish their prefill in one iteration even
while a long prompt is mid-stream. A chunked request starts decoding
only after its LAST chunk lands (that chunk's sampled token is the
first generated token — exactly the monolithic prefill's tail), and
under the async loop chunk progress commits only at reconcile, from
the `InflightStep.chunks` cursor snapshot (fxlint FX105).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flexflow_tpu.serving.kv_cache import PagePoolExhausted
from flexflow_tpu.telemetry import MetricsRegistry
from flexflow_tpu.telemetry.slo import percentiles as _percentiles


class RequestStatus:
    """String constants (json-friendly) for the request lifecycle."""

    QUEUED = "queued"
    RUNNING = "running"
    PREEMPTED = "preempted"  # transient: requeued for recompute
    FINISHED = "finished"
    FAILED = "failed"
    CANCELLED = "cancelled"
    TIMED_OUT = "timed_out"


#: statuses a request never leaves
TERMINAL_STATUSES = frozenset(
    {
        RequestStatus.FINISHED,
        RequestStatus.FAILED,
        RequestStatus.CANCELLED,
        RequestStatus.TIMED_OUT,
    }
)

_ADMISSION_MODES = ("reserve", "optimistic")


@dataclasses.dataclass
class Request:
    """One generation request. `generated` accumulates post-prompt tokens
    (the first comes from the admission prefill itself). `deadline_s` is
    a wall-clock budget from submit — queued or running, the request is
    TIMED_OUT once it elapses. `events` is the per-request audit log:
    (wall time, event, detail) for submit/admit/first_token/preempt/
    terminal transitions — a RING buffer bounded by `events_max`, so a
    long-running request cannot grow it without bound: past the cap the
    OLDEST entry drops and `events_dropped` counts it (surfaced as the
    `serve_request_events_dropped_total` telemetry counter)."""

    rid: int
    prompt: List[int]
    max_new_tokens: int = 16
    eos_token: Optional[int] = None
    deadline_s: Optional[float] = None
    events_max: int = 64
    # multi-tenant serving: free-form tenant tag (telemetry only),
    # priority class name ("" = the first configured class), and the
    # LoRA adapter serving this request (-1 = the base model)
    tenant: str = ""
    priority_class: str = ""
    adapter_id: int = -1
    # durable serving: the CLIENT's idempotency key — a retried submit
    # carrying the same key dedups against the journal/front-door
    # instead of opening a second stream (None = no dedup)
    request_key: Optional[str] = None

    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    status: str = RequestStatus.QUEUED
    error: Optional[str] = None
    preemptions: int = 0
    submit_iter: int = -1
    admit_iter: int = -1
    finish_iter: int = -1
    submit_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0
    events: List[Tuple[float, str, str]] = dataclasses.field(
        default_factory=list
    )
    events_dropped: int = 0
    # inter-token-latency stamp (telemetry only): wall time of the last
    # emitted token — 0.0 until telemetry observes the first one
    last_token_time: float = 0.0
    # chunked prefill (token_budget > 0): the sequence being prefilled
    # (prompt + recompute tokens, fixed at admission), the dispatch
    # cursor (tokens handed to a chunk step, possibly still in flight)
    # and the committed cursor (tokens whose chunk reconciled).
    # prefill_pos < len(prefill_seq) means the request is still
    # prefilling — it neither decodes nor drafts until its last chunk
    # lands. Reconcile-phase code reads cursor state from the
    # InflightStep.chunks snapshot, never these live attrs (FX105).
    prefill_seq: List[int] = dataclasses.field(default_factory=list)
    prefill_pos: int = 0
    prefill_dispatched: int = 0
    # KV swap-to-host (kv_swap=True): handle of this request's staged
    # pages while it waits PREEMPTED->QUEUED for re-admission — swap_in
    # restores them (no re-prefill); None everywhere else
    swap_handle: Optional[int] = None

    def log(self, event: str, detail: str = "") -> None:
        if len(self.events) >= max(1, self.events_max):
            del self.events[0]
            self.events_dropped += 1
        self.events.append((time.perf_counter(), event, detail))

    @property
    def finished(self) -> bool:
        """Terminal in ANY status — the request will never run again."""
        return self.status in TERMINAL_STATUSES

    @property
    def ok(self) -> bool:
        """Terminal AND successful — the only requests whose latency
        numbers mean anything."""
        return self.status == RequestStatus.FINISHED

    @property
    def latency_s(self) -> float:
        return self.finish_time - self.submit_time

    @property
    def ttft_s(self) -> float:
        """Submit → first generated token (the prefill-side latency a
        user perceives before streaming starts). Meaningless (0.0) for
        a request that never produced a token."""
        if not self.generated:
            return 0.0
        return self.first_token_time - self.submit_time

    @property
    def decode_s_per_token(self) -> float:
        """Mean seconds per generated token AFTER the first — the
        decode-side latency speculative decoding compresses (several
        accepted tokens share one verify step's wall time)."""
        if len(self.generated) <= 1:
            return 0.0
        return (self.finish_time - self.first_token_time) / (
            len(self.generated) - 1
        )

    def deadline_exceeded(self, now: float) -> bool:
        return (
            self.deadline_s is not None
            and now - self.submit_time > self.deadline_s
        )

    def _done_after(self, token: int) -> bool:
        return (
            self.eos_token is not None and token == self.eos_token
        ) or len(self.generated) >= self.max_new_tokens


#: SchedulerStats fields, name -> default. Each is backed by a
#: `serve_stats_<name>` gauge in a telemetry.MetricsRegistry — comments
#: that used to annotate the dataclass fields live here.
_STAT_FIELDS: Dict[str, object] = dict(
    iterations=0,
    decode_steps=0,
    prefill_batches=0,
    tokens_generated=0,
    slot_steps=0,  # Σ over decode/verify iterations of max_seqs
    busy_slot_steps=0,  # Σ of actually-active slots
    peak_in_flight=0,  # max concurrent running requests observed
    elapsed_s=0.0,
    # speculative decoding (verify iterations only)
    verify_steps=0,
    draft_tokens_proposed=0,
    draft_tokens_accepted=0,
    # token-tree speculation (spec_branch > 1): under trees,
    # draft_tokens_proposed counts the tree DEPTH (the most tokens one
    # verify could accept), so acceptance_rate keeps its meaning — the
    # full node count lives here instead
    tree_verify_steps=0,  # verify steps that scored a draft tree
    tree_nodes_proposed=0,  # Σ tree nodes dispatched for verification
    # chunked prefill (token_budget > 0)
    chunk_steps=0,  # chunk steps dispatched
    chunk_tokens=0,  # Σ prompt tokens streamed in via chunks
    budget_deferrals=0,  # prefill-pending slots granted no tokens
    budget_used=0,  # tokens the LAST iteration charged to its budget
    # device-resident multi-step decode (decode_multistep=True)
    multistep_windows=0,  # fused K-step scan windows dispatched
    multistep_steps=0,  # Σ decode steps executed inside fused windows
    host_syncs=0,  # step reconciles (host round-trips), all kinds
    multistep_cache_entries=0,  # live jitted scan programs (LRU gauge)

    # request lifecycle (filled at terminal transitions)
    submitted_requests=0,
    finished_requests=0,  # FINISHED only — not failures
    failed_requests=0,
    cancelled_requests=0,
    timed_out_requests=0,
    preemptions=0,  # preempt-and-requeue events
    step_faults=0,  # whole-step engine faults (all slots retired)
    draft_faults=0,  # proposer faults degraded to plain decode
    tokens_finished=0,  # Σ generated over FINISHED requests only
    # per-request latency accumulators (FINISHED requests only — a
    # request failing before its first token has no TTFT to aggregate).
    # TTFT and decode latency are stamped at COMMIT (when _emit actually
    # hands the token over), never at dispatch: under the async loop a
    # token's step is enqueued an iteration before its value exists, and
    # dispatch-time stamps would fake latencies exactly as deep as the
    # pipeline.
    ttft_sum_s=0.0,
    decode_latency_sum_s=0.0,  # Σ of per-request decode_s_per_token
    # dispatch/commit split (async double-buffered engine; the sync loop
    # fills them too — its overlap window is just ~empty)
    dispatch_count=0,  # decode/verify steps enqueued
    dispatch_gap_sum_s=0.0,  # Σ wall time between consecutive dispatches
    commit_wait_s=0.0,  # Σ time blocked on device outputs at reconcile
    overlapped_host_s=0.0,  # Σ host work done while a step was in flight
    # speculative pre-proposals drafted during the in-flight window
    # (async spec mode): used as-is vs rolled back on reconcile mismatch
    pre_proposal_hits=0,
    pre_proposal_misses=0,
    # live jitted verify programs in the engine's LRU (sampled at the
    # end of each iteration — bounded by engine.verify_cache_max)
    verify_cache_entries=0,
    # kernel-failure dense fallbacks (mirrored from the engine's ledger
    # at each iteration end)
    kernel_fallbacks=0,
    # prefix-sharing page cache (paged layout with --prefix-cache;
    # mirrored from the allocator's ledgers at each iteration end)
    prefix_hits=0,  # admissions that mapped at least one shared page
    prefix_pages_shared=0,  # live shared table entries (gauge-like)
    cow_copies=0,  # copy-on-write page forks
    # graceful degradation under pressure (kv_swap / prefix_evict;
    # mirrored from the allocator's ledgers at each iteration end)
    swap_outs=0,  # victims whose pages rode the host link out
    swap_ins=0,  # swap-restored re-admissions (no re-prefill)
    swap_bytes=0,  # Σ bytes staged across the host link, both ways
    swapped_pages=0,  # pages currently parked in host buffers (gauge)
    prefix_evictions=0,  # publication-only prefix pages reclaimed
    host_downs=0,  # host partitions drained after a failure
    # per-request audit-log ring-buffer drops, summed at finalize
    events_dropped=0,
)

#: derived SchedulerStats properties `publish_derived` exports as
#: gauges so the JSONL time series and text exposition carry them
_STAT_DERIVED = (
    "tokens_per_s",
    "goodput_tokens_per_s",
    "terminal_requests",
    "occupancy",
    "acceptance_rate",
    "mean_dispatch_gap_s",
    "overlap_fraction",
    "mean_ttft_s",
    "mean_decode_s_per_token",
    "host_syncs_per_token",
)


class _StatField:
    """Descriptor backing one SchedulerStats field with its registry
    gauge: reads and writes go straight to the gauge's value, so
    `stats.tokens_generated += 1` and the exported
    `serve_stats_tokens_generated` series can never disagree."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj._metrics[self.name].value

    def __set__(self, obj, value):
        obj._metrics[self.name].value = value


class SchedulerStats:
    """Scheduler counters/aggregates — a façade over a
    telemetry.MetricsRegistry. Every field is a `serve_stats_<name>`
    gauge; with telemetry attached the scheduler passes the shared
    registry, so `--metrics-out` exposition and the JSONL time series
    read the SAME storage the tests and benches read through this
    class. Without telemetry each instance owns a private registry —
    the field surface and update syntax are unchanged from the old
    dataclass, and the cost per update is one dict lookup plus an
    attribute write."""

    __slots__ = ("_registry", "_metrics", "_derived")

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry if registry is not None else MetricsRegistry()
        self._metrics = {}
        for name, default in _STAT_FIELDS.items():
            gauge = self._registry.gauge("serve_stats_" + name)
            # a fresh stats object owns its series: re-zero so a reused
            # registry (new scheduler, same Telemetry) starts clean
            gauge.value = default
            self._metrics[name] = gauge
        # derived-property gauge handles, resolved once — the
        # per-iteration publish is then pure attribute writes
        self._derived = {
            name: self._registry.gauge("serve_stats_" + name)
            for name in _STAT_DERIVED
        }
        for gauge in self._derived.values():
            gauge.value = 0.0

    def publish_derived(self) -> None:
        """Refresh the derived-property gauges
        (`serve_stats_<property>`) — the per-iteration sampler's hook,
        so ratios like occupancy and overlap_fraction ride the time
        series without consumers re-deriving them."""
        for name, gauge in self._derived.items():
            gauge.value = round(float(getattr(self, name)), 9)

    def as_dict(self) -> Dict[str, object]:
        """Fields + derived properties as one plain dict (bench
        artifacts embed it)."""
        out: Dict[str, object] = {
            name: self._metrics[name].value for name in _STAT_FIELDS
        }
        for name in _STAT_DERIVED:
            out[name] = float(getattr(self, name))
        return out

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{name}={self._metrics[name].value!r}" for name in _STAT_FIELDS
        )
        return f"SchedulerStats({inner})"

    @property
    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def goodput_tokens_per_s(self) -> float:
        """Tokens of successfully FINISHED requests per second — the
        number a resilient scheduler maximizes under faults. Tokens
        generated for requests that later failed, timed out, or were
        cancelled are work, not goodput."""
        return self.tokens_finished / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def terminal_requests(self) -> int:
        return (
            self.finished_requests
            + self.failed_requests
            + self.cancelled_requests
            + self.timed_out_requests
        )

    @property
    def occupancy(self) -> float:
        """Fraction of decode/verify slot-steps that carried a live
        request — the metric continuous batching exists to push toward
        1.0."""
        return self.busy_slot_steps / self.slot_steps if self.slot_steps else 0.0

    @property
    def acceptance_rate(self) -> float:
        """Fraction of drafted tokens the verify step accepted — the
        measured α that optimize_spec_k turns into a draft length."""
        if not self.draft_tokens_proposed:
            return 0.0
        return self.draft_tokens_accepted / self.draft_tokens_proposed

    @property
    def mean_dispatch_gap_s(self) -> float:
        """Mean wall time between consecutive step dispatches — the
        host-side critical path per iteration. Under the async loop
        this is what bounds throughput (the device works through the
        gap); under the sync loop it includes the device wait."""
        if self.dispatch_count <= 1:
            return 0.0
        return self.dispatch_gap_sum_s / (self.dispatch_count - 1)

    @property
    def overlap_fraction(self) -> float:
        """Of the wall time between a step's dispatch and the end of
        its reconcile, the fraction the host spent doing useful work
        (admission, page claims, drafting, the next dispatch) instead
        of blocked on device outputs — the number the double-buffered
        loop exists to push toward 1.0. The sync reference loop
        reconciles immediately after dispatching, so it sits at ~0."""
        window = self.overlapped_host_s + self.commit_wait_s
        if window <= 0.0:
            return 0.0
        return self.overlapped_host_s / window

    @property
    def mean_ttft_s(self) -> float:
        if not self.finished_requests:
            return 0.0
        return self.ttft_sum_s / self.finished_requests

    @property
    def mean_decode_s_per_token(self) -> float:
        if not self.finished_requests:
            return 0.0
        return self.decode_latency_sum_s / self.finished_requests

    @property
    def host_syncs_per_token(self) -> float:
        """Host round-trips (step reconciles) per committed token — the
        cost the device-resident multi-step loop exists to amortize:
        the step-at-a-time loop sits at ~1.0, a fused K-step window
        pushes it toward 1/K."""
        if not self.tokens_generated:
            return 0.0
        return self.host_syncs / self.tokens_generated


for _name in _STAT_FIELDS:
    setattr(SchedulerStats, _name, _StatField(_name))
del _name


class _SchedulerBase:
    """Shared admission/decode/verify machinery. `proposer` switches the
    per-iteration generation step from plain decode to speculative
    draft/verify (serving/spec.py). `admission` picks the paged cache's
    policy ("reserve" = preemption-free worst-case gate, "optimistic" =
    admit-now/preempt-later, bounded by `max_preemptions` per request).
    `injector` threads a faults.FaultInjector through the step
    boundaries; the isolation machinery below runs either way — the
    injector only makes faults happen on schedule."""

    def __init__(
        self,
        engine,
        params=None,
        proposer=None,
        spec_k: int = 4,
        spec_branch: int = 1,
        admission: str = "reserve",
        max_preemptions: int = 3,
        injector=None,
        debug_invariants: bool = False,
        telemetry=None,
        token_budget: int = 0,
        chunk_size: int = 16,
        kv_swap: bool = False,
        swap_decider=None,
        decode_multistep: bool = False,
        max_fused_steps: int = 8,
        classes=None,
        victim_pricer=None,
        journal=None,
        journal_snapshot_every: int = 0,
    ):
        self.engine = engine
        self.cache = engine.cache
        self.params = params if params is not None else engine.model.params
        self.proposer = proposer
        self.spec_k = int(spec_k)
        if proposer is not None and self.spec_k < 1:
            raise ValueError("speculative decoding needs spec_k >= 1")
        # token-tree speculation: spec_branch > 1 switches the verify
        # step from a single draft chain to a deduped token TREE of up
        # to spec_k * spec_branch nodes (depth spec_k, spec_branch
        # alternatives per level before prefix sharing). The compiled
        # verify width is FIXED at 1 + _tree_nodes — the tree's shape
        # rides in as a parent table (data), so topology changes never
        # recompile. spec_branch == 1 keeps the linear chain path
        # bit-for-bit untouched.
        self.spec_branch = int(spec_branch)
        if self.spec_branch < 1:
            raise ValueError(f"spec_branch must be >= 1, got {spec_branch}")
        self._tree_nodes = self.spec_k * self.spec_branch
        # iteration-scoped dry-proposal cache: _fusable_steps may draft
        # to learn whether speculation has work this iteration; the
        # result is handed to _verify_once so nothing drafts twice
        self._cached_proposals = None
        if admission not in _ADMISSION_MODES:
            raise ValueError(
                f"admission must be one of {_ADMISSION_MODES}, "
                f"got {admission!r}"
            )
        self.admission = admission
        self.max_preemptions = int(max_preemptions)
        # chunked prefill: token_budget > 0 switches admission to the
        # chunk-streaming path and caps each iteration's token work.
        # Bad combinations don't raise here — they park an error that
        # _validate raises per-request, so a serving surface built on
        # strict=False degrades to per-request FAILED (the PR 5
        # contract) instead of dying at construction.
        self.token_budget = int(token_budget)
        self.chunk_size = int(chunk_size)
        self._chunk_config_error: Optional[str] = None
        if token_budget < 0:
            self._chunk_config_error = (
                f"token_budget must be >= 0, got {token_budget}"
            )
            self.token_budget = 0
        elif self.token_budget:
            from flexflow_tpu.ops.pallas.decode_kernel import SUBLANES

            if self.chunk_size < 1:
                self._chunk_config_error = (
                    f"chunk_size must be >= 1, got {chunk_size}"
                )
            elif self.token_budget < self.chunk_size:
                self._chunk_config_error = (
                    f"token_budget {token_budget} < chunk_size "
                    f"{chunk_size}: an iteration could never fit one "
                    f"chunk"
                )
            elif self.chunk_size % SUBLANES and self._kernel_active():
                # mirror decode_kernel.supports(): chunk widths are the
                # kernel's query-tile dim, so a misaligned chunk_size
                # would silently route EVERY chunk to the dense fallback
                self._chunk_config_error = (
                    f"chunk_size {chunk_size} must be a multiple of "
                    f"{SUBLANES} when decode_kernel is "
                    f"{engine.decode_kernel!r}"
                )
        self.injector = injector
        # durable serving (serving/journal.py): when a RequestJournal is
        # attached, submit/commit/terminal records flow through it at
        # the seams below — submit() at queue entry, _emit -> note
        # (buffered), _end_iteration -> commit_pending (ONE commit
        # record per request per host sync, so a fused window or
        # tree-verify round journals its accepted run at its natural
        # grain), _finalize -> terminal. The commit flush runs INSIDE
        # step(), before any front door can observe the new tokens:
        # journal-before-publish (fxlint FX111).
        self.journal = journal
        self.journal_snapshot_every = int(journal_snapshot_every)
        if self.journal_snapshot_every < 0:
            raise ValueError(
                "journal_snapshot_every must be >= 0, got "
                f"{journal_snapshot_every}"
            )
        # KV swap-to-host: when on (paged layout only), a preemption
        # victim's committed pages ride the host link instead of being
        # recomputed — unless `swap_decider(cache, request)` (built from
        # CostModel.swap_cost vs estimate_recompute_step; None means
        # always-swap) says the recompute is cheaper, or the allocator
        # refuses (budget / in-flight step), or the injector fails it.
        self.kv_swap = bool(kv_swap)
        if self.kv_swap and not getattr(engine.cache, "paged", False):
            raise ValueError("kv_swap requires the paged KV layout")
        self.swap_decider = swap_decider
        # device-resident multi-step decode: when on, runs of decode
        # iterations with no host-visible event pending fuse into ONE
        # jitted lax.scan window of up to max_fused_steps steps
        # (engine.decode_multi_dispatch) reconciled in a single host
        # sync — see _fusable_steps for the event list that holds
        # fusing to one step
        self.decode_multistep = bool(decode_multistep)
        self.max_fused_steps = int(max_fused_steps)
        if self.decode_multistep and self.max_fused_steps < 1:
            raise ValueError(
                f"max_fused_steps must be >= 1, got {max_fused_steps}"
            )
        # ServeConfig.debug_invariants / --check-invariants: re-derive
        # the cache/allocator accounting after EVERY iteration (what the
        # chaos harness does), so an invariant violation surfaces at the
        # iteration that caused it instead of steps later
        self.debug_invariants = bool(debug_invariants)
        # telemetry (flexflow_tpu.telemetry.Telemetry): `_tele` is the
        # hot-path handle — None when disabled, so every instrument
        # point costs exactly one predicate when telemetry is off
        self.telemetry = telemetry
        self._tele = (
            telemetry
            if telemetry is not None and getattr(telemetry, "enabled", False)
            else None
        )
        self.queue: deque = deque()
        self.running: Dict[int, Request] = {}  # slot -> request
        self.finished: List[Request] = []
        self.stats = SchedulerStats(
            registry=self._tele.registry if self._tele is not None else None
        )
        self._by_rid: Dict[int, Request] = {}
        self._iter = 0
        self._iter_t0 = 0.0
        self._gauge_handles: Optional[Dict[str, object]] = None
        self._last_dispatch_t: Optional[float] = None
        # per-iteration budget ledger: zeroed by _begin_iteration,
        # published as the `budget_used` gauge by _end_iteration
        self._budget_used_iter = 0
        # slots whose FINAL chunk committed this iteration: their first
        # decode/verify waits for the next one, so the chunk planner's
        # grants alone bound the iteration's token work
        self._chunk_unlocked: set = set()
        # -- multi-tenancy ---------------------------------------------------
        # `classes` ({name: PriorityClass}, config order = scheduling
        # order) switches admission and token grants to weighted-fair
        # DRR and preemption victims to class-priced cost. One class or
        # None keeps every decision EXACTLY what it was before classes
        # existed (FIFO admission, youngest-first victims), so single-
        # tenant schedules — including chaos replays — are untouched.
        self.classes = dict(classes) if classes else None
        self._multiclass = bool(self.classes) and len(self.classes) > 1
        self._default_class = next(iter(self.classes)) if self.classes else ""
        self._admit_drr = None
        self._grant_drr: Dict[int, object] = {}  # host -> DRR (token grants)
        self._class_slo: Dict[str, object] = {}
        if self._multiclass:
            from flexflow_tpu.serving.tenancy.fairness import (
                DeficitRoundRobin,
            )

            weights = {n: c.weight for n, c in self.classes.items()}
            self._admit_drr = DeficitRoundRobin(weights, unit=1.0)
        if self.classes and self._tele is not None:
            from flexflow_tpu.serving.tenancy.slo import build_class_monitors

            self._class_slo = build_class_monitors(
                self._tele.registry, self.classes
            )
        # class-priced preemption: weight x resident tokens by default,
        # or the api.py-built CostModel pricer when provided
        self._victim_pricer = victim_pricer
        # paged multi-LoRA adapter pool riding the engine (None = no
        # adapters anywhere; the scheduler owns attach/detach lifecycle)
        self.adapters = getattr(engine, "adapters", None)

    # -- submission / cancellation -------------------------------------------

    def submit(self, request: Request, strict: bool = True) -> bool:
        """Queue a request. Invalid requests raise ValueError when
        `strict` (the library-call contract), or transition straight to
        FAILED when not (the serving-surface contract: one bad request
        must not take down a batch submitted with it). Returns True when
        the request entered the queue."""
        try:
            self._validate(request)
        except ValueError as e:
            if strict:
                raise
            request.submit_iter = self._iter
            request.submit_time = time.perf_counter()
            self._by_rid[request.rid] = request
            self.stats.submitted_requests += 1
            if self.journal is not None:
                # journal the submit BEFORE its terminal record so the
                # strict=False reject leaves the same submit->terminal
                # pair a served request would
                self.journal.submitted(request)
            self._finalize(request, RequestStatus.FAILED, error=str(e))
            return False
        request.status = RequestStatus.QUEUED
        request.submit_iter = self._iter
        request.submit_time = time.perf_counter()
        request.log("submit")
        self._by_rid[request.rid] = request
        self.stats.submitted_requests += 1
        if self.journal is not None:
            self.journal.submitted(request)
        self.queue.append(request)
        return True

    def _validate(self, request: Request) -> None:
        if self._chunk_config_error is not None:
            # rejected chunked-prefill config: every request fails with
            # the parked error — ValueError under strict submit, a
            # per-request FAILED under strict=False
            raise ValueError(self._chunk_config_error)
        if not request.prompt:
            raise ValueError("empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.rid}: max_new_tokens must be >= 1, "
                f"got {request.max_new_tokens}"
            )
        if request.deadline_s is not None and request.deadline_s <= 0:
            raise ValueError(
                f"request {request.rid}: deadline_s must be > 0, "
                f"got {request.deadline_s}"
            )
        need = len(request.prompt) + request.max_new_tokens
        if need > self.cache.spec.max_len:
            raise ValueError(
                f"request {request.rid}: prompt+max_new_tokens {need} "
                f"exceeds cache max_len {self.cache.spec.max_len}"
            )
        if request.priority_class and (
            self.classes is None or request.priority_class not in self.classes
        ):
            raise ValueError(
                f"request {request.rid}: unknown priority class "
                f"{request.priority_class!r} (configured: "
                f"{sorted(self.classes) if self.classes else []})"
            )
        if request.adapter_id != -1:
            if self.adapters is None:
                raise ValueError(
                    f"request {request.rid}: adapter_id "
                    f"{request.adapter_id} but the engine has no adapter "
                    "pool (--adapters)"
                )
            if request.adapter_id not in self.adapters.loaded:
                raise ValueError(
                    f"request {request.rid}: adapter {request.adapter_id} "
                    "is not loaded"
                )

    def _class_of(self, req: Request) -> str:
        """The request's effective priority class — the FIRST configured
        class when it names none (config order is scheduling order)."""
        return req.priority_class or self._default_class

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or running request; its slot and pages free
        at the next finalize. Returns False for unknown or already-
        terminal rids (cancellation races are expected, not errors)."""
        req = self._by_rid.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        self._finalize(req, RequestStatus.CANCELLED)
        return True

    # -- lifecycle core ------------------------------------------------------

    def _finalize(self, req: Request, status: str, error: Optional[str] = None):
        """The ONLY transition into a terminal status: releases the
        slot/pages (or the queue position), notifies the proposer, logs
        the event, and feeds the stats — so every path (finish, fail,
        cancel, timeout, preemption overrun) accounts identically and no
        request can leak a slot or vanish without a terminal record."""
        if req.status in TERMINAL_STATUSES:
            return
        req.status = status
        req.error = error
        req.finish_iter = self._iter
        req.finish_time = time.perf_counter()
        req.log(status, error or "")
        if self.journal is not None:
            # terminal record (preceded inside finalize() by the rid's
            # still-buffered commit run): no request ends undurably
            self.journal.finalize(req.rid, status, error, self._iter)
        slot_host = (
            self.cache.host_of_slot(req.slot)
            if req.slot is not None
            else None
        )
        if req.slot is not None and self.running.get(req.slot) is req:
            if self.proposer is not None:
                self.proposer.retire(req)
            del self.running[req.slot]
            if self.adapters is not None:
                self.adapters.detach(req.slot)
            self.cache.free(req.slot)
            req.slot = None
        else:
            # identity-based removal: Request is a dataclass, so the
            # deque's __eq__-based remove() could drop a twin instead
            for i, queued in enumerate(self.queue):
                if queued is req:
                    del self.queue[i]
                    break
        if req.swap_handle is not None:
            # a terminal request still holding host-swapped pages (e.g.
            # cancelled or timed out while QUEUED) returns its staged
            # bytes to the swap budget
            self.cache.discard_swap(req.swap_handle)
            req.swap_handle = None
        self.finished.append(req)
        stats = self.stats
        stats.events_dropped += req.events_dropped
        if status == RequestStatus.FINISHED:
            stats.finished_requests += 1
            stats.tokens_finished += len(req.generated)
            # latency aggregates take FINISHED requests only: a request
            # retired before its first token has no TTFT, and averaging
            # a 0.0 in would fake lower latencies exactly when faults
            # are making things worse
            stats.ttft_sum_s += req.ttft_s
            stats.decode_latency_sum_s += req.decode_s_per_token
        elif status == RequestStatus.FAILED:
            stats.failed_requests += 1
        elif status == RequestStatus.CANCELLED:
            stats.cancelled_requests += 1
        elif status == RequestStatus.TIMED_OUT:
            stats.timed_out_requests += 1
        tele = self._tele
        if tele is not None:
            reg = tele.registry
            reg.counter(
                "serve_requests_total",
                help="terminal request transitions by status",
                labels={"status": status},
            ).inc()
            if self.classes:
                reg.counter(
                    "serve_requests_total",
                    help="terminal request transitions by status",
                    labels={
                        "status": status,
                        "class": self._class_of(req),
                    },
                ).inc()
            if req.tenant:
                reg.counter(
                    "serve_requests_total",
                    help="terminal request transitions by status",
                    labels={"status": status, "tenant": req.tenant},
                ).inc()
            if (
                getattr(self.cache, "num_hosts", 1) > 1
                and slot_host is not None
            ):
                reg.counter(
                    "serve_requests_total",
                    help="terminal request transitions by status",
                    labels={"status": status, "host": str(slot_host)},
                ).inc()
            if req.events_dropped:
                reg.counter(
                    "serve_request_events_dropped_total",
                    help="audit-log ring-buffer drops (events_max cap)",
                ).inc(req.events_dropped)
            if status == RequestStatus.FINISHED:
                # the SLO view aggregates FINISHED requests only, same
                # rule as the stats accumulators above
                if req.generated:
                    tele.slo.observe_ttft(req.ttft_s)
                tele.slo.observe_finished(
                    req.finish_time, len(req.generated)
                )
                mon = self._class_slo.get(self._class_of(req))
                if mon is not None:
                    if req.generated:
                        mon.observe_ttft(req.ttft_s)
                    mon.observe_finished(
                        req.finish_time, len(req.generated)
                    )
            tele.tracer.request_lifecycle(req)

    def _fail(self, req: Request, error: str) -> None:
        self._finalize(req, RequestStatus.FAILED, error=error)

    def _reap_deadlines(self) -> None:
        now = time.perf_counter()
        for req in [r for r in self.queue if r.deadline_exceeded(now)]:
            self._finalize(req, RequestStatus.TIMED_OUT)
        for req in [
            r for r in list(self.running.values()) if r.deadline_exceeded(now)
        ]:
            self._finalize(req, RequestStatus.TIMED_OUT)

    # -- preemption (optimistic admission) -----------------------------------

    def _victim_cost(self, req: Request) -> float:
        """Class-priced eviction cost (multiclass only): what preempting
        this request throws away, weighted by its class — resident
        tokens (prompt + generated so far, the recompute bill) times the
        class weight, so a gold:4 request prices 4x the identical
        bronze one. `victim_pricer` (api.py builds it from the
        CostModel) replaces the token count with a modeled recompute
        cost; the class weight still multiplies it."""
        base = float(len(req.prompt) + len(req.generated))
        if self._victim_pricer is not None:
            try:
                base = float(self._victim_pricer(self.cache, req))
            except Exception:
                pass  # a broken pricer must not break preemption
        return base * self.classes[self._class_of(req)].weight

    def _pick_victim(self) -> Optional[Request]:
        """Youngest-by-admission running request — the vLLM victim rule:
        the newest sequence has the least recompute to lose and, under
        FIFO, the weakest fairness claim. (admit_iter, rid) makes the
        choice deterministic within an admission batch.

        Multiclass flips the rule to cheapest-by-class-priced-cost
        (`_victim_cost`): evict what costs least to redo, priced by
        class weight. Equal cost falls back to the SAME youngest-first
        key — (-admit_iter, -rid) under min() — so ties are
        deterministic by admission order and chaos schedules replay
        exactly."""
        if not self.running:
            return None
        if self._multiclass:
            return min(
                self.running.values(),
                key=lambda r: (self._victim_cost(r), -r.admit_iter, -r.rid),
            )
        return max(
            self.running.values(), key=lambda r: (r.admit_iter, r.rid)
        )

    def _preempt(
        self, req: Request, cause: str = "pool", allow_swap: bool = True
    ) -> None:
        """Reclaim the victim's slot and pages and requeue it at the
        queue HEAD. With kv_swap the victim's committed pages ride the
        host link out (`swap_out`) and restore page-for-page at
        re-admission — no re-prefill; every refusal along that path
        (ineligible victim, cost decider, swap budget, injected
        swap_fail, in-flight step) degrades to prefill-from-recompute
        (prompt + generated so far). A request preempted more than
        `max_preemptions` times hard-fails instead — the bound that
        turns a livelock into a diagnosable error — and the failure
        carries the triggering cause (forensics contract)."""
        req.preemptions += 1
        self.stats.preemptions += 1
        if req.preemptions > self.max_preemptions:
            self._fail(
                req,
                f"preempted {req.preemptions} times "
                f"(max_preemptions {self.max_preemptions}; "
                f"last cause={cause})",
            )
            return
        req.status = RequestStatus.PREEMPTED
        if self.proposer is not None:
            self.proposer.retire(req)
        del self.running[req.slot]
        if self.adapters is not None:
            self.adapters.detach(req.slot)
        action = "recompute"
        if allow_swap and self._swap_eligible(req):
            handle = self.cache.swap_out(req.slot)
            if handle is not None:  # None: budget/in-flight refusal
                req.swap_handle = handle
                action = "swap"
        if action == "recompute":
            self.cache.free(req.slot)
        req.slot = None
        req.log(
            "preempt", f"cause={cause} action={action} iteration {self._iter}"
        )
        if self._tele is not None:
            reg = self._tele.registry
            reg.counter(
                "serve_preemptions_total",
                help="preempt-and-requeue events (optimistic admission)",
            ).inc()
            reg.counter(
                "serve_preemptions_total",
                help="preempt-and-requeue events (optimistic admission)",
                labels={"cause": cause, "action": action},
            ).inc()
        req.status = RequestStatus.QUEUED
        self.queue.appendleft(req)

    def _swap_eligible(self, req: Request) -> bool:
        """Whether this victim's KV should ride the host link instead of
        being recomputed: swap must be ON and the layout paged, the
        slot's committed history worth saving (generated tokens exist
        and no prefill is mid-stream — a half-prefilled slot recomputes
        its chunks anyway), the injector must not fail the swap-out,
        and the cost decider must prefer the copy over the recompute."""
        if not self.kv_swap or not getattr(self.cache, "paged", False):
            return False
        if req.slot is None or not req.generated:
            return False
        if self._prefill_pending(req):
            return False
        if self.injector is not None and self.injector.maybe_swap_fail(
            "swap_out"
        ):
            return False
        if self.swap_decider is not None:
            try:
                if not self.swap_decider(self.cache, req):
                    return False
            except Exception:
                return False  # a broken decider must not lose requests
        return True

    def _secure_pages(self, widths: Dict[int, int]) -> None:
        """Claim every page this iteration's step will touch BEFORE the
        jitted call: slot s writes rows lengths[s] .. lengths[s] +
        widths[s] - 1. Under reserve admission the claims are guaranteed
        (a PagePoolExhausted here means something outside the accounting
        drained the pool — an injected fault — and fails just that
        slot); under optimistic admission a dry pool preempts the
        youngest victim and retries, so the engine's own ensure_position
        calls always find the pages already present."""
        if not getattr(self.cache, "paged", False):
            return
        for slot in sorted(widths):
            req = self.running.get(slot)
            if req is None:
                continue
            start = int(self.cache.lengths[slot])
            pos = start
            while req.status == RequestStatus.RUNNING and (
                pos < start + widths[slot]
            ):
                try:
                    self.cache.ensure_position(slot, pos)
                    pos += 1
                except PagePoolExhausted as e:
                    # pages pinned by an in-flight step return to the
                    # pool once that step reconciles — drain the
                    # pipeline (async loop; sync has nothing in flight)
                    # and retry before resorting to preemption
                    if self._reclaim_inflight_pages():
                        continue
                    if self.admission != "optimistic":
                        self._fail(req, str(e))
                        break
                    victim = self._pick_victim()
                    if victim is None:
                        self._fail(req, str(e))
                        break
                    self._preempt(victim)
                    # preempting may have evicted `req` itself (it was
                    # the youngest); its requeue ends the claim loop

    def _reclaim_inflight_pages(self) -> bool:
        """Hook for the async loop: reconcile any in-flight step so its
        pinned (limbo) pages return to the free pool. The sync
        schedulers never have a step in flight — nothing to reclaim."""
        return False

    def _admit_swapped(self, req: Request) -> bool:
        """Re-admit a host-swapped queue head: restore its staged pages
        into a fresh slot (no re-prefill — the stream resumes at the
        next decode from generated[-1], and cache.lengths resumes at
        len(prompt) + len(generated) - 1, exactly where free() left it).
        An injected swap_in failure discards the staged copy and sends
        the head back through the normal recompute path — degraded,
        never lost. Returns False when no host can take it right now
        (FIFO: the queue holds behind the head)."""
        if self.injector is not None and self.injector.maybe_swap_fail(
            "swap_in"
        ):
            self.cache.discard_swap(req.swap_handle)
            req.swap_handle = None
            req.log(
                "swap_in_fail",
                f"iteration {self._iter} -> recompute re-admission",
            )
            return True  # head re-enters the loop on the normal path
        # restores are always conservative (reserve the full remaining
        # footprint), even under optimistic admission: a restore that
        # gets re-evicted at the next boundary crossing made no
        # progress but paid the host round-trip twice — bring the
        # stream back only when it can run to completion
        slot = self.cache.swap_in(
            req.swap_handle,
            total_len=len(req.prompt) + req.max_new_tokens,
            optimistic=False,
        )
        if slot is None:
            return False  # handle stays valid for a later iteration
        self._dequeue(req)
        req.swap_handle = None
        req.slot = slot
        req.admit_iter = self._iter
        req.status = RequestStatus.RUNNING
        if self.adapters is not None:
            self.adapters.attach(slot, req.adapter_id)
        # any stale chunk cursors die with the swap restore: the full
        # committed history is already resident, nothing left to stream
        req.prefill_seq = []
        req.prefill_pos = 0
        req.prefill_dispatched = 0
        req.log("admit", f"slot {slot} swap_in")
        self.running[slot] = req
        if self.proposer is not None:
            # the draft cache holds no swapped copy — the proposer
            # re-prefills its side from the committed history (a cold
            # draft degrades acceptance, never correctness)
            self.proposer.admit([req])
        self.stats.peak_in_flight = max(
            self.stats.peak_in_flight, len(self.running)
        )
        return True

    # -- host-failure drain --------------------------------------------------

    def host_down(self, host: int) -> None:
        """Drain a lost host partition: reap its RUNNING requests to
        PREEMPTED (recompute — the dead host's pool content is gone
        with it; queued requests already swapped to host RAM still
        restore on survivors), refuse it new admissions, and stamp the
        event in telemetry. The per-host invariants keep re-deriving
        every iteration: the dead partition's ledgers stay consistent,
        just unused, so recovery is mark_host_up and nothing else."""
        cache = self.cache
        if not getattr(cache, "paged", False) or cache.num_hosts <= 1:
            raise ValueError(
                "host_down needs a multi-host paged partition"
            )
        t0 = time.perf_counter()
        # in-flight steps may still reference the dying host's slots —
        # drain the pipeline first, same discipline as _secure_pages
        self._reclaim_inflight_pages()
        cache.mark_host_down(host)
        self.stats.host_downs += 1
        victims = sorted(
            (
                r
                for r in self.running.values()
                if cache.host_of_slot(r.slot) == host
            ),
            key=lambda r: (r.admit_iter, r.rid),
        )
        for req in victims:
            # the partition is lost: its device pages cannot be staged
            # out, so the drain always recomputes
            self._preempt(req, cause="host_down", allow_swap=False)
        if self._tele is not None:
            tele = self._tele
            tele.registry.counter(
                "serve_host_down_total",
                help="host partitions drained after an injected failure",
                labels={"host": str(host)},
            ).inc()
            tele.tracer.complete(
                "host_down drain",
                f"host{host}",
                t0,
                time.perf_counter(),
                tid=tele.tracer.host_lane(host),
                args={"host": host, "reaped": len(victims)},
            )

    def host_up(self, host: int) -> None:
        """Re-join a recovered host partition into admission."""
        self.cache.mark_host_up(host)

    # -- cross-engine seams (disaggregated front door) -----------------------

    def stage_out(self, rid: int) -> Optional[int]:
        """Stage a RUNNING request's committed KV out of this engine and
        detach the request, WITHOUT a terminal transition: the caller
        owns the returned swap handle (export it with
        ``cache.export_swap`` to move the pages into another engine) and
        the Request object itself, which re-submits elsewhere with its
        stream intact. This is the prefill-tier half of the
        prefill→decode handoff. Returns None when the request is
        unknown, terminal, not resident, or the cache refuses the copy
        (budget / in-flight step) — the caller retries a later
        iteration; nothing is lost or half-moved."""
        req = self._by_rid.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return None
        if req.slot is None or self.running.get(req.slot) is not req:
            return None
        # pages pinned by an in-flight step would tear mid-copy — drain
        # the pipeline first, same discipline as host_down
        self._reclaim_inflight_pages()
        if req.status in TERMINAL_STATUSES or req.slot is None:
            return None  # reconcile finished/cancelled it
        handle = self.cache.swap_out(req.slot)
        if handle is None:
            return None
        if self.proposer is not None:
            self.proposer.retire(req)
        del self.running[req.slot]
        if self.adapters is not None:
            self.adapters.detach(req.slot)
        del self._by_rid[rid]
        req.slot = None
        req.status = RequestStatus.QUEUED
        req.swap_handle = handle
        # chunk cursors die with the move: the staged copy IS the
        # committed history, nothing left to stream on this engine
        req.prefill_seq = []
        req.prefill_pos = 0
        req.prefill_dispatched = 0
        req.log("stage_out", f"handle {handle} iteration {self._iter}")
        return handle

    def evacuate(self) -> List[Request]:
        """Detach every live request from this engine — the replica-kill
        drain. RUNNING requests drop their device state (the dead
        replica's pool dies with it: no stage-out) and return to QUEUED
        with recompute cursors; queued requests holding swap handles
        discard them (staged copies live in the dead replica's ledger).
        Returns the detached requests in FIFO order (running by
        admission order, then the queue) for the router to re-submit on
        survivors. Not a preemption — the requests never failed, the
        hardware did — so `preemptions` budgets don't tick."""
        self._reclaim_inflight_pages()
        if self.journal is not None:
            # the movers' committed tokens must be durable under THIS
            # journal before they re-enter another scheduler (which may
            # journal elsewhere, or not at all)
            self.journal.commit_pending(self._iter)
        moved: List[Request] = []
        for req in sorted(
            self.running.values(), key=lambda r: (r.admit_iter, r.rid)
        ):
            if self.proposer is not None:
                self.proposer.retire(req)
            if self.adapters is not None:
                self.adapters.detach(req.slot)
            self.cache.free(req.slot)
            req.slot = None
            req.status = RequestStatus.QUEUED
            req.prefill_seq = []
            req.prefill_pos = 0
            req.prefill_dispatched = 0
            req.log("evacuate", f"replica_down iteration {self._iter}")
            moved.append(req)
        self.running.clear()
        for req in self.queue:
            if req.swap_handle is not None:
                self.cache.discard_swap(req.swap_handle)
                req.swap_handle = None
            req.log("evacuate", f"replica_down iteration {self._iter}")
            moved.append(req)
        self.queue.clear()
        for req in moved:
            self._by_rid.pop(req.rid, None)
        return moved

    # -- shared pieces -------------------------------------------------------

    def _dequeue(self, req: Request) -> None:
        """Identity-based queue removal (the multiclass head need not be
        the GLOBAL front; dataclass __eq__ could drop a twin)."""
        for i, queued in enumerate(self.queue):
            if queued is req:
                del self.queue[i]
                return

    def _admission_head(self):
        """The next request admission should try: the global queue front
        (FIFO), or under multiclass the DRR-selected class's front —
        per-class FIFO is the global queue filtered by class, so a
        preempted request's appendleft keeps it at its class front.
        Returns (request, drr_commit) where drr_commit is the closure
        that charges the serve IF the admit lands (select is pure:
        a blocked head charges nothing)."""
        if not self._multiclass:
            return self.queue[0], None
        heads: Dict[str, Request] = {}
        for r in self.queue:
            c = self._class_of(r)
            if c not in heads:
                heads[c] = r
        backlogged = list(heads)
        self._admit_drr.settle(backlogged)
        name, rounds = self._admit_drr.select({c: 1.0 for c in backlogged})

        def commit(drr=self._admit_drr):
            drr.charge(name, rounds, backlogged, cost=1.0)

        return heads[name], commit

    def _admit(self, limit: Optional[int] = None) -> List[Request]:
        """FIFO admission into free slots (never reorders the queue —
        starvation-free: the head either admits or blocks everyone
        behind it) + ONE prefill batch for the admitted set. Admission
        asks the cache, so the gate is layout-specific: the slot layout
        admits while a slot is free; the paged layout also requires
        enough free PAGES — the request's worst case under the reserve
        policy, only its immediate need under the optimistic one. A
        preempted request re-admits with its recompute sequence
        (prompt + tokens already generated): the prefill rebuilds the
        KV it lost and its next token comes out of that same call.

        Multiclass (`classes` with >1 entry) replaces WHICH head is
        tried — deficit round-robin across per-class FIFO queues, so a
        gold:4 class admits ~4x bronze under contention while every
        backlogged class still serves within bounded rounds — but not
        the blocking rule: a selected head that cannot take a slot NOW
        stops admission for everyone (no bypass), exactly the single-
        class no-reorder guarantee, just applied to the DRR order."""
        optimistic = self.admission == "optimistic"
        prefix = bool(getattr(self.cache, "prefix_cache", False))
        admitted: List[Request] = []
        seqs: List[List[int]] = []
        cursors: List[int] = []
        while self.queue:
            if limit is not None and len(admitted) >= limit:
                break
            req, drr_commit = self._admission_head()
            if req.swap_handle is not None:
                # host-swapped victim: restore its pages instead of
                # recomputing them — it joins running directly (its
                # stream resumes at the next decode), never the prefill
                # batch below
                if not self._admit_swapped(req):
                    break  # no host can take it NOW — FIFO holds
                if drr_commit is not None and (
                    req.status == RequestStatus.RUNNING
                ):
                    # charge only a LANDED restore (an injected
                    # swap_in failure re-routes through the normal
                    # path without consuming the class's turn)
                    drr_commit()
                continue
            seq = list(req.prompt) + list(req.generated)
            # chunked admission claims pages chunk by chunk (the step's
            # page claims), so nothing is needed NOW — the reserve
            # policy still gates on the same worst case either way
            if prefix:
                # prefix-sharing admission: registered pages matching a
                # prefix of the sequence map into the slot's table and
                # the cursor skips them (prefill recomputes the rest)
                res = self.cache.alloc_shared(
                    seq,
                    prompt_len=0 if self.token_budget else len(seq),
                    total_len=len(req.prompt) + req.max_new_tokens,
                    optimistic=optimistic,
                )
                slot, cursor = (None, 0) if res is None else res
            else:
                slot = self.cache.alloc(
                    0 if self.token_budget else len(seq),
                    len(req.prompt) + req.max_new_tokens,
                    optimistic=optimistic,
                )
                cursor = 0
            if slot is None:
                break
            self._dequeue(req)
            req.slot = slot
            req.admit_iter = self._iter
            req.status = RequestStatus.RUNNING
            if self.adapters is not None:
                self.adapters.attach(slot, req.adapter_id)
            if drr_commit is not None:
                drr_commit()
            req.log(
                "admit",
                f"slot {slot}" + (f" shared {cursor}" if cursor else ""),
            )
            self.running[req.slot] = req
            admitted.append(req)
            seqs.append(seq)
            cursors.append(cursor)
        self.stats.peak_in_flight = max(
            self.stats.peak_in_flight, len(self.running)
        )
        if admitted:
            if self.proposer is not None:
                self.proposer.admit(admitted)
            if self.token_budget:
                # chunked admission: NO monolithic prefill — arm the
                # chunk cursors and let the per-iteration planner
                # stream the sequence in. A preempted request re-admits
                # here too: its recompute sequence (prompt + generated)
                # replaces the old prefill_seq and the cursors restart.
                # Shared admissions start their cursors AT the shared
                # extent: alloc_shared left cache.lengths there, so the
                # planner streams only the unshared suffix.
                for req, seq, cur in zip(admitted, seqs, cursors):
                    req.prefill_seq = [int(t) for t in seq]
                    req.prefill_pos = cur
                    req.prefill_dispatched = cur
                return admitted
            try:
                plain = [i for i, c in enumerate(cursors) if c == 0]
                shared = [i for i, c in enumerate(cursors) if c > 0]
                rows: Dict[int, Tuple[int, np.ndarray]] = {}
                if plain:
                    nxt_p, last_p = self.engine.prefill(
                        self.params,
                        [seqs[i] for i in plain],
                        [admitted[i].slot for i in plain],
                    )
                    for j, i in enumerate(plain):
                        rows[i] = (int(nxt_p[j]), np.asarray(last_p[j]))
                if shared:
                    # shared slots recompute only tokens[cursor:] — the
                    # mapped pages already hold the prefix KV rows
                    nxt_s, last_s = self.engine.prefill_suffix(
                        self.params,
                        [seqs[i] for i in shared],
                        [admitted[i].slot for i in shared],
                        [cursors[i] for i in shared],
                    )
                    for j, i in enumerate(shared):
                        rows[i] = (int(nxt_s[j]), np.asarray(last_s[j]))
                nxt = np.array([rows[i][0] for i in range(len(admitted))])
                last = np.stack(
                    [rows[i][1] for i in range(len(admitted))]
                )
            except Exception as e:  # fault isolation: the batch fails,
                # in-flight slots are untouched and keep decoding
                self.stats.step_faults += 1
                for req in admitted:
                    self._fail(req, f"prefill failed: {e!r}")
                return admitted
            self.stats.prefill_batches += 1
            if prefix:
                # publish AFTER the prefill returned: a failed dispatch
                # must never leave hash keys pointing at pages whose
                # writes never executed
                for req, seq in zip(admitted, seqs):
                    self.cache.register_prefix(req.slot, seq, len(seq))
            if self.injector is not None:
                # np.array (copy): the step's output buffer is read-only
                last = np.array(last)
                self.injector.corrupt_logits(
                    last,
                    [r.slot for r in admitted],
                    rows=range(len(admitted)),
                )
            for i, (tok, req) in enumerate(zip(nxt, admitted)):
                if not np.isfinite(last[i]).all():
                    self._fail(
                        req,
                        f"non-finite prefill logits at iteration "
                        f"{self._iter}",
                    )
                    continue
                self._emit(req, int(tok))
        return admitted

    def _emit(self, req: Request, token: int) -> None:
        req.generated.append(token)
        if self.journal is not None:
            # journal-before-publish (fxlint FX111): _emit is the ONLY
            # writer of the stream-visible token list, and it notes
            # every token into the journal's pending buffer here —
            # _end_iteration flushes the buffer as commit records before
            # step() returns, so no front door can publish a token the
            # journal never saw
            self.journal.note(req.rid, token)
        if len(req.generated) == 1:
            req.first_token_time = time.perf_counter()
            req.log("first_token")
            req.last_token_time = req.first_token_time
        elif self._tele is not None:
            # inter-token latency: the gap between consecutive COMMITs
            # of one request's tokens (verify emits several per gap —
            # each counts, which is exactly how speculation compresses
            # the latency a user streams at). Telemetry-only: the
            # per-token clock read is the kind of hot-path cost the
            # disabled path must not pay.
            now = time.perf_counter()
            if req.last_token_time:
                self._tele.slo.observe_itl(now - req.last_token_time)
                mon = self._class_slo.get(self._class_of(req))
                if mon is not None:
                    mon.observe_itl(now - req.last_token_time)
            req.last_token_time = now
        self.stats.tokens_generated += 1
        if req._done_after(token):
            self._finalize(req, RequestStatus.FINISHED)

    def _fail_all_running(self, error: str) -> None:
        """Whole-step engine fault with no slot attribution: retire every
        participant with the captured error rather than crash the run —
        the queue behind them keeps serving."""
        self.stats.step_faults += 1
        for req in list(self.running.values()):
            self._fail(req, error)

    def _note_dispatch(self, step) -> None:
        self.stats.dispatch_count += 1
        # dispatch sequence number: the trace layer's step index (device
        # in-flight windows alternate lanes by its parity)
        step.seq = int(self.stats.dispatch_count)
        if self._last_dispatch_t is not None:
            self.stats.dispatch_gap_sum_s += (
                step.dispatch_t - self._last_dispatch_t
            )
        self._last_dispatch_t = step.dispatch_t

    def _decode_dispatch_step(self, chain=None):
        """Dispatch phase of one decode iteration: claim every page the
        step will touch, build the token/active arrays from the LIVE
        view (this side of the dispatch/reconcile split may read
        mutable state — the snapshot is taken here), and enqueue the
        jitted step. `chain` device-chains input tokens from a
        still-in-flight previous step (async loop): slots whose last
        token is that step's not-yet-materialized output read it on
        device instead of from the host. Returns the InflightStep, or
        None when there is nothing to step."""
        # predicted-view budget gate: a slot whose still-in-flight step
        # will emit its FINAL budgeted token has nothing useful to
        # compute here — the commit-phase identity check would discard
        # the result anyway. EOS is not predictable at dispatch time, so
        # an EOS retire still costs one wasted (discarded) slot-step.
        stepped: Dict[int, Request] = {}
        for slot, req in self.running.items():
            if self._prefill_pending(req) or slot in self._chunk_unlocked:
                continue  # chunked prefill: no decode until the last
                #            chunk's token has committed, and none in
                #            the commit's own iteration (its tokens
                #            were never in this budget's plan)
            chained = (
                chain is not None
                and chain.kind == "decode"
                and chain.active[slot]
                and chain.participants.get(slot) is req
            )
            if len(req.generated) + int(chained) >= req.max_new_tokens:
                continue
            stepped[slot] = req
        self._secure_pages({slot: 1 for slot in stepped})
        stepped = {s: r for s, r in stepped.items() if self.running.get(s) is r}
        if not stepped:
            return None
        spec = self.cache.spec
        tokens = np.zeros(spec.max_seqs, dtype=np.int32)
        active = np.zeros(spec.max_seqs, dtype=bool)
        chain_mask = np.zeros(spec.max_seqs, dtype=bool)
        for slot, req in stepped.items():
            tokens[slot] = req.generated[-1]
            active[slot] = True
            if (
                chain is not None
                and chain.kind == "decode"
                and chain.active[slot]
                and chain.participants.get(slot) is req
            ):
                chain_mask[slot] = True
        t0 = time.perf_counter()
        try:
            step = self.engine.decode_dispatch(
                self.params,
                tokens,
                active,
                chain=chain,
                chain_mask=chain_mask if chain is not None else None,
            )
        except Exception as e:
            self._fail_all_running(f"decode step failed: {e!r}")
            return None
        if self._tele is not None:
            self._tele.tracer.complete(
                "dispatch:decode",
                "host",
                t0,
                time.perf_counter(),
                args={"iter": self._iter, "active": int(active.sum())},
            )
        step.iteration = self._iter
        step.participants = stepped
        self._note_dispatch(step)
        self.stats.decode_steps += 1
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += int(active.sum())
        self._budget_used_iter += int(active.sum())
        return step

    def _reconcile_step(self, step) -> None:
        """Reconcile phase: block on the step's device outputs, then
        commit its results — under the async loop this runs one
        iteration after the dispatch, against the step's snapshot."""
        t0 = time.perf_counter()
        self.stats.overlapped_host_s += max(0.0, t0 - step.dispatch_t)
        try:
            if step.kind == "decode":
                nxt, logits = self.engine.decode_reconcile(step)
            elif step.kind == "chunk":
                nxt, logits = self.engine.prefill_chunk_reconcile(step)
            elif step.kind == "multistep":
                toks_ks, logits_ks, mask_ks = self.engine.decode_multi_reconcile(
                    step
                )
            else:
                logits = self.engine.verify_reconcile(step)
        except Exception as e:
            self._fail_all_running(f"{step.kind} step failed: {e!r}")
            return
        t1 = time.perf_counter()
        self.stats.commit_wait_s += t1 - t0
        # every reconcile is exactly one host round-trip, whatever the
        # step's width — the denominator of host_syncs_per_token
        self.stats.host_syncs += 1
        if step.kind == "decode":
            self._commit_decode(step, nxt, logits)
        elif step.kind == "chunk":
            self._commit_chunk(step, nxt, logits)
        elif step.kind == "multistep":
            self._commit_multistep(step, toks_ks, logits_ks, mask_ks)
        elif step.kind == "verify_tree":
            self._commit_verify_tree(step, logits)
        else:
            self._commit_verify(step, logits)
        if self._tele is not None:
            # trace the step's whole in-flight window (dispatch →
            # outputs materialized) on a device lane, and the host-side
            # reconcile (block + commit) on the host lane — everything
            # read here comes off the step record, never live cache
            # state (fxlint FX103)
            tr = self._tele.tracer
            tr.device_window(
                f"multistep[{int(step.k_steps)}]"
                if step.kind == "multistep"
                else step.kind,
                step.seq,
                step.dispatch_t,
                t1,
                args={"iter": step.iteration},
            )
            tr.complete(
                f"reconcile:{step.kind}",
                "host",
                t0,
                time.perf_counter(),
                args={"iter": step.iteration, "step": step.seq},
            )

    def _commit_decode(self, step, nxt, logits) -> None:
        """Commit a reconciled decode step: NaN isolation, token emit,
        EOS/budget retirement. Reads ONLY the step's snapshot — live
        scheduler/cache state is an iteration ahead under the async
        loop (fxlint FX103 holds this path to the snapshot). A
        participant that retired, was preempted, or whose slot was
        re-admitted while the step was in flight fails the identity
        check and its speculative token is discarded."""
        active_slots = [s for s, a in enumerate(step.active) if a]
        if self.injector is not None:
            logits = np.array(logits)  # writable copy for the injector
            self.injector.corrupt_logits(
                logits, active_slots, iteration=step.iteration
            )
        for slot in active_slots:
            req = step.participants.get(slot)
            if req is None or self.running.get(slot) is not req:
                continue
            if not np.isfinite(logits[slot]).all():
                self._fail(
                    req,
                    f"non-finite logits at iteration {step.iteration}",
                )
                continue
            self._emit(req, int(nxt[slot]))

    def _decode_once(self) -> None:
        """Synchronous decode iteration — dispatch + immediate
        reconcile (the reference loop the async engine is proved
        token-identical against)."""
        step = self._decode_dispatch_step()
        if step is not None:
            self._reconcile_step(step)

    # -- device-resident multi-step decode (decode_multistep=True) -----------

    def _fusable_steps(self) -> int:
        """How many decode steps the NEXT dispatch may fuse into one
        device-resident scan window: `max_fused_steps` when no
        host-visible event can need the host mid-window, else 1. The
        events that hold fusing to a single step: a speculative
        iteration with live drafts (a verify's acceptance is host
        logic), a non-empty queue (admission next iteration changes
        the batch), optimistic admission (preemption must never
        coexist with an open window), any chunk streaming in progress
        or a final chunk that just committed (phase changes), and
        deferred cancels waiting on a reconcile. Deadlines
        deliberately do NOT hold fusing: a deadline expiring
        mid-window reaps at the window's reconcile — at most K-1 steps
        of wasted (discarded) device work, the same one-step-stale
        contract the async loop already carries. Per-slot EOS and
        page-boundary caps are handled inside the window itself
        (`_decode_multi_dispatch_step`), not here.

        Speculation holds fusing only while it has something to
        verify: a STATELESS proposer is dry-run here (result cached
        for `_verify_once`, nothing drafts twice) and an iteration
        where no slot drafted — cold n-gram table, post-rollback gap —
        fuses exactly like plain decode. A stateful proposer keeps the
        unconditional one-step hold: its draft cache must advance with
        every committed token. A fused draft+verify round (one device
        window that drafts AND scores) would relax the live-drafts
        hold too; the tree-verify mask is already threaded as data
        (`InflightStep.tree_parents`), which is the seam such a fused
        kernel would dispatch through."""
        if not self.decode_multistep or self.max_fused_steps <= 1:
            return 1
        if self.queue:
            return 1
        if self.admission == "optimistic":
            return 1
        if self._chunk_unlocked:
            return 1
        if any(self._prefill_pending(r) for r in self.running.values()):
            return 1
        if getattr(self, "_pending_cancels", None):
            return 1
        if self.proposer is not None:
            if not getattr(self.proposer, "stateless", False):
                return 1
            if self._dry_propose():
                return 1
        return int(self.max_fused_steps)

    def _dry_propose(self) -> bool:
        """Draft for this iteration ahead of the fuse/verify decision
        and cache the result for `_verify_once`; True when any slot has
        a live draft (speculation needs the per-iteration host sync)."""
        if self.spec_branch > 1:
            trees = self._propose_trees()
            self._cached_proposals = ("tree", trees)
            return any(len(t.tokens) > 0 for t in trees.values())
        proposals = self._propose(self.spec_k)
        self._cached_proposals = ("linear", proposals)
        return any(len(d) > 0 for d in proposals.values())

    def _decode_multi_dispatch_step(self, k: int):
        """Dispatch phase of one fused K-step decode window: per slot,
        cap the window depth at the request's remaining token budget,
        the cache horizon, and (paged layout) the distance to the next
        page boundary — so the window claims AT MOST one fresh page per
        slot, which `_secure_pages` handles exactly like a plain decode
        step's claim. Every cache read on this side goes through
        `int()`/`np` snapshots (fxlint FX109a): the scan then runs K
        steps device-side against this dispatch's snapshot, carrying
        sampling, EOS detection, and length bumps in the scan state.
        Returns the InflightStep, or None when there is nothing to
        step."""
        stepped: Dict[int, Request] = {}
        limits: Dict[int, int] = {}
        ps = int(getattr(self.cache.spec, "page_size", 0) or 0)
        max_len = self.cache.spec.max_len
        for slot, req in self.running.items():
            if self._prefill_pending(req) or slot in self._chunk_unlocked:
                continue
            cur_len = int(self.cache.lengths[slot])
            cap = min(
                k,
                req.max_new_tokens - len(req.generated),
                max_len - cur_len,
            )
            if ps:
                # page-boundary truncation: the window ends where the
                # slot's next fresh page would begin
                cap = min(cap, ps - (cur_len % ps))
            if cap >= 1:
                stepped[slot] = req
                limits[slot] = cap
        self._secure_pages({slot: 1 for slot in stepped})
        stepped = {s: r for s, r in stepped.items() if self.running.get(s) is r}
        if not stepped:
            return None
        spec = self.cache.spec
        tokens = np.zeros(spec.max_seqs, dtype=np.int32)
        active = np.zeros(spec.max_seqs, dtype=bool)
        step_limits = np.zeros(spec.max_seqs, dtype=np.int32)
        eos = np.full(spec.max_seqs, -1, dtype=np.int32)
        for slot, req in stepped.items():
            tokens[slot] = req.generated[-1]
            active[slot] = True
            step_limits[slot] = limits[slot]
            if req.eos_token is not None:
                eos[slot] = int(req.eos_token)
        t0 = time.perf_counter()
        try:
            step = self.engine.decode_multi_dispatch(
                self.params, tokens, active, step_limits, eos_tokens=eos
            )
        except Exception as e:
            self._fail_all_running(f"multistep decode failed: {e!r}")
            return None
        kmax = int(step.k_steps)
        if self._tele is not None:
            tele = self._tele
            tele.tracer.complete(
                "dispatch:multistep",
                "host",
                t0,
                time.perf_counter(),
                args={
                    "iter": self._iter,
                    "active": int(active.sum()),
                    "k": kmax,
                },
            )
            reg = tele.registry
            reg.counter(
                "serve_multistep_windows_total",
                help="fused K-step decode windows dispatched",
            ).inc()
            reg.counter(
                "serve_multistep_steps_total",
                help="decode steps executed inside fused windows",
            ).inc(kmax)
            reg.histogram(
                "serve_multistep_window_size",
                bounds=(1, 2, 4, 8, 16, 32, 64),
                help="fused-window depth K per dispatched window",
            ).observe(float(kmax))
        step.iteration = self._iter
        step.participants = stepped
        self._note_dispatch(step)
        stats = self.stats
        stats.multistep_windows += 1
        stats.multistep_steps += kmax
        stats.decode_steps += kmax
        stats.slot_steps += spec.max_seqs * kmax
        stats.busy_slot_steps += int(step_limits.sum())
        self._budget_used_iter += int(step_limits.sum())
        return step

    def _commit_multistep(self, step, toks_ks, logits_ks, mask_ks) -> None:
        """Commit a reconciled K-step window: per slot, roll the cache
        back from the dispatch-time pre-advance to the length the scan
        actually took (an in-scan EOS hit clears the per-step mask for
        every later step, so `taken` lands exactly at the EOS
        position), then emit the taken tokens in order. Rollback runs
        BEFORE emitting: _emit may retire the request, which frees the
        slot (truncating a freed slot would be an error). Reads ONLY
        the step record — pre-step lengths, per-slot limits, and the
        per-step token/logit/mask stacks all ride the InflightStep
        (fxlint FX103/FX109b); live cache state is a full window
        ahead."""
        active_slots = [s for s, a in enumerate(step.active) if a]
        if self.injector is not None:
            logits_ks = np.array(logits_ks)  # writable copy
            self.injector.corrupt_logits(
                logits_ks[0], active_slots, iteration=step.iteration
            )
        for slot in active_slots:
            req = step.participants.get(slot)
            if req is None or self.running.get(slot) is not req:
                continue
            taken = int(mask_ks[:, slot].sum())
            if taken < int(step.step_limits[slot]):
                # EOS retired the slot mid-window: return the unused
                # pre-advanced rows (paged slots give surplus pages
                # back to the reserve) before any emit can free it
                self.cache.truncate(slot, int(step.lengths[slot]) + taken)
            for i in range(taken):
                if not np.isfinite(logits_ks[i, slot]).all():
                    self._fail(
                        req,
                        f"non-finite logits at iteration "
                        f"{step.iteration} (window step {i})",
                    )
                    break
                self._emit(req, int(toks_ks[i, slot]))
                if self.running.get(slot) is not req:
                    break  # retired (EOS/budget) — nothing past it

    def _propose(self, k: int) -> Dict[int, List[int]]:
        """Draft tokens for the running slots; a proposer fault (real or
        injected) degrades THIS iteration to plain decode — empty
        proposals make every verify a w=1 decode — instead of killing
        the run."""
        t0 = time.perf_counter()
        # chunked prefill: a slot mid-prefill has no committed history
        # to draft from — exclude it until its last chunk lands
        draftable = {
            s: r
            for s, r in self.running.items()
            if not self._prefill_pending(r) and s not in self._chunk_unlocked
        }
        try:
            if self.injector is not None:
                self.injector.maybe_draft_fault()
            proposals = self.proposer.propose(draftable, k)
        except Exception:
            self.stats.draft_faults += 1
            return {}
        if self._tele is not None:
            self._tele.tracer.complete(
                "draft:propose",
                "host",
                t0,
                time.perf_counter(),
                args={"iter": self._iter, "slots": len(proposals)},
            )
        return proposals

    def _verify_dispatch_step(self, proposals):
        """Dispatch phase of one speculative iteration: cap each slot's
        drafts to its remaining budget and the cache horizon (live
        reads — this is the dispatch side), claim every page the verify
        writes, and enqueue the batched verify. Returns the
        InflightStep (carrying the draft plan + the pre-step lengths
        snapshot acceptance needs), or None when nothing runs."""
        spec = self.cache.spec
        k = self.spec_k
        plan: Dict[int, List[int]] = {}
        # chunked mode: the iteration's token budget also caps draft
        # widths — every verifying slot keeps its 1-token floor (the
        # budget can pace speculation, not starve decoding), then
        # drafts fit in what remains, first-come by slot id
        budget_left = self.token_budget if self.token_budget else None
        for slot, req in sorted(self.running.items()):
            if self._prefill_pending(req) or slot in self._chunk_unlocked:
                continue  # still streaming its prompt in (or its last
                #            chunk committed THIS iteration) — no verify
            old_len = int(self.cache.lengths[slot])
            # the verify emits up to k_s + 1 tokens and writes k_s + 1
            # rows, so k_s is capped by the request's remaining token
            # budget and by the cache horizon — which also keeps paged
            # verify inside the admission reserve's worst case
            k_s = min(
                len(proposals.get(slot) or ()),
                k,
                req.max_new_tokens - len(req.generated) - 1,
                spec.max_len - old_len - 1,
            )
            if budget_left is not None:
                k_s = min(k_s, max(0, budget_left - 1))
            plan[slot] = list(proposals.get(slot) or ())[: max(0, k_s)]
            if budget_left is not None:
                budget_left -= 1 + len(plan[slot])
        # claim pages for every row the verify writes; optimistic
        # preemption may evict plan slots, so the arrays build AFTER
        self._secure_pages({s: 1 + len(d) for s, d in plan.items()})
        plan = {s: d for s, d in plan.items() if s in self.running}
        if not plan:
            return None
        tokens = np.zeros((spec.max_seqs, k + 1), dtype=np.int32)
        draft_lens = np.zeros(spec.max_seqs, dtype=np.int32)
        for slot, drafts in plan.items():
            req = self.running[slot]
            tokens[slot, 0] = req.generated[-1]
            for j, t in enumerate(drafts):
                tokens[slot, 1 + j] = int(t)
            draft_lens[slot] = 1 + len(drafts)
        t0 = time.perf_counter()
        try:
            step = self.engine.verify_dispatch(
                self.params, tokens, draft_lens
            )
        except Exception as e:
            self._fail_all_running(f"verify step failed: {e!r}")
            return None
        if self._tele is not None:
            self._tele.tracer.complete(
                "dispatch:verify",
                "host",
                t0,
                time.perf_counter(),
                args={"iter": self._iter, "slots": len(plan)},
            )
        step.iteration = self._iter
        step.plan = plan
        step.participants = {s: self.running[s] for s in plan}
        self._note_dispatch(step)
        self.stats.verify_steps += 1
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += len(plan)
        self._budget_used_iter += int(draft_lens.sum())
        return step

    def _commit_verify(self, step, logits) -> None:
        """Commit a reconciled verify step: per slot accept a prefix of
        the drafts, roll the cache to the accepted length (paged slots
        return surplus pages), and emit accepted + 1 tokens. Acceptance
        runs against the step's SNAPSHOT lengths — the committed
        pre-step lengths — never the live cache view (fxlint FX103). A
        slot whose proposer had nothing degraded to draft_lens 1 —
        exactly a decode step. EOS inside the accepted run retires the
        request AT the EOS position: tokens past it are never emitted."""
        from flexflow_tpu.serving.spec import accept_drafts

        if self.injector is not None:
            logits = np.array(logits)  # writable copy for the injector
            self.injector.corrupt_logits(
                logits, sorted(step.plan), iteration=step.iteration
            )
        for slot in sorted(step.plan):
            req = step.participants.get(slot)
            if req is None or self.running.get(slot) is not req:
                continue
            drafts = step.plan[slot]
            old_len = int(step.lengths[slot])
            if not np.isfinite(logits[slot, : 1 + len(drafts)]).all():
                # lengths never advanced for this slot; freeing it
                # returns its pages, stale verify rows and all
                self._fail(
                    req,
                    f"non-finite logits at iteration {step.iteration}",
                )
                continue
            accepted, emitted = accept_drafts(
                logits[slot],
                drafts,
                temperature=self.engine.temperature,
                seed=self.engine.seed,
                slot=slot,
                base_len=old_len,
            )
            # commit the accepted prefix / roll back the rejected tail
            # BEFORE emitting: _emit may retire the request, which frees
            # the slot (truncating a freed slot would be an error)
            self.cache.truncate(slot, old_len + accepted + 1)
            self.proposer.rollback(slot, old_len + accepted + 1)
            self.stats.draft_tokens_proposed += len(drafts)
            self.stats.draft_tokens_accepted += accepted
            for t in emitted:
                self._emit(req, int(t))
                if req.finished:
                    break  # EOS mid-verify: nothing past it is emitted

    def _verify_once(self) -> None:
        """Synchronous speculative iteration: draft up to spec_k tokens
        per slot (a spec_branch-way tree under tree speculation),
        dispatch ONE batched verify, and reconcile it immediately.
        Consumes `_fusable_steps`' dry-proposal when one was cached
        this iteration, so the fuse-or-verify probe never drafts
        twice."""
        cached = self._cached_proposals
        self._cached_proposals = None
        if self.spec_branch > 1:
            trees = (
                cached[1]
                if cached is not None and cached[0] == "tree"
                else self._propose_trees()
            )
            step = self._verify_tree_dispatch_step(trees)
        else:
            proposals = (
                cached[1]
                if cached is not None and cached[0] == "linear"
                else self._propose(self.spec_k)
            )
            step = self._verify_dispatch_step(proposals)
        if step is not None:
            self._reconcile_step(step)

    # -- token-tree speculation (spec_branch > 1) ----------------------------

    def _propose_trees(self) -> Dict[int, object]:
        """Tree twin of _propose: draft one deduped token TREE per
        running slot (up to spec_k deep, spec_branch alternatives per
        level, shared prefixes merged). A proposer fault (real or
        injected) degrades THIS iteration to plain decode — empty
        trees make every verify row a w=1 decode — instead of killing
        the run."""
        t0 = time.perf_counter()
        draftable = {
            s: r
            for s, r in self.running.items()
            if not self._prefill_pending(r) and s not in self._chunk_unlocked
        }
        try:
            if self.injector is not None:
                self.injector.maybe_draft_fault()
            trees = self.proposer.propose_trees(
                draftable, self.spec_k, self.spec_branch
            )
        except Exception:
            self.stats.draft_faults += 1
            return {}
        if self._tele is not None:
            self._tele.tracer.complete(
                "draft:propose_tree",
                "host",
                t0,
                time.perf_counter(),
                args={"iter": self._iter, "slots": len(trees)},
            )
        return trees

    def _verify_tree_dispatch_step(self, trees):
        """Dispatch phase of one tree-speculative iteration: prune each
        slot's draft tree to its budget and horizon caps (live reads —
        this is the dispatch side), claim every page the verify's
        1 + nodes rows need, and enqueue ONE batched tree verify. The
        compiled width is FIXED at 1 + spec_k * spec_branch whatever
        shape the trees take — the topology rides in as a parent table
        (data), so per-iteration tree changes never recompile. Returns
        the InflightStep (carrying the per-slot DraftTree plan + the
        pre-step lengths snapshot acceptance needs), or None when
        nothing runs."""
        from flexflow_tpu.serving.spec import DraftTree

        spec = self.cache.spec
        w = 1 + self._tree_nodes
        plan: Dict[int, object] = {}
        # chunked mode: tree NODES are charged against the iteration's
        # token budget exactly like linear drafts — every verifying
        # slot keeps its 1-token floor, then nodes fit in what remains
        budget_left = self.token_budget if self.token_budget else None
        for slot, req in sorted(self.running.items()):
            if self._prefill_pending(req) or slot in self._chunk_unlocked:
                continue
            old_len = int(self.cache.lengths[slot])
            # every node writes a cache row (horizon cap), but accepted
            # tokens are bounded by the DEPTH — so the request's
            # remaining token budget prunes depth, the cache horizon
            # and iteration budget prune node count
            max_nodes = min(self._tree_nodes, spec.max_len - old_len - 1)
            max_depth = req.max_new_tokens - len(req.generated) - 1
            if budget_left is not None:
                max_nodes = min(max_nodes, max(0, budget_left - 1))
            tree = trees.get(slot) or DraftTree([], [])
            tree = tree.prune(max(0, max_nodes), max(0, max_depth))
            plan[slot] = tree
            if budget_left is not None:
                budget_left -= 1 + len(tree.tokens)
        # claim pages for every row the verify writes; optimistic
        # preemption may evict plan slots, so the arrays build AFTER
        self._secure_pages({s: 1 + len(t.tokens) for s, t in plan.items()})
        plan = {s: t for s, t in plan.items() if s in self.running}
        if not plan:
            return None
        tokens = np.zeros((spec.max_seqs, w), dtype=np.int32)
        draft_lens = np.zeros(spec.max_seqs, dtype=np.int32)
        # pad rows/columns keep a valid chain topology (parent = j - 1)
        parents = np.tile(
            np.arange(-1, w - 1, dtype=np.int32), (spec.max_seqs, 1)
        )
        nodes_total = 0
        for slot, tree in plan.items():
            req = self.running[slot]
            tokens[slot, 0] = req.generated[-1]
            for j, t in enumerate(tree.tokens):
                tokens[slot, 1 + j] = int(t)
            parents[slot] = tree.row_parents(w)
            draft_lens[slot] = 1 + len(tree.tokens)
            nodes_total += len(tree.tokens)
        t0 = time.perf_counter()
        try:
            step = self.engine.verify_tree_dispatch(
                self.params, tokens, draft_lens, parents
            )
        except Exception as e:
            self._fail_all_running(f"tree verify step failed: {e!r}")
            return None
        if self._tele is not None:
            self._tele.tracer.complete(
                "dispatch:verify_tree",
                "host",
                t0,
                time.perf_counter(),
                args={
                    "iter": self._iter,
                    "slots": len(plan),
                    "nodes": nodes_total,
                },
            )
            self._tele.registry.counter(
                "serve_spec_tree_nodes_total",
                help="draft-tree nodes dispatched for verification",
            ).inc(nodes_total)
        step.iteration = self._iter
        step.plan = {s: list(t.tokens) for s, t in plan.items()}
        step.tree_plan = plan
        step.participants = {s: self.running[s] for s in plan}
        self._note_dispatch(step)
        self.stats.verify_steps += 1
        self.stats.tree_verify_steps += 1
        self.stats.tree_nodes_proposed += nodes_total
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += len(plan)
        self._budget_used_iter += int(draft_lens.sum())
        return step

    def _commit_verify_tree(self, step, logits) -> None:
        """Commit a reconciled tree-verify step: per slot walk the
        draft tree against the step's SNAPSHOT plan and lengths (fxlint
        FX103 — under the async loop the live proposer/cache view is an
        iteration ahead), accept the longest surviving root-to-leaf
        path, compact that path's scattered rows into contiguous cache
        positions (truncate + src_rows — dead branches' rows and pages
        return to the reserve in the same call), and emit
        len(path) + 1 tokens. Acceptance counters stay comparable to
        the linear path: proposed counts the tree DEPTH (the most one
        verify could accept), accepted the surviving path length."""
        from flexflow_tpu.serving.spec import accept_tree

        if self.injector is not None:
            logits = np.array(logits)  # writable copy for the injector
            self.injector.corrupt_logits(
                logits, sorted(step.tree_plan), iteration=step.iteration
            )
        for slot in sorted(step.tree_plan):
            req = step.participants.get(slot)
            if req is None or self.running.get(slot) is not req:
                continue
            tree = step.tree_plan[slot]
            n = len(tree.tokens)
            old_len = int(step.lengths[slot])
            if not np.isfinite(logits[slot, : 1 + n]).all():
                # lengths never advanced for this slot; freeing it
                # returns its pages, stale tree rows and all
                self._fail(
                    req,
                    f"non-finite logits at iteration {step.iteration}",
                )
                continue
            path, emitted = accept_tree(
                logits[slot],
                tree,
                temperature=self.engine.temperature,
                seed=self.engine.seed,
                slot=slot,
                base_len=old_len,
            )
            # commit the accepted path / drop every dead branch BEFORE
            # emitting: _emit may retire the request, which frees the
            # slot (truncating a freed slot would be an error). Tree
            # node i's row sits at position old_len + 1 + i; truncate
            # compacts the accepted rows down to old_len + 1 ...
            self.cache.truncate(
                slot,
                old_len + len(path) + 1,
                src_rows=[old_len + 1 + node for node in path],
            )
            self.proposer.rollback(slot, old_len + len(path) + 1)
            self.stats.draft_tokens_proposed += tree.depth()
            self.stats.draft_tokens_accepted += len(path)
            if self._tele is not None:
                self._tele.registry.histogram(
                    "serve_spec_tree_accepted_path_len",
                    bounds=(0, 1, 2, 4, 8, 16, 32),
                    help="accepted root-to-leaf path length per slot "
                    "per tree-verify step",
                ).observe(float(len(path)))
            for t in emitted:
                self._emit(req, int(t))
                if req.finished:
                    break  # EOS mid-verify: nothing past it is emitted

    # -- chunked prefill (token_budget > 0) ----------------------------------

    def _kernel_active(self) -> bool:
        """Whether the engine's decode-kernel mode can actually take the
        Pallas path — `use_kernel`'s mode resolution: "pallas" always
        can, "auto" only on a real TPU backend, "dense" never."""
        mode = getattr(self.engine, "decode_kernel", "dense")
        if mode == "pallas":
            return True
        if mode != "auto":
            return False
        import jax

        return jax.default_backend() == "tpu"

    def _prefill_pending(self, req: Request) -> bool:
        """True while a chunked request still has prompt tokens whose
        chunk has not COMMITTED — it neither decodes nor drafts until
        the last chunk lands. Monolithic admissions (empty prefill_seq)
        are never pending, so every non-chunked path is unaffected."""
        return bool(req.prefill_seq) and req.prefill_pos < len(
            req.prefill_seq
        )

    def _reserved_step_tokens(self, host: Optional[int] = None) -> int:
        """Tokens this iteration's decode/verify step may consume for
        the slots already past prefill — 1 per slot, plus up to spec_k
        drafts each under speculation. The chunk planner budgets around
        this reservation so chunks + decode work stay inside
        token_budget together, which is the whole point: decodes keep
        their cadence WHILE a prompt streams in. `host` narrows the
        count to one host partition's slots (the per-host budget of a
        pod placement)."""
        per = 1 + (
            (self._tree_nodes if self.spec_branch > 1 else self.spec_k)
            if self.proposer is not None
            else 0
        )
        return per * sum(
            1
            for r in self.running.values()
            if not self._prefill_pending(r)
            and len(r.generated) < r.max_new_tokens
            and (host is None or self.cache.host_of_slot(r.slot) == host)
        )

    def _plan_chunks(self, reserved: int) -> Dict[int, int]:
        """Fair-share chunk grants for one iteration: round-robin
        passes over the prefill-pending slots in admission order,
        granting one chunk_size unit (or the remainder) per pass until
        the budget left over from `reserved` runs out. Round-robin —
        not head-of-queue-until-done — is what kills head-of-line
        blocking among prefills themselves: a short prompt admitted
        behind a long one still completes in its first iteration. A
        grant that FINISHES a prompt costs only its own tokens: the
        slot's first decode/verify is deferred one iteration
        (`_chunk_unlocked`), so grants alone bound the iteration's
        token work — charging the unlocked decode here instead would
        wedge the planner when token_budget == chunk_size (a full
        final chunk could never fit). Pending slots granted nothing
        count as budget deferrals (`serve_budget_deferrals_total`).

        Under a multi-host placement the token budget applies PER HOST
        (each host prefills into its own pool shard at its own cadence),
        so the round-robin runs once per host partition over that host's
        pending slots against `token_budget - reserved_on_that_host`."""
        pending_all = sorted(
            (
                r
                for r in self.running.values()
                if r.prefill_dispatched < len(r.prefill_seq)
            ),
            key=lambda r: (r.admit_iter, r.rid),
        )
        if not pending_all:
            return {}
        # keep the chunk step's width inside the Pallas kernel's query
        # tile when a kernel mode is on — a wider grant would silently
        # route the whole step to the dense fallback
        max_grant = self.token_budget
        if self._kernel_active():
            from flexflow_tpu.ops.pallas.decode_kernel import _MAX_W

            max_grant = _MAX_W
        plan: Dict[int, int] = {r.slot: 0 for r in pending_all}
        hosts = range(self.cache.num_hosts)
        for h in hosts:
            if self.cache.num_hosts > 1:
                pending = [
                    r
                    for r in pending_all
                    if self.cache.host_of_slot(r.slot) == h
                ]
                budget = self.token_budget - self._reserved_step_tokens(h)
            else:
                pending = pending_all
                budget = self.token_budget - int(reserved)
            if self._multiclass:
                budget = self._plan_chunks_drr(
                    h, pending, plan, budget, max_grant
                )
                continue
            progress = True
            while progress and budget > 0:
                progress = False
                for req in pending:
                    rem = (
                        len(req.prefill_seq)
                        - req.prefill_dispatched
                        - plan[req.slot]
                    )
                    if rem <= 0 or plan[req.slot] >= max_grant:
                        continue
                    unit = min(
                        self.chunk_size, rem, max_grant - plan[req.slot]
                    )
                    if unit > budget:
                        continue
                    plan[req.slot] += unit
                    budget -= unit
                    progress = True
        deferred = sum(1 for c in plan.values() if c == 0)
        if deferred:
            self.stats.budget_deferrals += deferred
            if self._tele is not None:
                self._tele.registry.counter(
                    "serve_budget_deferrals_total",
                    help="prefill-pending slots granted no chunk tokens "
                    "by an iteration's budget",
                ).inc(deferred)
        return {s: c for s, c in plan.items() if c > 0}

    def _plan_chunks_drr(
        self,
        host: int,
        pending: List[Request],
        plan: Dict[int, int],
        budget: int,
        max_grant: int,
    ) -> int:
        """Weighted-fair grant loop for one host partition: each DRR
        serve grants one chunk unit (up to chunk_size tokens) to the
        selected class's next pending request, so prefill bandwidth
        under the token budget divides by class weight instead of
        admission order. Within a class, requests rotate in admission
        order (the round-robin fairness the single-class loop has).
        The DRR instance persists per host across iterations — carried
        deficits are what make the weighted shares hold over time —
        and idle classes settle to zero so a silent class cannot bank
        credit. Mutates `plan` in place; returns the leftover budget."""
        drr = self._grant_drr.get(host)
        if drr is None:
            from flexflow_tpu.serving.tenancy.fairness import (
                DeficitRoundRobin,
            )

            weights = {n: c.weight for n, c in self.classes.items()}
            drr = DeficitRoundRobin(
                weights, unit=float(max(1, self.chunk_size))
            )
            self._grant_drr[host] = drr
        by_class: Dict[str, List[Request]] = {}
        for r in pending:
            by_class.setdefault(self._class_of(r), []).append(r)
        drr.settle(list(by_class))
        rr: Dict[str, int] = {c: 0 for c in by_class}
        while budget > 0:
            costs: Dict[str, float] = {}
            heads: Dict[str, Tuple[Request, int, int]] = {}
            for c, reqs in by_class.items():
                n = len(reqs)
                for j in range(n):
                    pos = (rr[c] + j) % n
                    req = reqs[pos]
                    rem = (
                        len(req.prefill_seq)
                        - req.prefill_dispatched
                        - plan[req.slot]
                    )
                    if rem <= 0 or plan[req.slot] >= max_grant:
                        continue
                    unit = min(
                        self.chunk_size, rem, max_grant - plan[req.slot]
                    )
                    if unit > budget:
                        continue
                    costs[c] = float(unit)
                    heads[c] = (req, unit, pos)
                    break
            if not costs:
                break
            name, rounds = drr.select(costs)
            req, unit, pos = heads[name]
            plan[req.slot] += unit
            budget -= unit
            drr.charge(name, rounds, list(costs), cost=float(unit))
            rr[name] = (pos + 1) % len(by_class[name])
        if self.debug_invariants:
            drr.check_invariants(max_cost=float(max(1, self.chunk_size)))
        return budget

    def _chunk_dispatch_step(self, plan: Dict[int, int]):
        """Dispatch phase of one chunked-prefill step: claim the pages
        the chunk rows land in, build the token/width arrays from the
        LIVE cursors (this is the dispatch side), advance the dispatch
        cursors, and enqueue the step. The cursor state the commit
        phase needs rides the step record (`InflightStep.chunks`) —
        fxlint FX105 holds the reconcile side to that snapshot. The
        step width pads up to a chunk_size multiple so the engine's
        jitted-program LRU sees a bounded population of widths."""
        if not plan:
            return None
        self._secure_pages(dict(plan))
        live: Dict[int, int] = {}
        for slot, c in plan.items():
            req = self.running.get(slot)
            if req is None:  # optimistic preemption evicted it
                continue
            c = min(c, len(req.prefill_seq) - req.prefill_dispatched)
            if c > 0:
                live[slot] = c
        if not live:
            return None
        spec = self.cache.spec
        unit = max(1, self.chunk_size)
        w = max(live.values())
        w = -(-w // unit) * unit
        tokens = np.zeros((spec.max_seqs, w), dtype=np.int32)
        chunk_lens = np.zeros(spec.max_seqs, dtype=np.int32)
        chunks: Dict[int, tuple] = {}
        for slot, c in sorted(live.items()):
            req = self.running[slot]
            start = req.prefill_dispatched
            tokens[slot, :c] = req.prefill_seq[start : start + c]
            chunk_lens[slot] = c
            chunks[slot] = (start, c, start + c >= len(req.prefill_seq))
        t0 = time.perf_counter()
        try:
            step = self.engine.prefill_chunk_dispatch(
                self.params, tokens, chunk_lens
            )
        except Exception as e:
            self._fail_all_running(f"chunk step failed: {e!r}")
            return None
        for slot, (start, c, _final) in chunks.items():
            self.running[slot].prefill_dispatched = start + c
        if self._tele is not None:
            self._tele.tracer.complete(
                "prefill:chunk",
                "host",
                t0,
                time.perf_counter(),
                args={
                    "iter": self._iter,
                    "slots": len(chunks),
                    "tokens": int(chunk_lens.sum()),
                },
            )
            self._tele.registry.counter(
                "serve_chunks_total",
                help="prompt chunks dispatched (chunked prefill)",
            ).inc(len(chunks))
        step.iteration = self._iter
        step.participants = {s: self.running[s] for s in chunks}
        step.chunks = chunks
        step.chunk_seqs = {s: self.running[s].prefill_seq for s in chunks}
        self._note_dispatch(step)
        self.stats.chunk_steps += 1
        self.stats.chunk_tokens += int(chunk_lens.sum())
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += len(chunks)
        self._budget_used_iter += int(chunk_lens.sum())
        return step

    def _commit_chunk(self, step, nxt, logits) -> None:
        """Commit a reconciled chunk step: advance each participant's
        committed cursor from the step's OWN cursor record
        (`step.chunks` — never the live prefill_* attrs, fxlint FX105)
        and, on a slot's FINAL chunk, emit the sampled token — exactly
        the monolithic prefill's tail, so the downstream stream is
        token-identical. The usual identity check discards results for
        slots that retired or turned over while the step was in
        flight."""
        if self.injector is not None:
            logits = np.array(logits)  # writable copy for the injector
            self.injector.corrupt_logits(
                logits, sorted(step.chunks), iteration=step.iteration
            )
        for slot in sorted(step.chunks):
            req = step.participants.get(slot)
            if req is None or self.running.get(slot) is not req:
                continue
            start, size, final = step.chunks[slot]
            if not np.isfinite(logits[slot]).all():
                self._fail(
                    req,
                    f"non-finite chunk logits at iteration "
                    f"{step.iteration}",
                )
                continue
            req.prefill_pos = start + size
            if getattr(self.cache, "prefix_cache", False):
                # progressive publication: every COMMITTED full page of
                # the streaming prompt becomes matchable immediately —
                # and only committed ones (a faulted chunk never
                # publishes pages with unexecuted writes). Tokens and
                # extent both come from the step record (FX105).
                self.cache.register_prefix(
                    slot, step.chunk_seqs[slot], start + size
                )
            if final:
                self._chunk_unlocked.add(slot)
                self._emit(req, int(nxt[slot]))

    def _chunk_once(self) -> None:
        """Synchronous chunk iteration: plan within the budget left
        after the decode/verify reservation, dispatch, reconcile
        immediately."""
        step = self._chunk_dispatch_step(
            self._plan_chunks(self._reserved_step_tokens())
        )
        if step is not None:
            self._reconcile_step(step)

    def _generate_once(self) -> None:
        # the fuse probe runs FIRST even under speculation: an
        # iteration where no slot drafted (see _fusable_steps) runs a
        # fused decode window instead of a degenerate w=1 verify
        k = self._fusable_steps()
        if k > 1:
            step = self._decode_multi_dispatch_step(k)
            if step is not None:
                self._reconcile_step(step)
            return
        if self.proposer is not None:
            self._verify_once()
            return
        self._decode_once()

    def _begin_iteration(self) -> None:
        self._iter += 1
        self.stats.iterations += 1
        self._budget_used_iter = 0
        self._chunk_unlocked.clear()
        self._cached_proposals = None
        if self._tele is not None:
            self._iter_t0 = time.perf_counter()
        if self.injector is not None:
            self.injector.on_iteration(self._iter, self)
            # chaos: process death at the step boundary, before any
            # work — everything journaled so far survives, nothing new
            # is at risk (serving/journal.py proves the restart)
            crash = getattr(self.injector, "maybe_crash", None)
            if crash is not None:
                crash("begin")
        self._reap_deadlines()

    def _end_iteration(self) -> None:
        # per-iteration gauge: tokens this iteration's dispatches
        # charged against the budget (chunk + decode/verify widths)
        self.stats.budget_used = self._budget_used_iter
        self.stats.verify_cache_entries = getattr(
            self.engine, "verify_cache_entries", 0
        )
        self.stats.kernel_fallbacks = getattr(
            self.engine, "kernel_fallbacks", 0
        )
        self.stats.multistep_cache_entries = getattr(
            self.engine, "multistep_cache_entries", 0
        )
        self.stats.prefix_hits = getattr(self.cache, "prefix_hits", 0)
        self.stats.prefix_pages_shared = int(
            getattr(self.cache, "_shared", np.zeros(1)).sum()
        )
        self.stats.cow_copies = getattr(self.cache, "cow_copies", 0)
        self.stats.swap_outs = getattr(self.cache, "swap_outs", 0)
        self.stats.swap_ins = getattr(self.cache, "swap_ins", 0)
        self.stats.swap_bytes = getattr(self.cache, "swap_bytes_total", 0)
        self.stats.swapped_pages = getattr(self.cache, "swapped_pages", 0)
        self.stats.prefix_evictions = getattr(
            self.cache, "prefix_evictions", 0
        )
        if self.debug_invariants:
            # pages the injector stole this iteration are accounted as
            # extra frees — conservation must hold even mid-chaos
            self.cache.check_invariants(
                extra_free=(
                    self.injector.stolen_pages
                    if self.injector is not None
                    else 0
                )
            )
            if self.adapters is not None:
                self.adapters.check_invariants()
            if self._admit_drr is not None:
                self._admit_drr.check_invariants(max_cost=1.0)
        if self._tele is not None:
            self._sample_telemetry()
        if self.injector is not None:
            # chaos: process death AFTER this iteration's tokens were
            # emitted but BEFORE the journal's commit flush below — the
            # worst case: a whole fused multi-step window's or
            # tree-verify round's accepted run is host-visible yet
            # unjournaled, and the restart must recompute it
            # token-identically from the last durable cursor
            crash = getattr(self.injector, "maybe_crash", None)
            if crash is not None:
                crash("commit")
        if self.journal is not None:
            # per-host-sync commit flush, INSIDE step(): the front
            # door's publish runs after step() returns, so the journal
            # always dominates the published cursor (FX111)
            self.journal.commit_pending(self._iter)
            if (
                self.journal_snapshot_every
                and self._iter % self.journal_snapshot_every == 0
            ):
                self._journal_snapshots()

    def _journal_snapshots(self) -> None:
        """Journal-referenced KV snapshots (paged layout only): every
        `journal_snapshot_every` iterations, each running slot's
        committed pages ride `snapshot_swap` into a snapshot record, so
        a restart can restore KV over `import_swap` instead of
        recomputing — priced at recovery by `build_restore_decider`.
        `gen_len` stamps the committed-run length the snapshot is
        consistent with; recovery honors the snapshot only while that
        still matches the journal's committed cursor."""
        snap = getattr(self.cache, "snapshot_swap", None)
        if snap is None or self.journal.degraded:
            return
        for slot in sorted(self.running):
            req = self.running[slot]
            if self._prefill_pending(req):
                continue  # mid-prefill KV is not a resumable cursor
            rec = snap(slot)
            if rec is not None:
                rec["gen_len"] = len(req.generated)
                self.journal.snapshot(req.rid, rec)

    def _sample_telemetry(self) -> None:
        """One iteration's telemetry sample: KV-pool gauges straight
        from the allocator's ledgers, scheduler queue gauges, the fault
        injector's ledger, the derived stats ratios, then one JSONL row
        and the iteration's host span. Runs only with telemetry
        attached — the disabled path never gets here — and resolves
        every gauge handle ONCE, so the steady-state cost is attribute
        writes, not registry lookups."""
        tele = self._tele
        handles = self._gauge_handles
        if handles is None:
            reg = tele.registry
            handles = {
                name: reg.gauge(name)
                for name in self.cache.telemetry_gauges()
            }
            handles["serve_queue_depth"] = reg.gauge(
                "serve_queue_depth", help="requests waiting for admission"
            )
            handles["serve_running_requests"] = reg.gauge(
                "serve_running_requests", help="requests holding a slot"
            )
            self._gauge_handles = handles
        for name, value in self.cache.telemetry_gauges().items():
            handles[name].value = value
        handles["serve_queue_depth"].value = len(self.queue)
        handles["serve_running_requests"].value = len(self.running)
        if getattr(self.cache, "num_hosts", 1) > 1:
            # per-host pool/scheduler slices under a `host` label (the
            # process index on a real pod; simulated-host partitions on
            # one process). The unlabelled series above stay the
            # pod-wide totals, so single-host dashboards see identical
            # streams; labelled series ride the same JSONL sample rows
            # as extra name{host="h"} columns.
            reg = tele.registry
            for h in range(self.cache.num_hosts):
                labels = {"host": str(h)}
                for name, value in self.cache.telemetry_gauges_host(
                    h
                ).items():
                    reg.gauge(name, labels=labels).value = value
                reg.gauge(
                    "serve_running_requests", labels=labels
                ).value = sum(
                    1
                    for r in self.running.values()
                    if self.cache.host_of_slot(r.slot) == h
                )
        if self.classes:
            # per-class scheduler gauges + the rolling per-class SLO
            # views: the unlabelled series stay fleet-wide aggregates,
            # same layering as the per-host block above
            reg = tele.registry
            for name in self.classes:
                labels = {"class": name}
                reg.gauge(
                    "serve_queue_depth", labels=labels
                ).value = sum(
                    1 for r in self.queue if self._class_of(r) == name
                )
                reg.gauge(
                    "serve_running_requests", labels=labels
                ).value = sum(
                    1
                    for r in self.running.values()
                    if self._class_of(r) == name
                )
            for mon in self._class_slo.values():
                mon.publish()
        if self.adapters is not None:
            reg = tele.registry
            for name, value in self.adapters.telemetry_gauges().items():
                reg.gauge(name).value = value
            for name, value in self.adapters.telemetry_counters().items():
                reg.counter(name).set_monotonic(value)
        if self.injector is not None:
            self.injector.publish_metrics(tele.registry)
        if self.proposer is not None:
            for name, value in self.proposer.telemetry_counters().items():
                tele.registry.counter(name).set_monotonic(value)
        cache_counters = getattr(self.cache, "telemetry_counters", None)
        if cache_counters is not None:
            for name, value in cache_counters().items():
                tele.registry.counter(name).set_monotonic(value)
        self.stats.publish_derived()
        tele.sample(self._iter)
        now = time.perf_counter()
        tele.tracer.complete(
            "iteration",
            "host",
            self._iter_t0,
            now,
            args={"iter": self._iter},
        )
        if getattr(self.cache, "num_hosts", 1) > 1:
            # one lane per host partition: the iteration span again, but
            # annotated with that host's running/free-page view so the
            # Perfetto timeline shows per-host load side by side
            free_by_host = self.cache.free_pages_by_host()
            for h in range(self.cache.num_hosts):
                tele.tracer.complete(
                    "iteration",
                    f"host{h}",
                    self._iter_t0,
                    now,
                    tid=tele.tracer.host_lane(h),
                    args={
                        "iter": self._iter,
                        "running": sum(
                            1
                            for r in self.running.values()
                            if self.cache.host_of_slot(r.slot) == h
                        ),
                        "free_pages": free_by_host[h],
                    },
                )

    def _work_pending(self) -> bool:
        return bool(self.queue or self.running)

    def work_pending(self) -> bool:
        """Public driving surface (shared with `ReplicaRouter` and
        `DisaggregatedPipeline`): anything submitted but not yet
        terminal. The front door and the benches drive every backend
        through this same duck type."""
        return self._work_pending()

    def run(self, requests: Optional[Sequence[Request]] = None) -> List[Request]:
        """Drain the queue (plus `requests`, submitted first) to
        completion; returns requests in terminal order — check
        `Request.status`/`Request.ok`, a fault-isolated run finishes
        with FAILED entries instead of raising."""
        for r in requests or ():
            self.submit(r)
        t0 = time.perf_counter()
        while self._work_pending():
            self.step()
        self.stats.elapsed_s += time.perf_counter() - t0
        if self._tele is not None:
            self.stats.publish_derived()
            self.telemetry.flush()
        return self.finished


class ContinuousBatchingScheduler(_SchedulerBase):
    """Orca-style: every iteration joins new prefills with in-flight
    decodes; slots recycle the moment a request retires. With a
    `proposer` + `spec_k`, each iteration runs the speculative
    draft/verify step instead of single-token decode. With a
    `token_budget`, each iteration additionally runs one chunked-
    prefill step for the slots still streaming their prompts in,
    planned so chunks + decode/verify work stay inside the budget."""

    def step(self) -> None:
        self._begin_iteration()
        self._admit()
        if self.token_budget and self.running:
            self._chunk_once()
        if self.running:
            self._generate_once()
        self._end_iteration()


class AsyncContinuousBatchingScheduler(ContinuousBatchingScheduler):
    """Double-buffered Orca loop: overlap host scheduling with device
    steps (`--serve-async`; the synchronous ContinuousBatchingScheduler
    stays the reference it is proved token-identical against).

    The sync loop round-trips every iteration — host admission/paging/
    bookkeeping while the device idles, then the jitted step while the
    host idles. This loop splits each step into its dispatch and
    reconcile halves (engine.InflightStep) and runs them one iteration
    apart: while step N is in flight on the device, the host reaps
    queued deadlines, admits newcomers, claims pages, and dispatches
    step N+1 — chaining N+1's input tokens from N's device outputs so
    the data dependency never touches the host — and only then blocks
    on N's outputs to emit tokens and retire requests.

    One-step-stale semantics: terminal events land at RECONCILE, so a
    request that hits EOS/budget in step N is still (wastefully but
    harmlessly) stepped in N+1 — the identity check in the commit phase
    discards its speculative token, the slot layout's stale cache write
    is overwritten before any lengths mask exposes it, and the paged
    layout pins every page an in-flight step references (kv_cache
    limbo) so the row cannot land in a page a new sequence owns.
    `cancel()` of a RUNNING request and running-deadline reaping defer
    to the next reconcile for the same reason; queued requests cancel/
    reap immediately. When a page claim finds the pool dry because of
    pinned pages, `_reclaim_inflight_pages` drains the pipeline (a
    stall, traded for allocator soundness) before any preemption.

    Speculative mode cannot pipeline two verifies (the next verify's
    input tokens are acceptance DECISIONS, host logic, not a device
    array) — instead the in-flight window hides the proposer: while
    verify N runs, a stateless proposer drafts for N+1 against N's
    predicted (full-accept) history, rolled back at reconcile when the
    prediction misses (stats.pre_proposal_hits/misses)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._inflight: deque = deque()  # InflightStep records, oldest first
        self._pending_cancels: set = set()

    # -- one-step-stale control surface --------------------------------------

    def cancel(self, rid: int) -> bool:
        """Cancel a request. Queued requests finalize immediately; a
        RUNNING request whose slot may be referenced by an in-flight
        step defers to the next reconcile (it may receive at most one
        more token's worth of device work, which is discarded)."""
        req = self._by_rid.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        if req.slot is not None and self._inflight:
            self._pending_cancels.add(rid)
            return True
        return super().cancel(rid)

    def _reap_deadlines(self) -> None:
        now = time.perf_counter()
        for req in [r for r in self.queue if r.deadline_exceeded(now)]:
            self._finalize(req, RequestStatus.TIMED_OUT)
        if not self._inflight:
            for req in [
                r
                for r in list(self.running.values())
                if r.deadline_exceeded(now)
            ]:
                self._finalize(req, RequestStatus.TIMED_OUT)

    def _after_reconcile(self) -> None:
        """Deferred control events land at the commit boundary: cancels
        queued during the in-flight window, then running-deadline
        reaping."""
        for rid in sorted(self._pending_cancels):
            req = self._by_rid.get(rid)
            if req is not None and req.status not in TERMINAL_STATUSES:
                self._finalize(req, RequestStatus.CANCELLED)
        self._pending_cancels.clear()
        now = time.perf_counter()
        for req in [
            r for r in list(self.running.values()) if r.deadline_exceeded(now)
        ]:
            self._finalize(req, RequestStatus.TIMED_OUT)

    # -- pipeline ------------------------------------------------------------

    def _reconcile_front(self) -> None:
        step = self._inflight.popleft()
        self._reconcile_step(step)
        self._after_reconcile()

    def _drain_inflight(self) -> bool:
        drained = bool(self._inflight)
        while self._inflight:
            self._reconcile_front()
        return drained

    def _reclaim_inflight_pages(self) -> bool:
        # pages pinned for the in-flight step return at its reconcile —
        # the drain stalls the pipeline but keeps the allocator sound
        return self._drain_inflight()

    def _work_pending(self) -> bool:
        return bool(self.queue or self.running or self._inflight)

    def step(self) -> None:
        self._begin_iteration()
        self._admit()
        if self.proposer is not None:
            self._verify_iteration_async()
        else:
            self._decode_iteration_async()
        self._end_iteration()

    def _decode_iteration_async(self) -> None:
        """Dispatch decode N+1 (token-chained on the in-flight step N's
        device outputs), THEN reconcile N — the double buffer. Under a
        token budget the iteration also dispatches one chunk step ahead
        of the decode: chunk progress has no host data dependency (the
        prompt tokens are accepted by construction, the engine advances
        lengths at dispatch), so chunks pipeline exactly like chained
        decodes and both steps of iteration N ride the device while the
        host reconciles N-1.

        A fused multi-step window (decode_multistep=True) rides the
        same deque but cannot be token-chained — its last token is K
        steps deep in the scan — so any in-flight step drains before a
        window dispatches (the window reads committed generated[-1]
        tokens), and an open window drains at the NEXT iteration's top
        before anything else dispatches, which is also where deferred
        cancels and running-deadline reaping land. The host work that
        overlaps an open window is the next iteration's admission and
        bookkeeping, exactly as for a plain in-flight step."""
        if any(s.kind == "multistep" for s in self._inflight):
            self._drain_inflight()
        keep = 0
        if self.token_budget and self.running:
            step = self._chunk_dispatch_step(
                self._plan_chunks(self._reserved_step_tokens())
            )
            if step is not None:
                self._inflight.append(step)
                keep += 1
        if self.running:
            k = self._fusable_steps()
            if k > 1:
                # k > 1 implies no chunk streaming in progress, so
                # nothing was appended above (keep == 0); drain any
                # plain decode step still in flight from the previous
                # iteration — the window's input tokens must be
                # committed before the scan captures them
                self._drain_inflight()
                step = self._decode_multi_dispatch_step(k)
                if step is not None:
                    self._inflight.append(step)
                    keep += 1
            else:
                # chain on the newest in-flight DECODE step — an
                # interleaved chunk step never carries the decoding
                # slots' next tokens
                chain = next(
                    (
                        s
                        for s in reversed(self._inflight)
                        if s.kind == "decode"
                    ),
                    None,
                )
                step = self._decode_dispatch_step(chain=chain)
                if step is not None:
                    self._inflight.append(step)
                    keep += 1
        while len(self._inflight) > keep:
            self._reconcile_front()
        if not keep:
            # nothing enqueued this iteration (drained queue tail,
            # every slot budget-gated behind the in-flight step, or a
            # whole-step fault) — flush the pipeline so its pinned
            # pages and terminal events land instead of livelocking
            self._drain_inflight()

    def _verify_iteration_async(self) -> None:
        """Speculative iteration: while verify N is in flight, draft
        for N+1 against its predicted outcome; reconcile N; dispatch
        N+1 with the surviving pre-proposals. Under a token budget a
        chunk step dispatches BEFORE the drain — it overlaps the
        in-flight verify on the device — and stays in flight through
        this iteration's verify dispatch."""
        pre = self._pre_propose()
        keep = 0
        if self.token_budget and self.running:
            step = self._chunk_dispatch_step(
                self._plan_chunks(self._reserved_step_tokens())
            )
            if step is not None:
                self._inflight.append(step)
                keep += 1
        while len(self._inflight) > keep:
            self._reconcile_front()
        if self.running:
            if self.spec_branch > 1:
                # tree mode: pre-proposals never fire (_pre_propose
                # gates on kind == "verify" — predicting which PATH a
                # tree verify accepts would misfire far more often than
                # a chain's full-acceptance bet), so trees draft fresh
                # against the reconciled state
                step = self._verify_tree_dispatch_step(
                    self._propose_trees()
                )
            else:
                step = self._verify_dispatch_step(
                    self._merge_proposals(pre)
                )
            if step is not None:
                self._inflight.append(step)

    # -- speculative pre-proposals -------------------------------------------

    def _pre_propose(self) -> Dict[int, Tuple[int, List[int]]]:
        """Draft for the NEXT verify while the current one is still in
        flight, against each slot's PREDICTED history: committed tokens
        plus the in-flight drafts, assuming full acceptance (the
        common case in the regimes speculation wins). Only stateless
        proposers pre-draft — a model proposer's cache feeds would need
        their own rollback story. Returns slot -> (predicted generated
        length, proposal); `_merge_proposals` validates the prediction
        at reconcile and rolls mispredictions back to a fresh draft."""
        if (
            not self._inflight
            or self.proposer is None
            or not getattr(self.proposer, "stateless", False)
        ):
            return {}
        step = self._inflight[-1]
        if step.kind != "verify" or not step.plan:
            return {}
        seqs: Dict[int, List[int]] = {}
        basis: Dict[int, int] = {}
        for slot, drafts in step.plan.items():
            req = step.participants.get(slot)
            if req is None or self.running.get(slot) is not req:
                continue
            seqs[slot] = list(req.prompt) + list(req.generated) + [
                int(t) for t in drafts
            ]
            basis[slot] = len(req.generated) + len(drafts)
        if not seqs:
            return {}
        # draft one EXTRA token: the prediction cannot know the verify's
        # bonus/correction token, so a pre-proposal only survives when
        # its first token turns out to BE that token — the rest aligns
        t0 = time.perf_counter()
        proposals = self.proposer.propose_sequences(seqs, self.spec_k + 1)
        if self._tele is not None:
            # the draft/verify overlap the async spec loop exists for:
            # this host span sits INSIDE the in-flight verify's device
            # window in the exported trace
            self._tele.tracer.complete(
                "draft:pre_propose",
                "host",
                t0,
                time.perf_counter(),
                args={"iter": self._iter, "slots": len(seqs)},
            )
        return {
            s: (basis[s], [int(t) for t in proposals.get(s) or ()])
            for s in seqs
        }

    def _merge_proposals(
        self, pre: Dict[int, Tuple[int, List[int]]]
    ) -> Dict[int, List[int]]:
        """Fresh proposals overlaid with the pre-proposals whose
        prediction held: the in-flight verify fully accepted (generated
        grew by exactly drafts + bonus) AND the pre-draft's first token
        is the bonus token it could not see. Everything else is a
        rolled-back misprediction and uses the fresh draft."""
        proposals = self._propose(self.spec_k)
        for slot, (basis, prop) in pre.items():
            req = self.running.get(slot)
            if req is None:
                continue
            if (
                len(req.generated) == basis + 1
                and len(prop) > 1
                and prop[0] == int(req.generated[-1])
            ):
                proposals[slot] = prop[1:]
                self.stats.pre_proposal_hits += 1
            else:
                self.stats.pre_proposal_misses += 1
        return proposals


class StaticBatchingScheduler(_SchedulerBase):
    """Request-level batching baseline: a batch runs until every member
    finishes; freed slots stay idle until the batch drains. Chunked
    prefill is an iteration-level technique — the baseline rejects a
    token_budget rather than silently admitting requests whose prompts
    would then never stream in."""

    def __init__(self, *args, **kwargs):
        if kwargs.get("token_budget"):
            raise ValueError(
                "token_budget (chunked prefill) requires the continuous "
                "scheduler"
            )
        super().__init__(*args, **kwargs)

    def step(self) -> None:
        self._begin_iteration()
        if not self.running:
            self._admit()
        if self.running:
            self._generate_once()
        self._end_iteration()


_LATENCY_METRICS = {
    "latency": lambda r: r.latency_s,
    "ttft": lambda r: r.ttft_s,
    "decode_per_token": lambda r: r.decode_s_per_token,
}


def latency_percentiles(
    requests: Sequence[Request], pcts=(50, 95), metric: str = "latency"
):
    """{pct: seconds} over successfully FINISHED requests (failed,
    cancelled, and timed-out requests have no meaningful latency and
    would drag the percentiles toward zero). metric: "latency"
    (submit→finish, the default), "ttft" (submit→first token), or
    "decode_per_token" (per-generated-token decode latency after the
    first — where speculative decoding's win shows up as latency rather
    than throughput).

    The percentile math itself lives in telemetry.slo.percentiles —
    the ONE implementation the rolling SLO windows also use, so this
    post-hoc view and the live `serve_slo_*` gauges agree exactly
    whenever the window still holds every sample."""
    if metric not in _LATENCY_METRICS:
        raise ValueError(
            f"metric must be one of {sorted(_LATENCY_METRICS)}, got {metric!r}"
        )
    fn = _LATENCY_METRICS[metric]
    return _percentiles((fn(r) for r in requests if r.ok), pcts)
