"""Iteration-level request scheduling (Orca, OSDI'22).

The unit of scheduling is one model *iteration*, not one request: every
iteration the scheduler (a) admits queued requests into free KV-cache
slots — strictly FIFO, so admission is starvation-free by construction —
running one prefill batch for the newcomers, then (b) runs one decode
step over ALL in-flight slots. A request leaving (EOS or max-new-tokens)
frees its slot at that same iteration boundary, so the next iteration's
admission can refill it. That is the continuous-batching loop; the
throughput win over request-level ("static") batching comes from never
holding finished requests' slots hostage to the longest request in a
batch.

Speculative decoding (SpecInfer, ASPLOS'24; serving/spec.py) is a mode
of the same loop: when a scheduler carries a `DraftProposer`, step (b)
becomes draft → one batched verify call → accept/rollback, emitting
1..spec_k+1 tokens per slot per iteration instead of exactly one. The
iteration-level frame is unchanged — a verify is just a wider decode —
so admission, retirement, and slot recycling all work as before.

`StaticBatchingScheduler` is the deliberately-worse baseline the bench
and the comparison test measure against: admit a batch, decode until the
WHOLE batch finishes, only then admit the next batch (the reference
FFModel::generate shape, and every pre-Orca serving stack).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class Request:
    """One generation request. `generated` accumulates post-prompt tokens
    (the first comes from the admission prefill itself)."""

    rid: int
    prompt: List[int]
    max_new_tokens: int = 16
    eos_token: Optional[int] = None

    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    submit_iter: int = -1
    admit_iter: int = -1
    finish_iter: int = -1
    submit_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0

    @property
    def finished(self) -> bool:
        return self.finish_iter >= 0

    @property
    def latency_s(self) -> float:
        return self.finish_time - self.submit_time

    @property
    def ttft_s(self) -> float:
        """Submit → first generated token (the prefill-side latency a
        user perceives before streaming starts)."""
        return self.first_token_time - self.submit_time

    @property
    def decode_s_per_token(self) -> float:
        """Mean seconds per generated token AFTER the first — the
        decode-side latency speculative decoding compresses (several
        accepted tokens share one verify step's wall time)."""
        if len(self.generated) <= 1:
            return 0.0
        return (self.finish_time - self.first_token_time) / (
            len(self.generated) - 1
        )

    def _done_after(self, token: int) -> bool:
        return (
            self.eos_token is not None and token == self.eos_token
        ) or len(self.generated) >= self.max_new_tokens


@dataclasses.dataclass
class SchedulerStats:
    iterations: int = 0
    decode_steps: int = 0
    prefill_batches: int = 0
    tokens_generated: int = 0
    slot_steps: int = 0  # Σ over decode/verify iterations of max_seqs
    busy_slot_steps: int = 0  # Σ of actually-active slots
    peak_in_flight: int = 0  # max concurrent running requests observed
    elapsed_s: float = 0.0
    # speculative decoding (verify iterations only)
    verify_steps: int = 0
    draft_tokens_proposed: int = 0
    draft_tokens_accepted: int = 0
    # per-request latency accumulators (filled at retirement)
    finished_requests: int = 0
    ttft_sum_s: float = 0.0
    decode_latency_sum_s: float = 0.0  # Σ of per-request decode_s_per_token

    @property
    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def occupancy(self) -> float:
        """Fraction of decode/verify slot-steps that carried a live
        request — the metric continuous batching exists to push toward
        1.0."""
        return self.busy_slot_steps / self.slot_steps if self.slot_steps else 0.0

    @property
    def acceptance_rate(self) -> float:
        """Fraction of drafted tokens the verify step accepted — the
        measured α that optimize_spec_k turns into a draft length."""
        if not self.draft_tokens_proposed:
            return 0.0
        return self.draft_tokens_accepted / self.draft_tokens_proposed

    @property
    def mean_ttft_s(self) -> float:
        if not self.finished_requests:
            return 0.0
        return self.ttft_sum_s / self.finished_requests

    @property
    def mean_decode_s_per_token(self) -> float:
        if not self.finished_requests:
            return 0.0
        return self.decode_latency_sum_s / self.finished_requests


class _SchedulerBase:
    """Shared admission/decode/verify machinery. `proposer` switches the
    per-iteration generation step from plain decode to speculative
    draft/verify (serving/spec.py): propose up to `spec_k` tokens per
    slot, score them all in ONE engine.verify call, accept a prefix
    (exact match under greedy, rejection sampling under temperature),
    and roll the cache back to the accepted length."""

    def __init__(self, engine, params=None, proposer=None, spec_k: int = 4):
        self.engine = engine
        self.cache = engine.cache
        self.params = params if params is not None else engine.model.params
        self.proposer = proposer
        self.spec_k = int(spec_k)
        if proposer is not None and self.spec_k < 1:
            raise ValueError("speculative decoding needs spec_k >= 1")
        self.queue: deque = deque()
        self.running: Dict[int, Request] = {}  # slot -> request
        self.finished: List[Request] = []
        self.stats = SchedulerStats()
        self._iter = 0

    # -- submission ----------------------------------------------------------

    def submit(self, request: Request) -> None:
        if not request.prompt:
            raise ValueError("empty prompt")
        need = len(request.prompt) + request.max_new_tokens
        if need > self.cache.spec.max_len:
            raise ValueError(
                f"request {request.rid}: prompt+max_new_tokens {need} "
                f"exceeds cache max_len {self.cache.spec.max_len}"
            )
        request.submit_iter = self._iter
        request.submit_time = time.perf_counter()
        self.queue.append(request)

    # -- shared pieces -------------------------------------------------------

    def _admit(self, limit: Optional[int] = None) -> List[Request]:
        """FIFO admission into free slots (never reorders the queue —
        starvation-free: the head either admits or blocks everyone
        behind it) + ONE prefill batch for the admitted set. Admission
        asks the cache, so the gate is layout-specific: the slot layout
        admits while a slot is free; the paged layout also requires
        enough free PAGES to cover the request's worst case
        (prompt + max_new_tokens) on top of every in-flight request's
        outstanding reserve — the preemption-free policy that lets a
        mid-flight decode always claim its next page."""
        admitted: List[Request] = []
        while self.queue:
            if limit is not None and len(admitted) >= limit:
                break
            req = self.queue[0]
            slot = self.cache.alloc(
                len(req.prompt), len(req.prompt) + req.max_new_tokens
            )
            if slot is None:
                break
            self.queue.popleft()
            req.slot = slot
            req.admit_iter = self._iter
            self.running[req.slot] = req
            admitted.append(req)
        self.stats.peak_in_flight = max(
            self.stats.peak_in_flight, len(self.running)
        )
        if admitted:
            if self.proposer is not None:
                self.proposer.admit(admitted)
            nxt, _ = self.engine.prefill(
                self.params,
                [r.prompt for r in admitted],
                [r.slot for r in admitted],
            )
            self.stats.prefill_batches += 1
            for tok, req in zip(nxt, admitted):
                self._emit(req, int(tok))
        return admitted

    def _emit(self, req: Request, token: int) -> None:
        req.generated.append(token)
        if len(req.generated) == 1:
            req.first_token_time = time.perf_counter()
        self.stats.tokens_generated += 1
        if req._done_after(token):
            self._retire(req)

    def _retire(self, req: Request) -> None:
        req.finish_iter = self._iter
        req.finish_time = time.perf_counter()
        if self.proposer is not None:
            self.proposer.retire(req)
        self.cache.free(req.slot)
        del self.running[req.slot]
        self.finished.append(req)
        self.stats.finished_requests += 1
        self.stats.ttft_sum_s += req.ttft_s
        self.stats.decode_latency_sum_s += req.decode_s_per_token

    def _decode_once(self) -> None:
        spec = self.cache.spec
        tokens = np.zeros(spec.max_seqs, dtype=np.int32)
        active = np.zeros(spec.max_seqs, dtype=bool)
        for slot, req in self.running.items():
            tokens[slot] = req.generated[-1]
            active[slot] = True
        nxt, _ = self.engine.decode(self.params, tokens, active)
        self.stats.decode_steps += 1
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += int(active.sum())
        for slot in [s for s, a in enumerate(active) if a]:
            req = self.running.get(slot)
            if req is not None:
                self._emit(req, int(nxt[slot]))

    def _verify_once(self) -> None:
        """One speculative iteration: draft up to spec_k tokens per slot,
        score every slot's (last token + drafts) in ONE batched verify,
        then per slot accept a prefix, roll the cache to the accepted
        length (paged slots return surplus pages), and emit
        accepted + 1 tokens. A slot whose proposer has nothing degrades
        to draft_lens 1 — exactly a decode step. EOS inside the accepted
        run retires the request AT the EOS position: tokens past it are
        never emitted."""
        from flexflow_tpu.serving.spec import accept_drafts

        spec = self.cache.spec
        k = self.spec_k
        proposals = self.proposer.propose(self.running, k)
        tokens = np.zeros((spec.max_seqs, k + 1), dtype=np.int32)
        draft_lens = np.zeros(spec.max_seqs, dtype=np.int32)
        plan: Dict[int, List[int]] = {}
        for slot, req in self.running.items():
            old_len = int(self.cache.lengths[slot])
            # the verify emits up to k_s + 1 tokens and writes k_s + 1
            # rows, so k_s is capped by the request's remaining token
            # budget and by the cache horizon — which also keeps paged
            # verify inside the admission reserve's worst case
            k_s = min(
                len(proposals.get(slot) or ()),
                k,
                req.max_new_tokens - len(req.generated) - 1,
                spec.max_len - old_len - 1,
            )
            drafts = list(proposals.get(slot) or ())[: max(0, k_s)]
            tokens[slot, 0] = req.generated[-1]
            for j, t in enumerate(drafts):
                tokens[slot, 1 + j] = int(t)
            draft_lens[slot] = 1 + len(drafts)
            plan[slot] = drafts
        logits = self.engine.verify(self.params, tokens, draft_lens)
        self.stats.verify_steps += 1
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += len(plan)
        for slot in sorted(plan):
            req = self.running.get(slot)
            if req is None:
                continue
            drafts = plan[slot]
            old_len = int(self.cache.lengths[slot])
            accepted, emitted = accept_drafts(
                logits[slot],
                drafts,
                temperature=self.engine.temperature,
                seed=self.engine.seed,
                slot=slot,
                base_len=old_len,
            )
            # commit the accepted prefix / roll back the rejected tail
            # BEFORE emitting: _emit may retire the request, which frees
            # the slot (truncating a freed slot would be an error)
            self.cache.truncate(slot, old_len + accepted + 1)
            self.proposer.rollback(slot, old_len + accepted + 1)
            self.stats.draft_tokens_proposed += len(drafts)
            self.stats.draft_tokens_accepted += accepted
            for t in emitted:
                self._emit(req, int(t))
                if req.finished:
                    break  # EOS mid-verify: nothing past it is emitted

    def _generate_once(self) -> None:
        if self.proposer is not None:
            self._verify_once()
        else:
            self._decode_once()

    def run(self, requests: Optional[Sequence[Request]] = None) -> List[Request]:
        """Drain the queue (plus `requests`, submitted first) to completion;
        returns finished requests in completion order."""
        for r in requests or ():
            self.submit(r)
        t0 = time.perf_counter()
        while self.queue or self.running:
            self.step()
        self.stats.elapsed_s += time.perf_counter() - t0
        return self.finished


class ContinuousBatchingScheduler(_SchedulerBase):
    """Orca-style: every iteration joins new prefills with in-flight
    decodes; slots recycle the moment a request retires. With a
    `proposer` + `spec_k`, each iteration runs the speculative
    draft/verify step instead of single-token decode."""

    def step(self) -> None:
        self._iter += 1
        self.stats.iterations += 1
        self._admit()
        if self.running:
            self._generate_once()


class StaticBatchingScheduler(_SchedulerBase):
    """Request-level batching baseline: a batch runs until every member
    finishes; freed slots stay idle until the batch drains."""

    def step(self) -> None:
        self._iter += 1
        self.stats.iterations += 1
        if not self.running:
            self._admit()
        if self.running:
            self._generate_once()


_LATENCY_METRICS = {
    "latency": lambda r: r.latency_s,
    "ttft": lambda r: r.ttft_s,
    "decode_per_token": lambda r: r.decode_s_per_token,
}


def latency_percentiles(
    requests: Sequence[Request], pcts=(50, 95), metric: str = "latency"
):
    """{pct: seconds} over finished requests. metric: "latency"
    (submit→finish, the default), "ttft" (submit→first token), or
    "decode_per_token" (per-generated-token decode latency after the
    first — where speculative decoding's win shows up as latency rather
    than throughput)."""
    if metric not in _LATENCY_METRICS:
        raise ValueError(
            f"metric must be one of {sorted(_LATENCY_METRICS)}, got {metric!r}"
        )
    fn = _LATENCY_METRICS[metric]
    lats = [fn(r) for r in requests if r.finished]
    if not lats:
        return {p: 0.0 for p in pcts}
    return {p: float(np.percentile(lats, p)) for p in pcts}
