"""Iteration-level request scheduling (Orca, OSDI'22) with per-request
fault isolation and preemption-by-recompute (vLLM / PagedAttention,
SOSP'23).

The unit of scheduling is one model *iteration*, not one request: every
iteration the scheduler (a) admits queued requests into free KV-cache
slots — strictly FIFO, so admission is starvation-free by construction —
running one prefill batch for the newcomers, then (b) runs one decode
step over ALL in-flight slots. A request leaving (EOS or max-new-tokens)
frees its slot at that same iteration boundary, so the next iteration's
admission can refill it. That is the continuous-batching loop; the
throughput win over request-level ("static") batching comes from never
holding finished requests' slots hostage to the longest request in a
batch.

Speculative decoding (SpecInfer, ASPLOS'24; serving/spec.py) is a mode
of the same loop: when a scheduler carries a `DraftProposer`, step (b)
becomes draft → one batched verify call → accept/rollback, emitting
1..spec_k+1 tokens per slot per iteration instead of exactly one. The
iteration-level frame is unchanged — a verify is just a wider decode —
so admission, retirement, and slot recycling all work as before.

**Request lifecycle.** Every request ends in exactly one terminal
status: FINISHED (EOS / token budget), FAILED (bad input, non-finite
logits, an engine fault, or too many preemptions — the error is captured
on the request), CANCELLED (`scheduler.cancel(rid)`), or TIMED_OUT
(`Request.deadline_s` elapsed, whether queued or running). PREEMPTED is
the one transient status: an optimistic-admission victim whose pages
were reclaimed goes back to the queue head and re-enters RUNNING via
prefill-from-recompute. The resilience contract — proved by
tests/test_resilience.py under a seeded FaultInjector — is that a fault
retires only the requests it touches: every other slot's greedy token
stream is identical to a fault-free run, because greedy decode is a pure
function of a slot's own context, never of which neighbors share the
iteration.

**Admission policies** (paged layout): the default `reserve` policy
admits only when the free pool covers a request's worst case on top of
every in-flight reservation — preemption-free by construction. The
opt-in `optimistic` policy admits on the pages a request needs NOW;
when the pool later runs dry mid-decode (PagePoolExhausted from
`ensure_position`), the scheduler preempts the youngest-by-admission
victims — frees their pages and requeues them at the queue head for
prefill-from-recompute over prompt + tokens generated so far — up to
`max_preemptions` times per request before hard FAILED. Recompute (not
swap) is the right recovery here for the same reason vLLM defaults to
it: a preempted sequence's KV is recomputable from its token history in
one prefill-shaped step, so no swap-space subsystem is needed.

`StaticBatchingScheduler` is the deliberately-worse baseline the bench
and the comparison test measure against: admit a batch, decode until the
WHOLE batch finishes, only then admit the next batch (the reference
FFModel::generate shape, and every pre-Orca serving stack).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flexflow_tpu.serving.kv_cache import PagePoolExhausted


class RequestStatus:
    """String constants (json-friendly) for the request lifecycle."""

    QUEUED = "queued"
    RUNNING = "running"
    PREEMPTED = "preempted"  # transient: requeued for recompute
    FINISHED = "finished"
    FAILED = "failed"
    CANCELLED = "cancelled"
    TIMED_OUT = "timed_out"


#: statuses a request never leaves
TERMINAL_STATUSES = frozenset(
    {
        RequestStatus.FINISHED,
        RequestStatus.FAILED,
        RequestStatus.CANCELLED,
        RequestStatus.TIMED_OUT,
    }
)

_ADMISSION_MODES = ("reserve", "optimistic")


@dataclasses.dataclass
class Request:
    """One generation request. `generated` accumulates post-prompt tokens
    (the first comes from the admission prefill itself). `deadline_s` is
    a wall-clock budget from submit — queued or running, the request is
    TIMED_OUT once it elapses. `events` is the per-request audit log:
    (wall time, event, detail) for submit/admit/first_token/preempt/
    terminal transitions."""

    rid: int
    prompt: List[int]
    max_new_tokens: int = 16
    eos_token: Optional[int] = None
    deadline_s: Optional[float] = None

    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    status: str = RequestStatus.QUEUED
    error: Optional[str] = None
    preemptions: int = 0
    submit_iter: int = -1
    admit_iter: int = -1
    finish_iter: int = -1
    submit_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0
    events: List[Tuple[float, str, str]] = dataclasses.field(
        default_factory=list
    )

    def log(self, event: str, detail: str = "") -> None:
        self.events.append((time.perf_counter(), event, detail))

    @property
    def finished(self) -> bool:
        """Terminal in ANY status — the request will never run again."""
        return self.status in TERMINAL_STATUSES

    @property
    def ok(self) -> bool:
        """Terminal AND successful — the only requests whose latency
        numbers mean anything."""
        return self.status == RequestStatus.FINISHED

    @property
    def latency_s(self) -> float:
        return self.finish_time - self.submit_time

    @property
    def ttft_s(self) -> float:
        """Submit → first generated token (the prefill-side latency a
        user perceives before streaming starts). Meaningless (0.0) for
        a request that never produced a token."""
        if not self.generated:
            return 0.0
        return self.first_token_time - self.submit_time

    @property
    def decode_s_per_token(self) -> float:
        """Mean seconds per generated token AFTER the first — the
        decode-side latency speculative decoding compresses (several
        accepted tokens share one verify step's wall time)."""
        if len(self.generated) <= 1:
            return 0.0
        return (self.finish_time - self.first_token_time) / (
            len(self.generated) - 1
        )

    def deadline_exceeded(self, now: float) -> bool:
        return (
            self.deadline_s is not None
            and now - self.submit_time > self.deadline_s
        )

    def _done_after(self, token: int) -> bool:
        return (
            self.eos_token is not None and token == self.eos_token
        ) or len(self.generated) >= self.max_new_tokens


@dataclasses.dataclass
class SchedulerStats:
    iterations: int = 0
    decode_steps: int = 0
    prefill_batches: int = 0
    tokens_generated: int = 0
    slot_steps: int = 0  # Σ over decode/verify iterations of max_seqs
    busy_slot_steps: int = 0  # Σ of actually-active slots
    peak_in_flight: int = 0  # max concurrent running requests observed
    elapsed_s: float = 0.0
    # speculative decoding (verify iterations only)
    verify_steps: int = 0
    draft_tokens_proposed: int = 0
    draft_tokens_accepted: int = 0
    # request lifecycle (filled at terminal transitions)
    submitted_requests: int = 0
    finished_requests: int = 0  # FINISHED only — not failures
    failed_requests: int = 0
    cancelled_requests: int = 0
    timed_out_requests: int = 0
    preemptions: int = 0  # preempt-and-requeue events
    step_faults: int = 0  # whole-step engine faults (all slots retired)
    draft_faults: int = 0  # proposer faults degraded to plain decode
    tokens_finished: int = 0  # Σ generated over FINISHED requests only
    # per-request latency accumulators (FINISHED requests only — a
    # request failing before its first token has no TTFT to aggregate)
    ttft_sum_s: float = 0.0
    decode_latency_sum_s: float = 0.0  # Σ of per-request decode_s_per_token

    @property
    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def goodput_tokens_per_s(self) -> float:
        """Tokens of successfully FINISHED requests per second — the
        number a resilient scheduler maximizes under faults. Tokens
        generated for requests that later failed, timed out, or were
        cancelled are work, not goodput."""
        return self.tokens_finished / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def terminal_requests(self) -> int:
        return (
            self.finished_requests
            + self.failed_requests
            + self.cancelled_requests
            + self.timed_out_requests
        )

    @property
    def occupancy(self) -> float:
        """Fraction of decode/verify slot-steps that carried a live
        request — the metric continuous batching exists to push toward
        1.0."""
        return self.busy_slot_steps / self.slot_steps if self.slot_steps else 0.0

    @property
    def acceptance_rate(self) -> float:
        """Fraction of drafted tokens the verify step accepted — the
        measured α that optimize_spec_k turns into a draft length."""
        if not self.draft_tokens_proposed:
            return 0.0
        return self.draft_tokens_accepted / self.draft_tokens_proposed

    @property
    def mean_ttft_s(self) -> float:
        if not self.finished_requests:
            return 0.0
        return self.ttft_sum_s / self.finished_requests

    @property
    def mean_decode_s_per_token(self) -> float:
        if not self.finished_requests:
            return 0.0
        return self.decode_latency_sum_s / self.finished_requests


class _SchedulerBase:
    """Shared admission/decode/verify machinery. `proposer` switches the
    per-iteration generation step from plain decode to speculative
    draft/verify (serving/spec.py). `admission` picks the paged cache's
    policy ("reserve" = preemption-free worst-case gate, "optimistic" =
    admit-now/preempt-later, bounded by `max_preemptions` per request).
    `injector` threads a faults.FaultInjector through the step
    boundaries; the isolation machinery below runs either way — the
    injector only makes faults happen on schedule."""

    def __init__(
        self,
        engine,
        params=None,
        proposer=None,
        spec_k: int = 4,
        admission: str = "reserve",
        max_preemptions: int = 3,
        injector=None,
        debug_invariants: bool = False,
    ):
        self.engine = engine
        self.cache = engine.cache
        self.params = params if params is not None else engine.model.params
        self.proposer = proposer
        self.spec_k = int(spec_k)
        if proposer is not None and self.spec_k < 1:
            raise ValueError("speculative decoding needs spec_k >= 1")
        if admission not in _ADMISSION_MODES:
            raise ValueError(
                f"admission must be one of {_ADMISSION_MODES}, "
                f"got {admission!r}"
            )
        self.admission = admission
        self.max_preemptions = int(max_preemptions)
        self.injector = injector
        # ServeConfig.debug_invariants / --check-invariants: re-derive
        # the cache/allocator accounting after EVERY iteration (what the
        # chaos harness does), so an invariant violation surfaces at the
        # iteration that caused it instead of steps later
        self.debug_invariants = bool(debug_invariants)
        self.queue: deque = deque()
        self.running: Dict[int, Request] = {}  # slot -> request
        self.finished: List[Request] = []
        self.stats = SchedulerStats()
        self._by_rid: Dict[int, Request] = {}
        self._iter = 0

    # -- submission / cancellation -------------------------------------------

    def submit(self, request: Request, strict: bool = True) -> bool:
        """Queue a request. Invalid requests raise ValueError when
        `strict` (the library-call contract), or transition straight to
        FAILED when not (the serving-surface contract: one bad request
        must not take down a batch submitted with it). Returns True when
        the request entered the queue."""
        try:
            self._validate(request)
        except ValueError as e:
            if strict:
                raise
            request.submit_iter = self._iter
            request.submit_time = time.perf_counter()
            self._by_rid[request.rid] = request
            self.stats.submitted_requests += 1
            self._finalize(request, RequestStatus.FAILED, error=str(e))
            return False
        request.status = RequestStatus.QUEUED
        request.submit_iter = self._iter
        request.submit_time = time.perf_counter()
        request.log("submit")
        self._by_rid[request.rid] = request
        self.stats.submitted_requests += 1
        self.queue.append(request)
        return True

    def _validate(self, request: Request) -> None:
        if not request.prompt:
            raise ValueError("empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.rid}: max_new_tokens must be >= 1, "
                f"got {request.max_new_tokens}"
            )
        if request.deadline_s is not None and request.deadline_s <= 0:
            raise ValueError(
                f"request {request.rid}: deadline_s must be > 0, "
                f"got {request.deadline_s}"
            )
        need = len(request.prompt) + request.max_new_tokens
        if need > self.cache.spec.max_len:
            raise ValueError(
                f"request {request.rid}: prompt+max_new_tokens {need} "
                f"exceeds cache max_len {self.cache.spec.max_len}"
            )

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or running request; its slot and pages free
        at the next finalize. Returns False for unknown or already-
        terminal rids (cancellation races are expected, not errors)."""
        req = self._by_rid.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        self._finalize(req, RequestStatus.CANCELLED)
        return True

    # -- lifecycle core ------------------------------------------------------

    def _finalize(self, req: Request, status: str, error: Optional[str] = None):
        """The ONLY transition into a terminal status: releases the
        slot/pages (or the queue position), notifies the proposer, logs
        the event, and feeds the stats — so every path (finish, fail,
        cancel, timeout, preemption overrun) accounts identically and no
        request can leak a slot or vanish without a terminal record."""
        if req.status in TERMINAL_STATUSES:
            return
        req.status = status
        req.error = error
        req.finish_iter = self._iter
        req.finish_time = time.perf_counter()
        req.log(status, error or "")
        if req.slot is not None and self.running.get(req.slot) is req:
            if self.proposer is not None:
                self.proposer.retire(req)
            del self.running[req.slot]
            self.cache.free(req.slot)
            req.slot = None
        else:
            # identity-based removal: Request is a dataclass, so the
            # deque's __eq__-based remove() could drop a twin instead
            for i, queued in enumerate(self.queue):
                if queued is req:
                    del self.queue[i]
                    break
        self.finished.append(req)
        stats = self.stats
        if status == RequestStatus.FINISHED:
            stats.finished_requests += 1
            stats.tokens_finished += len(req.generated)
            # latency aggregates take FINISHED requests only: a request
            # retired before its first token has no TTFT, and averaging
            # a 0.0 in would fake lower latencies exactly when faults
            # are making things worse
            stats.ttft_sum_s += req.ttft_s
            stats.decode_latency_sum_s += req.decode_s_per_token
        elif status == RequestStatus.FAILED:
            stats.failed_requests += 1
        elif status == RequestStatus.CANCELLED:
            stats.cancelled_requests += 1
        elif status == RequestStatus.TIMED_OUT:
            stats.timed_out_requests += 1

    def _fail(self, req: Request, error: str) -> None:
        self._finalize(req, RequestStatus.FAILED, error=error)

    def _reap_deadlines(self) -> None:
        now = time.perf_counter()
        for req in [r for r in self.queue if r.deadline_exceeded(now)]:
            self._finalize(req, RequestStatus.TIMED_OUT)
        for req in [
            r for r in list(self.running.values()) if r.deadline_exceeded(now)
        ]:
            self._finalize(req, RequestStatus.TIMED_OUT)

    # -- preemption (optimistic admission) -----------------------------------

    def _pick_victim(self) -> Optional[Request]:
        """Youngest-by-admission running request — the vLLM victim rule:
        the newest sequence has the least recompute to lose and, under
        FIFO, the weakest fairness claim. (admit_iter, rid) makes the
        choice deterministic within an admission batch."""
        if not self.running:
            return None
        return max(
            self.running.values(), key=lambda r: (r.admit_iter, r.rid)
        )

    def _preempt(self, req: Request) -> None:
        """Reclaim the victim's slot and pages and requeue it at the
        queue HEAD for prefill-from-recompute (prompt + generated so
        far). A request preempted more than `max_preemptions` times
        hard-fails instead — the bound that turns a livelock into a
        diagnosable error."""
        req.preemptions += 1
        self.stats.preemptions += 1
        if req.preemptions > self.max_preemptions:
            self._fail(
                req,
                f"preempted {req.preemptions} times "
                f"(max_preemptions {self.max_preemptions})",
            )
            return
        req.status = RequestStatus.PREEMPTED
        req.log("preempt", f"iteration {self._iter}")
        if self.proposer is not None:
            self.proposer.retire(req)
        del self.running[req.slot]
        self.cache.free(req.slot)
        req.slot = None
        req.status = RequestStatus.QUEUED
        self.queue.appendleft(req)

    def _secure_pages(self, widths: Dict[int, int]) -> None:
        """Claim every page this iteration's step will touch BEFORE the
        jitted call: slot s writes rows lengths[s] .. lengths[s] +
        widths[s] - 1. Under reserve admission the claims are guaranteed
        (a PagePoolExhausted here means something outside the accounting
        drained the pool — an injected fault — and fails just that
        slot); under optimistic admission a dry pool preempts the
        youngest victim and retries, so the engine's own ensure_position
        calls always find the pages already present."""
        if not getattr(self.cache, "paged", False):
            return
        for slot in sorted(widths):
            req = self.running.get(slot)
            if req is None:
                continue
            start = int(self.cache.lengths[slot])
            pos = start
            while req.status == RequestStatus.RUNNING and (
                pos < start + widths[slot]
            ):
                try:
                    self.cache.ensure_position(slot, pos)
                    pos += 1
                except PagePoolExhausted as e:
                    if self.admission != "optimistic":
                        self._fail(req, str(e))
                        break
                    victim = self._pick_victim()
                    if victim is None:
                        self._fail(req, str(e))
                        break
                    self._preempt(victim)
                    # preempting may have evicted `req` itself (it was
                    # the youngest); its requeue ends the claim loop

    # -- shared pieces -------------------------------------------------------

    def _admit(self, limit: Optional[int] = None) -> List[Request]:
        """FIFO admission into free slots (never reorders the queue —
        starvation-free: the head either admits or blocks everyone
        behind it) + ONE prefill batch for the admitted set. Admission
        asks the cache, so the gate is layout-specific: the slot layout
        admits while a slot is free; the paged layout also requires
        enough free PAGES — the request's worst case under the reserve
        policy, only its immediate need under the optimistic one. A
        preempted request re-admits with its recompute sequence
        (prompt + tokens already generated): the prefill rebuilds the
        KV it lost and its next token comes out of that same call."""
        optimistic = self.admission == "optimistic"
        admitted: List[Request] = []
        seqs: List[List[int]] = []
        while self.queue:
            if limit is not None and len(admitted) >= limit:
                break
            req = self.queue[0]
            seq = list(req.prompt) + list(req.generated)
            slot = self.cache.alloc(
                len(seq),
                len(req.prompt) + req.max_new_tokens,
                optimistic=optimistic,
            )
            if slot is None:
                break
            self.queue.popleft()
            req.slot = slot
            req.admit_iter = self._iter
            req.status = RequestStatus.RUNNING
            req.log("admit", f"slot {slot}")
            self.running[req.slot] = req
            admitted.append(req)
            seqs.append(seq)
        self.stats.peak_in_flight = max(
            self.stats.peak_in_flight, len(self.running)
        )
        if admitted:
            if self.proposer is not None:
                self.proposer.admit(admitted)
            try:
                nxt, last = self.engine.prefill(
                    self.params, seqs, [r.slot for r in admitted]
                )
            except Exception as e:  # fault isolation: the batch fails,
                # in-flight slots are untouched and keep decoding
                self.stats.step_faults += 1
                for req in admitted:
                    self._fail(req, f"prefill failed: {e!r}")
                return admitted
            self.stats.prefill_batches += 1
            if self.injector is not None:
                # np.array (copy): the step's output buffer is read-only
                last = np.array(last)
                self.injector.corrupt_logits(
                    last,
                    [r.slot for r in admitted],
                    rows=range(len(admitted)),
                )
            for i, (tok, req) in enumerate(zip(nxt, admitted)):
                if not np.isfinite(last[i]).all():
                    self._fail(
                        req,
                        f"non-finite prefill logits at iteration "
                        f"{self._iter}",
                    )
                    continue
                self._emit(req, int(tok))
        return admitted

    def _emit(self, req: Request, token: int) -> None:
        req.generated.append(token)
        if len(req.generated) == 1:
            req.first_token_time = time.perf_counter()
            req.log("first_token")
        self.stats.tokens_generated += 1
        if req._done_after(token):
            self._finalize(req, RequestStatus.FINISHED)

    def _fail_all_running(self, error: str) -> None:
        """Whole-step engine fault with no slot attribution: retire every
        participant with the captured error rather than crash the run —
        the queue behind them keeps serving."""
        self.stats.step_faults += 1
        for req in list(self.running.values()):
            self._fail(req, error)

    def _decode_once(self) -> None:
        self._secure_pages({slot: 1 for slot in self.running})
        if not self.running:
            return
        spec = self.cache.spec
        tokens = np.zeros(spec.max_seqs, dtype=np.int32)
        active = np.zeros(spec.max_seqs, dtype=bool)
        for slot, req in self.running.items():
            tokens[slot] = req.generated[-1]
            active[slot] = True
        try:
            nxt, logits = self.engine.decode(self.params, tokens, active)
        except Exception as e:
            self._fail_all_running(f"decode step failed: {e!r}")
            return
        self.stats.decode_steps += 1
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += int(active.sum())
        active_slots = [s for s, a in enumerate(active) if a]
        if self.injector is not None:
            logits = np.array(logits)  # writable copy for the injector
            self.injector.corrupt_logits(logits, active_slots)
        for slot in active_slots:
            req = self.running.get(slot)
            if req is None:
                continue
            if not np.isfinite(logits[slot]).all():
                self._fail(
                    req, f"non-finite logits at iteration {self._iter}"
                )
                continue
            self._emit(req, int(nxt[slot]))

    def _propose(self, k: int) -> Dict[int, List[int]]:
        """Draft tokens for the running slots; a proposer fault (real or
        injected) degrades THIS iteration to plain decode — empty
        proposals make every verify a w=1 decode — instead of killing
        the run."""
        try:
            if self.injector is not None:
                self.injector.maybe_draft_fault()
            return self.proposer.propose(self.running, k)
        except Exception:
            self.stats.draft_faults += 1
            return {}

    def _verify_once(self) -> None:
        """One speculative iteration: draft up to spec_k tokens per slot,
        score every slot's (last token + drafts) in ONE batched verify,
        then per slot accept a prefix, roll the cache to the accepted
        length (paged slots return surplus pages), and emit
        accepted + 1 tokens. A slot whose proposer has nothing degrades
        to draft_lens 1 — exactly a decode step. EOS inside the accepted
        run retires the request AT the EOS position: tokens past it are
        never emitted."""
        from flexflow_tpu.serving.spec import accept_drafts

        spec = self.cache.spec
        k = self.spec_k
        proposals = self._propose(k)
        plan: Dict[int, List[int]] = {}
        for slot, req in self.running.items():
            old_len = int(self.cache.lengths[slot])
            # the verify emits up to k_s + 1 tokens and writes k_s + 1
            # rows, so k_s is capped by the request's remaining token
            # budget and by the cache horizon — which also keeps paged
            # verify inside the admission reserve's worst case
            k_s = min(
                len(proposals.get(slot) or ()),
                k,
                req.max_new_tokens - len(req.generated) - 1,
                spec.max_len - old_len - 1,
            )
            plan[slot] = list(proposals.get(slot) or ())[: max(0, k_s)]
        # claim pages for every row the verify writes; optimistic
        # preemption may evict plan slots, so the arrays build AFTER
        self._secure_pages({s: 1 + len(d) for s, d in plan.items()})
        plan = {s: d for s, d in plan.items() if s in self.running}
        if not plan:
            return
        tokens = np.zeros((spec.max_seqs, k + 1), dtype=np.int32)
        draft_lens = np.zeros(spec.max_seqs, dtype=np.int32)
        for slot, drafts in plan.items():
            req = self.running[slot]
            tokens[slot, 0] = req.generated[-1]
            for j, t in enumerate(drafts):
                tokens[slot, 1 + j] = int(t)
            draft_lens[slot] = 1 + len(drafts)
        try:
            logits = self.engine.verify(self.params, tokens, draft_lens)
        except Exception as e:
            self._fail_all_running(f"verify step failed: {e!r}")
            return
        self.stats.verify_steps += 1
        self.stats.slot_steps += spec.max_seqs
        self.stats.busy_slot_steps += len(plan)
        if self.injector is not None:
            logits = np.array(logits)  # writable copy for the injector
            self.injector.corrupt_logits(logits, sorted(plan))
        for slot in sorted(plan):
            req = self.running.get(slot)
            if req is None:
                continue
            drafts = plan[slot]
            old_len = int(self.cache.lengths[slot])
            if not np.isfinite(logits[slot, : 1 + len(drafts)]).all():
                # lengths never advanced for this slot; freeing it
                # returns its pages, stale verify rows and all
                self._fail(
                    req, f"non-finite logits at iteration {self._iter}"
                )
                continue
            accepted, emitted = accept_drafts(
                logits[slot],
                drafts,
                temperature=self.engine.temperature,
                seed=self.engine.seed,
                slot=slot,
                base_len=old_len,
            )
            # commit the accepted prefix / roll back the rejected tail
            # BEFORE emitting: _emit may retire the request, which frees
            # the slot (truncating a freed slot would be an error)
            self.cache.truncate(slot, old_len + accepted + 1)
            self.proposer.rollback(slot, old_len + accepted + 1)
            self.stats.draft_tokens_proposed += len(drafts)
            self.stats.draft_tokens_accepted += accepted
            for t in emitted:
                self._emit(req, int(t))
                if req.finished:
                    break  # EOS mid-verify: nothing past it is emitted

    def _generate_once(self) -> None:
        if self.proposer is not None:
            self._verify_once()
        else:
            self._decode_once()

    def _begin_iteration(self) -> None:
        self._iter += 1
        self.stats.iterations += 1
        if self.injector is not None:
            self.injector.on_iteration(self._iter, self)
        self._reap_deadlines()

    def _end_iteration(self) -> None:
        if self.debug_invariants:
            self.cache.check_invariants()

    def run(self, requests: Optional[Sequence[Request]] = None) -> List[Request]:
        """Drain the queue (plus `requests`, submitted first) to
        completion; returns requests in terminal order — check
        `Request.status`/`Request.ok`, a fault-isolated run finishes
        with FAILED entries instead of raising."""
        for r in requests or ():
            self.submit(r)
        t0 = time.perf_counter()
        while self.queue or self.running:
            self.step()
        self.stats.elapsed_s += time.perf_counter() - t0
        return self.finished


class ContinuousBatchingScheduler(_SchedulerBase):
    """Orca-style: every iteration joins new prefills with in-flight
    decodes; slots recycle the moment a request retires. With a
    `proposer` + `spec_k`, each iteration runs the speculative
    draft/verify step instead of single-token decode."""

    def step(self) -> None:
        self._begin_iteration()
        self._admit()
        if self.running:
            self._generate_once()
        self._end_iteration()


class StaticBatchingScheduler(_SchedulerBase):
    """Request-level batching baseline: a batch runs until every member
    finishes; freed slots stay idle until the batch drains."""

    def step(self) -> None:
        self._begin_iteration()
        if not self.running:
            self._admit()
        if self.running:
            self._generate_once()
        self._end_iteration()


_LATENCY_METRICS = {
    "latency": lambda r: r.latency_s,
    "ttft": lambda r: r.ttft_s,
    "decode_per_token": lambda r: r.decode_s_per_token,
}


def latency_percentiles(
    requests: Sequence[Request], pcts=(50, 95), metric: str = "latency"
):
    """{pct: seconds} over successfully FINISHED requests (failed,
    cancelled, and timed-out requests have no meaningful latency and
    would drag the percentiles toward zero). metric: "latency"
    (submit→finish, the default), "ttft" (submit→first token), or
    "decode_per_token" (per-generated-token decode latency after the
    first — where speculative decoding's win shows up as latency rather
    than throughput)."""
    if metric not in _LATENCY_METRICS:
        raise ValueError(
            f"metric must be one of {sorted(_LATENCY_METRICS)}, got {metric!r}"
        )
    fn = _LATENCY_METRICS[metric]
    lats = [fn(r) for r in requests if r.ok]
    if not lats:
        return {p: 0.0 for p in pcts}
    return {p: float(np.percentile(lats, p)) for p in pcts}
