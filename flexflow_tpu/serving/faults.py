"""Deterministic fault injection for the serving engine.

Production serving dies from the faults nobody scheduled: a kernel
miscompiles on one geometry, a model emits NaN logits for one request,
a burst of long prompts drains the page pool, a client disconnects
mid-stream. The resilience contract (serving/scheduler.py) is that every
such fault retires ONE request — or falls back to a slower path — while
every unaffected request's greedy token stream stays identical to a
fault-free run. A contract like that is only worth having if it is
*proved*, so this module is a chaos harness: a seeded `FaultInjector`
threaded through the engine/scheduler seams that injects

* **corrupted logits** — one slot's logits row becomes NaN after a
  decode/verify/prefill step (the scheduler's per-step finite guard must
  retire exactly that slot as FAILED);
* **kernel failure** — the next Pallas-kernel dispatch raises
  (the engine must fall back to the dense attention paths, permanently,
  and keep serving);
* **page-pool exhaustion** — pages are stolen from the paged cache's
  free pool for a bounded window (under optimistic admission the
  scheduler must preempt-and-recompute; the allocator invariants must
  hold throughout);
* **step latency spikes** — a host-side sleep before an iteration
  (deadlines must fire, goodput accounting must stay honest);
* **mid-flight cancellation** — `scheduler.cancel(rid)` on a running
  request (its slot and pages must free; the stream must stop);
* **swap failure** — a KV swap_out/swap_in attempt refuses (the
  scheduler must degrade to recompute-preemption / recompute
  re-admission — never a lost request);
* **host-partition failure** — a pod host partition goes down for a
  bounded window (the scheduler must drain its requests to survivors
  and re-join it on recovery);
* **engine-replica failure** — a front-door engine replica dies
  mid-stream (the router must evacuate its requests to surviving
  replicas with zero lost streams);
* **process crash** — the whole engine process dies at an iteration
  boundary, before or after the write-ahead journal's commit flush
  (a restart must rebuild the live set from the journal and resume
  every stream token-identically — serving/journal.py);
* **journal write failure** — an append to the write-ahead journal
  refuses (the journal must degrade to undurable, never block or
  kill serving).

Determinism discipline: every decision draws from a fresh
`np.random.default_rng([seed, iteration, site, key])` stream, so the
schedule is a pure function of (seed, plan, workload) and independent of
host call ordering — the property the token-identity proofs in
tests/test_resilience.py are built on.
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from flexflow_tpu.serving.kv_cache import PagePoolExhausted

__all__ = [
    "FaultError",
    "KernelFault",
    "DraftFault",
    "ProcessCrash",
    "PagePoolExhausted",
    "FaultPlan",
    "FaultInjector",
]


class FaultError(RuntimeError):
    """Base class for injected faults."""


class KernelFault(FaultError):
    """Injected Pallas-kernel dispatch failure (the engine answers by
    falling back to the dense attention paths)."""


class DraftFault(FaultError):
    """Injected draft-proposer failure (the scheduler answers by
    degrading the iteration to plain decode)."""


class ProcessCrash(FaultError):
    """Injected engine-process death. Deliberately NOT absorbed by the
    scheduler's per-step fault isolation: it propagates out of `step()`
    to the harness, which abandons the scheduler object entirely and
    restarts from the journal — the in-process stand-in for kill -9."""


# deterministic sub-stream ids per injection site
_SITE = {
    "spike": 1,
    "cancel": 2,
    "nan": 3,
    "kernel": 4,
    "draft": 5,
    "swap_fail": 6,
    "host_down": 7,
    "replica_down": 8,
    "crash": 9,
    "journal_fail": 10,
}


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to inject. Rates are per-opportunity probabilities drawn
    from the injector's seeded streams; the `*_iters` fields schedule
    faults at EXACT scheduler iterations for targeted tests (both
    compose). All-zero defaults inject nothing."""

    # corrupted (NaN) logits: per-(iteration, slot) probability, plus an
    # explicit {iteration: [slot, ...]} schedule
    nan_rate: float = 0.0
    nan_iters: Mapping[int, Sequence[int]] = dataclasses.field(
        default_factory=dict
    )
    # Pallas-kernel dispatch failure: per-dispatch probability, plus
    # explicit scheduler iterations. Only fires while the engine is on a
    # kernel path — once fallen back to dense there is nothing to fail.
    kernel_rate: float = 0.0
    kernel_iters: Sequence[int] = ()
    # draft-proposer failure (spec mode): per-iteration probability plus
    # explicit iterations; the iteration degrades to plain decode
    draft_rate: float = 0.0
    draft_iters: Sequence[int] = ()
    # host-side latency spike before an iteration
    spike_rate: float = 0.0
    spike_s: float = 0.0
    # mid-flight cancellation: per-(iteration, running rid) probability,
    # plus an explicit {iteration: [rid, ...]} schedule
    cancel_rate: float = 0.0
    cancel_iters: Mapping[int, Sequence[int]] = dataclasses.field(
        default_factory=dict
    )
    # page-pool exhaustion: at each listed iteration, steal up to
    # `steal_pages` pages from the paged cache's free pool and hold them
    # for `steal_hold` iterations before returning them
    steal_iters: Sequence[int] = ()
    steal_pages: int = 0
    steal_hold: int = 2
    # KV swap failure: per-attempt probability that a swap_out (stage to
    # host) or swap_in (restore) refuses — the scheduler must degrade to
    # recompute-preemption / recompute re-admission, never lose the
    # request
    swap_fail_rate: float = 0.0
    swap_fail_iters: Sequence[int] = ()
    # host-partition failure: {iteration: host} marks that host's
    # partition lost at that iteration; it recovers (scheduler.host_up)
    # `host_down_hold` iterations later
    host_down_iters: Mapping[int, int] = dataclasses.field(
        default_factory=dict
    )
    host_down_hold: int = 3
    # engine-replica failure (front-door router): {iteration: replica}
    # marks that replica killed at that router iteration — the router
    # must evacuate its streams to survivors with zero lost requests.
    # Unlike host_down there is no recovery window: a killed replica's
    # process is gone; the chaos leg proves the drain, not the re-join.
    replica_down_iters: Mapping[int, int] = dataclasses.field(
        default_factory=dict
    )
    # process crash: {iteration: phase} kills the engine process at that
    # scheduler iteration. Phase "begin" crashes at the step boundary
    # BEFORE any work (nothing new to lose); phase "commit" crashes at
    # the END of the iteration AFTER tokens were emitted but BEFORE the
    # journal's commit flush — the worst case: a whole fused multi-step
    # window's or tree-verify round's accepted run is host-visible yet
    # unjournaled, and the restart must recompute it token-identically.
    crash_iters: Mapping[int, str] = dataclasses.field(default_factory=dict)
    # journal write failure: at each listed iteration the NEXT journal
    # append refuses (OSError stand-in); the journal must degrade, not
    # raise into the serving path
    journal_fail_iters: Sequence[int] = ()

    def __post_init__(self):
        for name in ("nan_rate", "kernel_rate", "draft_rate", "spike_rate",
                     "cancel_rate", "swap_fail_rate"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        if self.spike_s < 0.0 or self.steal_pages < 0 or self.steal_hold < 0:
            raise ValueError("spike_s / steal_pages / steal_hold must be >= 0")
        if self.host_down_hold < 1:
            raise ValueError(
                f"host_down_hold must be >= 1, got {self.host_down_hold}"
            )
        for it, host in self.host_down_iters.items():
            if int(it) < 0 or int(host) < 0:
                raise ValueError(
                    "host_down_iters maps iterations >= 0 to hosts >= 0, "
                    f"got {{{it}: {host}}}"
                )
        for it, rep in self.replica_down_iters.items():
            if int(it) < 0 or int(rep) < 0:
                raise ValueError(
                    "replica_down_iters maps iterations >= 0 to replicas "
                    f">= 0, got {{{it}: {rep}}}"
                )
        for it, phase in self.crash_iters.items():
            if int(it) < 0 or phase not in ("begin", "commit"):
                raise ValueError(
                    "crash_iters maps iterations >= 0 to phase "
                    f"'begin'|'commit', got {{{it}: {phase!r}}}"
                )
        if any(int(it) < 0 for it in self.journal_fail_iters):
            raise ValueError("journal_fail_iters must be iterations >= 0")


class FaultInjector:
    """Seeded, deterministic fault source threaded through the serving
    seams. The scheduler calls `on_iteration` at every step boundary
    (spikes, cancellations, page steal/return), `corrupt_logits` on each
    step's host-side logits, and `maybe_draft_fault` before proposing;
    the engine calls `maybe_kernel_fault` before each kernel-path
    dispatch. `injected` counts every fault that actually fired, keyed
    by site — the ledger the chaos bench publishes."""

    def __init__(self, plan: FaultPlan = None, seed: int = 0):
        self.plan = plan if plan is not None else FaultPlan()
        self.seed = int(seed) & 0x7FFFFFFF
        self.injected: Counter = Counter()
        self._iter = 0
        # pages stolen from a paged cache's free pool: [(page, release_iter)]
        self._stolen: List[Tuple[int, int]] = []
        # host partitions currently marked down: [(host, recover_iter)]
        self._downed: List[Tuple[int, int]] = []

    def _rng(
        self, site: str, key: int = 0, iteration: Optional[int] = None
    ) -> np.random.Generator:
        it = self._iter if iteration is None else int(iteration)
        return np.random.default_rng(
            [self.seed, it, _SITE[site], int(key) & 0x7FFFFFFF]
        )

    @property
    def stolen_pages(self) -> int:
        """Pages currently held outside the cache's free pool — the
        allocator invariant check must count them (check_invariants
        extra_free)."""
        return len(self._stolen)

    # -- scheduler seams -----------------------------------------------------

    def on_iteration(self, iteration: int, scheduler) -> None:
        """Step-boundary faults: latency spike, cancellations, page
        steal/return. Called by the scheduler BEFORE admission so a
        stolen page affects this iteration's gate."""
        self._iter = int(iteration)
        plan = self.plan
        if plan.spike_s > 0.0 and plan.spike_rate > 0.0:
            if self._rng("spike").random() < plan.spike_rate:
                self.injected["spike"] += 1
                time.sleep(plan.spike_s)
        # cancellations: explicit rids first, then rate draws over the
        # running set (sorted for determinism)
        for rid in plan.cancel_iters.get(self._iter, ()):
            if scheduler.cancel(int(rid)):
                self.injected["cancel"] += 1
        if plan.cancel_rate > 0.0:
            rids = sorted(r.rid for r in scheduler.running.values())
            for rid in rids:
                if self._rng("cancel", rid).random() < plan.cancel_rate:
                    if scheduler.cancel(rid):
                        self.injected["cancel"] += 1
        cache = scheduler.cache
        if getattr(cache, "paged", False):
            self._page_faults(cache)
        self._host_faults(scheduler)

    def _host_faults(self, scheduler) -> None:
        """Recover held-down hosts whose hold window closed, then fire
        this iteration's scheduled host_down. Never downs the last
        alive host — a pod with zero partitions is an outage, not a
        degradation, and the drain contract (every request completes on
        survivors) would be unsatisfiable."""
        plan = self.plan
        cache = scheduler.cache
        if not plan.host_down_iters and not self._downed:
            return
        kept: List[Tuple[int, int]] = []
        for host, recover_iter in self._downed:
            if self._iter >= recover_iter:
                scheduler.host_up(host)
            else:
                kept.append((host, recover_iter))
        self._downed = kept
        host = plan.host_down_iters.get(self._iter)
        if host is None:
            return
        host = int(host)
        num_hosts = getattr(cache, "num_hosts", 1)
        if not getattr(cache, "paged", False) or num_hosts <= 1:
            return
        down = {h for h, _ in self._downed}
        if host in down or host >= num_hosts:
            return
        if len(down) + 1 >= num_hosts:
            return  # never down the last alive host
        scheduler.host_down(host)
        self._downed.append((host, self._iter + plan.host_down_hold))
        self.injected["host_down"] += 1

    def _page_faults(self, cache) -> None:
        """Steal pages at scheduled iterations; return them after the
        hold window. Stolen pages leave the free heap entirely — the
        closest host-side analog to a neighbor tenant (or a leak)
        draining the pool out from under the allocator."""
        import heapq

        plan = self.plan
        kept: List[Tuple[int, int]] = []
        for page, release_iter in self._stolen:
            if self._iter >= release_iter:
                heapq.heappush(cache._free_pages, page)
            else:
                kept.append((page, release_iter))
        self._stolen = kept
        if self._iter in set(plan.steal_iters) and plan.steal_pages > 0:
            for _ in range(min(plan.steal_pages, len(cache._free_pages))):
                page = heapq.heappop(cache._free_pages)
                self._stolen.append((page, self._iter + plan.steal_hold))
                self.injected["page_steal"] += 1

    def release_stolen_pages(self, cache) -> None:
        """Return every held page immediately (end-of-run cleanup)."""
        import heapq

        for page, _ in self._stolen:
            heapq.heappush(cache._free_pages, page)
        self._stolen = []

    def corrupt_logits(
        self, logits: np.ndarray, slots, rows=None, iteration=None
    ) -> List[int]:
        """Overwrite the listed-or-drawn slots' logits rows with NaN in
        place (logits is a host-side array a step returned). The fault
        schedule is keyed by SLOT id; `rows` maps each slot to its row
        index in `logits` when the two differ (prefill returns one row
        per admitted request, decode/verify one row per slot). Returns
        the corrupted slots. The scheduler's finite guard — not this
        method — decides what happens next, exactly as it would for a
        model-produced NaN.

        `iteration` re-keys the schedule for the async engine's
        in-flight window: a step DISPATCHED at iteration i reconciles —
        and has its logits corrupted — an iteration later, so the async
        scheduler passes the step's dispatch iteration and a seeded
        `nan_iters={i: [slot]}` plan lands on the same step it would
        hit under the sync loop."""
        plan = self.plan
        it = self._iter if iteration is None else int(iteration)
        slots = [int(s) for s in slots]
        rows = slots if rows is None else [int(r) for r in rows]
        hit: List[int] = []
        scheduled = set(plan.nan_iters.get(it, ()))
        for slot, row in sorted(zip(slots, rows)):
            if slot in scheduled or (
                plan.nan_rate > 0.0
                and self._rng("nan", slot, iteration=it).random()
                < plan.nan_rate
            ):
                logits[row] = np.nan
                hit.append(slot)
                self.injected["nan"] += 1
        return hit

    def maybe_swap_fail(self, op: str = "swap_out") -> bool:
        """Whether this swap attempt fails. `op` is "swap_out" (staging
        a victim's pages to host) or "swap_in" (restoring them) — the
        two draw from distinct sub-streams so a plan can be replayed
        regardless of how many of each the scheduler attempts. The
        scheduler degrades a failed swap to recompute; this method only
        decides and counts."""
        plan = self.plan
        if plan.swap_fail_rate <= 0.0 and not plan.swap_fail_iters:
            return False
        key = 0 if op == "swap_out" else 1
        if self._iter in set(plan.swap_fail_iters) or (
            plan.swap_fail_rate > 0.0
            and self._rng("swap_fail", key).random() < plan.swap_fail_rate
        ):
            self.injected["swap_fail"] += 1
            return True
        return False

    def maybe_replica_down(self, iteration: int) -> Optional[int]:
        """The replica scheduled to die at this router iteration, or
        None. Consulted by the front-door router at each step boundary;
        the router — not this method — performs the evacuation (it
        alone knows the survivor set), this method only schedules and
        counts. The router is expected to refuse killing the last alive
        replica, same contract as `_host_faults`."""
        rep = self.plan.replica_down_iters.get(int(iteration))
        if rep is None:
            return None
        self.injected["replica_down"] += 1
        return int(rep)

    def maybe_crash(self, phase: str) -> None:
        """Raise ProcessCrash when the plan schedules this iteration's
        `phase` boundary. The scheduler consults it at two seams:
        "begin" right after `on_iteration` (the step dies before doing
        work) and "commit" at the end of `_end_iteration` BEFORE the
        journal's commit flush (the step's emitted tokens die
        unjournaled — a crash mid-fused-window or mid-tree-verify, since
        those reconcile exactly once per iteration)."""
        if self.plan.crash_iters.get(self._iter) == phase:
            self.injected["crash"] += 1
            raise ProcessCrash(
                f"injected process crash at iteration {self._iter} "
                f"({phase} phase)"
            )

    def maybe_journal_fail(self) -> bool:
        """Whether the next journal append fails. Consulted by
        RequestJournal inside every `_append`; the journal answers a
        True by entering degraded mode (undurable, still serving)."""
        if self._iter in set(self.plan.journal_fail_iters):
            self.injected["journal_fail"] += 1
            return True
        return False

    def maybe_draft_fault(self) -> None:
        plan = self.plan
        if self._iter in set(plan.draft_iters) or (
            plan.draft_rate > 0.0
            and self._rng("draft").random() < plan.draft_rate
        ):
            self.injected["draft"] += 1
            raise DraftFault(f"injected draft fault at iteration {self._iter}")

    # -- engine seam ---------------------------------------------------------

    def maybe_kernel_fault(self, site: str = "decode") -> None:
        """Raise KernelFault when the plan says this dispatch fails. The
        engine only consults this on kernel-path dispatches, so a
        fallen-back (dense) engine never faults again."""
        plan = self.plan
        if self._iter in set(plan.kernel_iters) or (
            plan.kernel_rate > 0.0
            and self._rng("kernel").random() < plan.kernel_rate
        ):
            self.injected["kernel"] += 1
            raise KernelFault(
                f"injected {site} kernel fault at iteration {self._iter}"
            )

    def summary(self) -> Dict[str, int]:
        return dict(self.injected)

    def publish_metrics(self, registry) -> None:
        """Mirror the injected-fault ledger into a
        telemetry.MetricsRegistry as
        `serve_fault_injections_total{site=...}` — a fault the
        observability layer cannot see is a bug, so the chaos bench
        asserts every site that fired here appears in the exported
        metrics with the same count."""
        for site, n in self.injected.items():
            registry.counter(
                "serve_fault_injections_total",
                help="faults the injector actually fired, by site",
                labels={"site": site},
            ).set_monotonic(n)
