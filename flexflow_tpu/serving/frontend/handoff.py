"""Prefill-tier → decode-tier handoff (disaggregated serving).

The DistServe/Splitwise posture: a burst of long prompts saturating
chunked prefill must not inflate the inter-token latency of streams
already decoding, so prefill and decode run on SEPARATE engines. A
dedicated prefill engine (`PrefillOnlyScheduler` — the continuous loop
with the decode/verify half cut out) streams each prompt in by chunks
and emits the first token; the committed KV pages (int8 scale slivers
included) then stage out over the swap path (`scheduler.stage_out` →
`cache.export_swap`) and restore into the decode tier's cache
(`cache.import_swap`), where the stream resumes as plain decode from
`generated[-1]` — the exact re-admission contract swapped preemption
victims already use, so the restored stream is bit-identical to one
that never moved.

Refusals degrade, never lose: a stage-out the prefill cache refuses
(budget, in-flight step) retries next pipeline step; a record the
decode cache refuses (its own swap budget) falls back to recompute
admission on the decode tier (the prompt + first token re-prefill
there), counted as `serve_handoff_fallback_total`.

Both tiers keep their own telemetry bundles — gauges like
`serve_queue_depth` mean per-tier numbers, and the pipeline's own
`serve_handoff_*` counters land in the decode tier's registry (the
tier that owns the user-visible stream).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from flexflow_tpu.serving.scheduler import (
    ContinuousBatchingScheduler,
    Request,
)

__all__ = ["PrefillOnlyScheduler", "DisaggregatedPipeline"]


class PrefillOnlyScheduler(ContinuousBatchingScheduler):
    """The continuous-batching loop with decode cut out: admissions and
    chunked prefill only. A request is DONE here the moment its last
    chunk commits (the final chunk emits the stream's first token —
    TTFT is a prefill-tier number); it then waits in `running`, holding
    its committed pages, for `stage_out`. Deadlines still reap at every
    step boundary, so a request whose handoff never comes times out
    instead of squatting a slot forever."""

    def step(self) -> None:
        self._begin_iteration()
        self._admit()
        if self.token_budget and self.running:
            self._chunk_once()
        self._end_iteration()

    def ready_for_handoff(self) -> List[Request]:
        """Requests whose prompt is fully committed and first token
        emitted — everything the decode tier needs is in the pool.
        Admission order keeps the handoff FIFO-fair."""
        return sorted(
            (
                r
                for r in self.running.values()
                if r.generated and not self._prefill_pending(r)
            ),
            key=lambda r: (r.admit_iter, r.rid),
        )


class DisaggregatedPipeline:
    """Two engines, one request lifecycle: submit → prefill tier
    (chunked prefill, first token) → KV stage-out/import → decode tier
    (plain decode to completion). Presents the same driving surface as
    a single scheduler (`submit` / `cancel` / `step` / `run` /
    `work_pending`), so the front-door server and the bench drive it
    interchangeably with a monolithic engine.

    `serve` configures the decode tier verbatim (async double-buffering
    included); the prefill tier runs the same config pinned to the
    synchronous chunk-only loop — chunked prefill needs
    `serve.token_budget` set, enforced here because a prefill tier that
    monolithically prefills would hold its admission gate wide open and
    the disaggregation would prove nothing."""

    def __init__(
        self,
        prefill_model,
        decode_model,
        serve,
        injector=None,
    ):
        from flexflow_tpu.serving.api import build_scheduler

        if serve.kv_layout != "paged":
            raise ValueError(
                "disaggregated handoff needs kv_layout='paged' (KV "
                "moves between tiers page-by-page over the swap path)"
            )
        if not serve.token_budget:
            raise ValueError(
                "disaggregated handoff needs a token_budget (the "
                "prefill tier streams prompts in by chunks)"
            )
        pserve = dataclasses.replace(serve, serve_async=False)
        (
            self.prefill_sched,
            self.prefill_engine,
            self.prefill_cache,
        ) = build_scheduler(
            prefill_model,
            pserve,
            injector=injector,
            scheduler_cls=PrefillOnlyScheduler,
        )
        (
            self.decode_sched,
            self.decode_engine,
            self.decode_cache,
        ) = build_scheduler(decode_model, serve, injector=injector)
        self.handoffs = 0
        self.handoff_fallbacks = 0
        self.handoff_bytes = 0
        # wall time spent inside each tier's steps — the clocks a
        # bench attributes latency to: on disaggregated hardware the
        # tiers run concurrently, so decode latency is decode-tier
        # time (not the in-process interleaving's sum), and the
        # overlap a concurrent deployment hides is bounded by the
        # smaller tier's clock
        self.prefill_step_s = 0.0
        self.decode_step_s = 0.0

    # -- scheduler-compatible surface ----------------------------------------

    def submit(self, request: Request) -> bool:
        return self.prefill_sched.submit(request)

    def cancel(self, rid: int) -> bool:
        """Cancel wherever the request currently lives. There is no
        in-between: a handoff completes (or falls back) within one
        `_drain_ready` call, so every non-terminal request is owned by
        exactly one tier."""
        return self.prefill_sched.cancel(rid) or self.decode_sched.cancel(
            rid
        )

    def work_pending(self) -> bool:
        return (
            self.prefill_sched._work_pending()
            or self.decode_sched._work_pending()
        )

    def step(self) -> None:
        """One pipeline iteration: advance the prefill tier, move every
        finished prefill across, advance the decode tier. In the real
        deployment the two tiers step concurrently on separate
        hardware; in-process they interleave, which preserves every
        ordering the concurrent version allows (the handoff is the only
        cross-tier edge and it is explicit)."""
        if self.prefill_sched._work_pending():
            t0 = time.perf_counter()
            self.prefill_sched.step()
            self.prefill_step_s += time.perf_counter() - t0
        self._drain_ready()
        if self.decode_sched._work_pending():
            t0 = time.perf_counter()
            self.decode_sched.step()
            self.decode_step_s += time.perf_counter() - t0

    def run(self, requests=None) -> List[Request]:
        for r in requests or ():
            self.submit(r)
        while self.work_pending():
            self.step()
        return self.finished

    @property
    def finished(self) -> List[Request]:
        """Terminal requests from BOTH tiers in finish order: a
        max_new_tokens=1 stream (or a cancel/timeout during prefill)
        retires on the prefill tier and never crosses."""
        done = list(self.prefill_sched.finished) + list(
            self.decode_sched.finished
        )
        return sorted(done, key=lambda r: r.finish_time)

    def request(self, rid: int) -> Optional[Request]:
        return self.prefill_sched._by_rid.get(
            rid
        ) or self.decode_sched._by_rid.get(rid)

    # -- the handoff ---------------------------------------------------------

    def _drain_ready(self) -> None:
        for req in self.prefill_sched.ready_for_handoff():
            handle = self.prefill_sched.stage_out(req.rid)
            if handle is None:
                # cache refusal (budget / freshly-cancelled) — the
                # request stays resident and retries next step
                continue
            record = self.prefill_cache.export_swap(handle)
            req.swap_handle = None
            self._install(req, record)

    def _install(self, req: Request, record: Dict[str, object]) -> None:
        new_handle = self.decode_cache.import_swap(record)
        # TTFT was stamped when the prefill tier emitted the first
        # token; decode-tier submit() re-stamps submit_time for its own
        # queue accounting, which must not erase the client's clock
        submit_time = req.submit_time
        if new_handle is None:
            # decode-tier swap budget refused the staged bytes:
            # recompute fallback — the decode tier re-prefills
            # prompt + first token on admission. Slower, never lost.
            self.handoff_fallbacks += 1
            req.log("handoff_fallback", "decode tier refused staged bytes")
        else:
            req.swap_handle = new_handle
            self.handoffs += 1
            self.handoff_bytes += int(record["bytes"])
            req.log("handoff", f"decode-tier handle {new_handle}")
        if not self.decode_sched.submit(req):
            return  # validation failure already finalized it there
        req.submit_time = submit_time
        tele = self.decode_sched.telemetry
        if tele is not None:
            reg = tele.registry
            reg.counter(
                "serve_handoff_total",
                help="prefill->decode KV handoffs completed",
            ).inc()
            if new_handle is None:
                reg.counter(
                    "serve_handoff_fallback_total",
                    help="handoffs degraded to recompute admission",
                ).inc()
            else:
                reg.counter(
                    "serve_handoff_bytes_total",
                    help="staged KV bytes moved across the tier boundary",
                ).inc(int(record["bytes"]))
