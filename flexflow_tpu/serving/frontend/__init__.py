"""Disaggregated serving front door.

Three composable layers over the single-process engine:

* `server` — asyncio submit/stream/cancel front end (`FrontDoor`,
  `serve_tcp`) over any scheduler-shaped backend;
* `router` — prefix-affinity placement across N in-process engine
  replicas (`ReplicaRouter`), with the replica-kill drain;
* `handoff` — prefill-tier → decode-tier KV movement over the swap
  staging path (`DisaggregatedPipeline`, `PrefillOnlyScheduler`).

They stack: a `FrontDoor` can front a bare scheduler, a router, or a
router whose replicas are disaggregated pipelines — each layer only
assumes the `submit`/`cancel`/`step`/`work_pending` duck type.
"""

from flexflow_tpu.serving.frontend.handoff import (
    DisaggregatedPipeline,
    PrefillOnlyScheduler,
)
from flexflow_tpu.serving.frontend.router import EngineReplica, ReplicaRouter
from flexflow_tpu.serving.frontend.server import (
    FrontDoor,
    StreamEvent,
    serve_tcp,
)

__all__ = [
    "DisaggregatedPipeline",
    "PrefillOnlyScheduler",
    "EngineReplica",
    "ReplicaRouter",
    "FrontDoor",
    "StreamEvent",
    "serve_tcp",
]
