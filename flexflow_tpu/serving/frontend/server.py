"""Streaming front-door server (asyncio submit/stream/cancel).

The serving stack below this file is a synchronous iteration loop; a
front door is the piece that turns it into a service: clients submit a
prompt, stream tokens back AS THEY COMMIT, and disconnect (or cancel)
at any moment without disturbing other streams. `FrontDoor` is that
adapter over any backend exposing the scheduler driving surface —
a single scheduler, a `ReplicaRouter`, or a `DisaggregatedPipeline`
(`submit` / `cancel` / `step` / `work_pending` duck type) — so every
lifecycle guarantee the lower layers prove (deadlines, deferred
cancel, terminal statuses, fault isolation) is what the wire sees.

Design rules:

* **One pump, many streams.** A single background task steps the
  backend and fans committed tokens out to per-request queues; client
  coroutines only await their own queue. The engine never runs
  per-client — exactly the continuous-batching posture.
* **Disconnect is cancel.** A client that stops consuming its stream
  (GeneratorExit / connection reset) cancels its request; the
  scheduler's deferred-cancel semantics retire it at the next safe
  boundary and its slot/pages free. No orphaned streams.
* **Terminal truth from the Request.** The stream's `done` event
  carries `Request.status` verbatim (finished / cancelled / timed_out
  / failed) — the audit trail clients see is the one the scheduler
  wrote.

The wire transport (`serve_tcp`) is deliberately minimal: newline-
delimited JSON over asyncio streams — an HTTP-ish request/streaming-
response shape without an HTTP dependency (the container rule: no new
deps). `{"op": "submit", "prompt": [...], ...}` answers
`{"event": "submitted", "rid": n}` then token events; `{"op":
"cancel", "rid": n}` cancels; closing the connection cancels every
stream it opened.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
from typing import AsyncIterator, Dict, List, Optional

from flexflow_tpu.serving.scheduler import (
    Request,
    TERMINAL_STATUSES,
)

__all__ = ["StreamEvent", "FrontDoor", "serve_tcp"]


@dataclasses.dataclass(frozen=True)
class StreamEvent:
    """One stream element: a committed token (`kind="token"`) or the
    terminal record (`kind="done"`, carrying the request's final
    status and error). A `done` with status "shed" is the overload
    refusal — the request never entered the engine (or the journal, so
    a retry is clean) and `retry_after_s` hints when to try again."""

    rid: int
    kind: str  # "token" | "done"
    token: Optional[int] = None
    status: Optional[str] = None
    error: Optional[str] = None
    retry_after_s: Optional[float] = None

    def to_wire(self) -> Dict[str, object]:
        out: Dict[str, object] = {"event": self.kind, "rid": self.rid}
        if self.kind == "token":
            out["token"] = self.token
        else:
            out["status"] = self.status
            if self.error:
                out["error"] = self.error
            if self.retry_after_s is not None:
                out["retry_after_s"] = self.retry_after_s
        return out


class FrontDoor:
    """Async submit/stream/cancel over a scheduler-shaped backend.

    Three durability/overload layers ride on the base adapter:

    * **idempotent resubmission** — a submit carrying a client
      `request_key` already seen (live, finished, or recovered from the
      journal) re-attaches to the EXISTING stream instead of opening a
      second one; a re-attached stream replays from token 0, so a
      reconnecting client sees the full committed history exactly once;
    * **crash-restart recovery** — constructed with a
      `journal.RecoveryState`, the door re-admits the journal's live
      set into the fresh backend with recompute cursors (or
      journal-referenced KV snapshots when `restore_decider` prices
      the copy under the recompute) and registers their streams, so
      deterministic greedy decode resumes every stream
      token-identically;
    * **overload protection** — `max_pending > 0` bounds the
      admission backlog; past it, a class whose pending count exceeds
      its weighted share (`backend.classes` weights; equal shares
      without them) is SHED: an immediate `done(status="shed")` event
      with a `retry_after_s` hint, never submitted to the engine and
      never journaled — so the retry is clean."""

    def __init__(
        self,
        backend,
        next_rid: int = 0,
        max_pending: int = 0,
        recovery=None,
        restore_decider=None,
    ):
        self.backend = backend
        self.max_pending = int(max_pending)
        self._next_rid = int(next_rid)
        self._requests: Dict[int, Request] = {}
        self._queues: Dict[int, asyncio.Queue] = {}
        self._published: Dict[int, int] = {}
        self._done: set = set()  # rids whose terminal event is queued
        self._pump_task: Optional[asyncio.Task] = None
        # idempotency: request_key -> rid for every stream this door
        # (or the journal it recovered from) knows
        self._keys: Dict[str, int] = {}
        self.shed_total: Dict[str, int] = {}
        self.recovered_requests = 0
        self.replayed_tokens = 0
        reg = self._registry()
        if reg is not None:
            from flexflow_tpu.telemetry.registry import (
                register_durability_metrics,
            )

            classes = tuple(getattr(backend, "classes", None) or ())
            register_durability_metrics(
                reg,
                classes=classes or ("default",),
                replicas=range(len(getattr(backend, "replicas", ()) or ())),
            )
        if recovery is not None:
            self._adopt(recovery, restore_decider)

    def _registry(self):
        tele = getattr(self.backend, "telemetry", None)
        if tele is not None and getattr(tele, "enabled", False):
            return tele.registry
        return None

    def _adopt(self, recovery, restore_decider=None) -> None:
        """Rebuild the live set from a journal RecoveryState: re-admit
        every recovered request into the fresh backend (recompute
        cursor, or a priced KV-snapshot restore) and register its
        stream with the published cursor at 0 — the committed run
        replays to the (re)connecting client, and everything past it
        comes from the resumed deterministic decode. Requests whose
        committed run already satisfied their stopping rule come back
        terminal without touching the engine (re-admitting them would
        emit a duplicate token)."""
        from flexflow_tpu.serving.journal import readmit

        resubmitted, completed = readmit(
            self.backend, recovery, decider=restore_decider
        )
        for req in resubmitted + completed:
            self._requests[req.rid] = req
            self._queues[req.rid] = asyncio.Queue()
            self._published[req.rid] = 0
            if req.request_key:
                self._keys[req.request_key] = req.rid
        # terminal verdicts stay dedupable: a retried submit with a
        # finished request's key replays its recorded stream
        for rid, term in recovery.terminals.items():
            key = term.get("key")
            if key and key not in self._keys:
                self._keys[key] = rid
                self._requests[rid] = Request(
                    rid=rid,
                    prompt=[0],
                    generated=list(term.get("tokens", ())),
                    status=term.get("status") or "failed",
                    error=term.get("error"),
                )
        self._next_rid = max(self._next_rid, recovery.next_rid)
        self.recovered_requests = len(resubmitted) + len(completed)
        self.replayed_tokens = recovery.replayed_tokens
        reg = self._registry()
        if reg is not None:
            reg.counter(
                "serve_recovery_total",
                help="journal crash-restart recoveries",
            ).inc()
            reg.counter(
                "serve_replayed_tokens_total",
                help="committed tokens re-adopted from the journal at "
                "recovery",
            ).inc(self.replayed_tokens)
        self._publish()  # recovered-terminal streams publish immediately

    # -- client surface ------------------------------------------------------

    def _pending_live(self) -> List[Request]:
        return [
            r
            for rid, r in self._requests.items()
            if rid not in self._done and r.status not in TERMINAL_STATUSES
        ]

    def _shed_check(self, priority_class: str) -> Optional[float]:
        """None = admit; a retry_after_s hint = shed. Sheds only when
        the TOTAL backlog is at the bound AND the class's own pending
        count is at its weighted share — so under overload a
        high-weight class keeps admitting while low-weight neighbors
        back off (the per-class degradation order, same posture as the
        scheduler's weighted-fair admission)."""
        if not self.max_pending:
            return None
        pending = self._pending_live()
        if len(pending) < self.max_pending:
            return None
        classes = getattr(self.backend, "classes", None)
        if classes and priority_class in classes:
            weights = {
                name: float(getattr(spec, "weight", 1.0))
                for name, spec in classes.items()
            }
            total = sum(weights.values()) or 1.0
            share = max(
                1,
                int(self.max_pending * weights[priority_class] / total),
            )
            mine = sum(
                1 for r in pending if (r.priority_class or "") == priority_class
            )
            if mine < share:
                return None
        excess = len(pending) - self.max_pending + 1
        return round(0.05 * excess, 4)

    async def submit(
        self,
        prompt: List[int],
        max_new_tokens: int = 16,
        eos_token: Optional[int] = None,
        deadline_s: Optional[float] = None,
        request_key: Optional[str] = None,
        priority_class: str = "",
        tenant: str = "",
        adapter_id: int = -1,
    ) -> int:
        """Submit one request; returns its rid (stream with
        `stream(rid)`). A validation rejection surfaces on the stream
        as an immediate failed `done` event, not an exception here —
        the wire protocol has one error path, not two. A duplicate
        `request_key` re-attaches to the existing stream (replayed from
        token 0); an overloaded door sheds with `done(status="shed")`
        instead of admitting."""
        if request_key:
            hit = self._keys.get(request_key)
            if hit is not None:
                req = self._requests.get(hit)
                if req is not None and hit not in self._queues:
                    # the original consumer detached (reconnect): replay
                    # the full committed stream on a fresh queue
                    self._queues[hit] = asyncio.Queue()
                    self._published[hit] = 0
                    self._done.discard(hit)
                    self._publish()
                return hit
        cls = priority_class or ""
        hint = self._shed_check(cls)
        if hint is not None:
            rid = self._next_rid
            self._next_rid += 1
            queue = asyncio.Queue()
            self._queues[rid] = queue
            self._published[rid] = 0
            queue.put_nowait(
                StreamEvent(
                    rid=rid,
                    kind="done",
                    status="shed",
                    error=(
                        f"admission backlog at bound "
                        f"({self.max_pending} pending)"
                    ),
                    retry_after_s=hint,
                )
            )
            self._done.add(rid)
            label = cls or "default"
            self.shed_total[label] = self.shed_total.get(label, 0) + 1
            reg = self._registry()
            if reg is not None:
                reg.counter(
                    "serve_shed_total",
                    help="admissions shed at the front door, by class",
                    labels={"class": label},
                ).inc()
            return rid
        rid = self._next_rid
        self._next_rid += 1
        req = Request(
            rid=rid,
            prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            eos_token=eos_token,
            deadline_s=deadline_s,
            request_key=request_key,
            priority_class=priority_class,
            tenant=tenant,
            adapter_id=adapter_id,
        )
        self._requests[rid] = req
        self._queues[rid] = asyncio.Queue()
        self._published[rid] = 0
        if request_key:
            self._keys[request_key] = rid
        self.backend.submit(req)
        self._ensure_pump()
        self._publish()  # a rejected submit is terminal already
        return rid

    async def stream(self, rid: int) -> AsyncIterator[StreamEvent]:
        """Yield this request's events until its terminal record. A
        consumer that stops early — client disconnect, GeneratorExit,
        task cancellation — CANCELS the request (deferred-cancel
        semantics below apply); a fully-consumed stream just cleans
        up."""
        queue = self._queues.get(rid)
        if queue is None:
            raise KeyError(f"unknown rid {rid}")
        try:
            while True:
                event = await queue.get()
                yield event
                if event.kind == "done":
                    return
        finally:
            self._detach(rid)

    async def cancel(self, rid: int) -> bool:
        return self.backend.cancel(rid)

    def request(self, rid: int) -> Optional[Request]:
        return self._requests.get(rid)

    async def drain(self) -> None:
        """Run the backend until every submitted stream is terminal
        (test/bench convenience — a live server just lets the pump
        idle)."""
        while self.backend.work_pending():
            self.backend.step()
            self._publish()
            await asyncio.sleep(0)
        self._publish()

    # -- engine pump ---------------------------------------------------------

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.ensure_future(self._pump())

    async def _pump(self) -> None:
        """THE engine driver: step, publish fresh commits, yield to the
        event loop (so client coroutines drain their queues between
        iterations), repeat until idle. Submissions restart it. A
        backend exception must not strand consumers on silent queues —
        every live stream gets a failed terminal event before the
        exception propagates into the task."""
        try:
            while self.backend.work_pending():
                self.backend.step()
                self._publish()
                await asyncio.sleep(0)
            self._publish()
        except Exception as exc:
            for rid, queue in list(self._queues.items()):
                if rid not in self._done:
                    queue.put_nowait(
                        StreamEvent(
                            rid=rid,
                            kind="done",
                            status="failed",
                            error=f"engine pump died: {exc!r}",
                        )
                    )
                    self._done.add(rid)
            raise

    def _publish(self) -> None:
        """Fan out every token committed since the last publish, then
        the terminal record. The scheduler appends to
        `Request.generated` as tokens commit; the cursor diff is the
        stream — no scheduler hook needed, and a burst (speculative
        accepts, chunk-final + decode) publishes as individual
        events."""
        for rid, queue in list(self._queues.items()):
            if rid in self._done:
                continue
            req = self._requests[rid]
            cursor = self._published[rid]
            fresh = req.generated[cursor:]
            for token in fresh:
                queue.put_nowait(
                    StreamEvent(rid=rid, kind="token", token=int(token))
                )
            self._published[rid] = cursor + len(fresh)
            if req.status in TERMINAL_STATUSES:
                # the queue stays registered (buffered events included)
                # until the consumer detaches — a client may open its
                # stream after a short request already finished
                queue.put_nowait(
                    StreamEvent(
                        rid=rid,
                        kind="done",
                        status=req.status,
                        error=req.error,
                    )
                )
                self._done.add(rid)

    def _detach(self, rid: int) -> None:
        """A consumer left. If the request is still live this is a
        disconnect: cancel it (the backend's deferred-cancel rules
        decide when it actually retires) and stop publishing to the
        dead queue."""
        req = self._requests.get(rid)
        if req is not None and req.status not in TERMINAL_STATUSES:
            self.backend.cancel(rid)
        self._queues.pop(rid, None)
        self._published.pop(rid, None)
        self._done.discard(rid)


# -- wire transport ----------------------------------------------------------


async def _handle_connection(
    door: FrontDoor, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
) -> None:
    """One client connection: newline-delimited JSON ops in, streamed
    events out. Submitted streams are served by concurrent writer
    tasks so several streams interleave on one connection; dropping
    the connection cancels every stream it still owns."""
    owned: List[int] = []
    stream_tasks: List[asyncio.Task] = []
    lock = asyncio.Lock()  # one writer at a time on the shared socket

    async def send(payload: Dict[str, object]) -> None:
        async with lock:
            writer.write(json.dumps(payload).encode() + b"\n")
            await writer.drain()

    async def run_stream(rid: int) -> None:
        async for event in door.stream(rid):
            await send(event.to_wire())

    try:
        while True:
            line = await reader.readline()
            if not line:
                break
            try:
                msg = json.loads(line)
                op = msg.get("op")
            except Exception:
                await send({"event": "error", "error": "bad json"})
                continue
            if op == "submit":
                rid = await door.submit(
                    prompt=list(msg.get("prompt", ())),
                    max_new_tokens=int(msg.get("max_new_tokens", 16)),
                    eos_token=msg.get("eos_token"),
                    deadline_s=msg.get("deadline_s"),
                )
                owned.append(rid)
                await send({"event": "submitted", "rid": rid})
                stream_tasks.append(asyncio.ensure_future(run_stream(rid)))
            elif op == "cancel":
                ok = await door.cancel(int(msg.get("rid", -1)))
                await send(
                    {"event": "cancelled", "rid": msg.get("rid"), "ok": ok}
                )
            else:
                await send({"event": "error", "error": f"unknown op {op!r}"})
    except (ConnectionResetError, asyncio.IncompleteReadError):
        pass
    finally:
        # connection gone: every stream it owns is a disconnect-cancel
        for task in stream_tasks:
            task.cancel()
        for rid in owned:
            req = door.request(rid)
            if req is not None and req.status not in TERMINAL_STATUSES:
                door.backend.cancel(rid)
        writer.close()


async def serve_tcp(
    backend, host: str = "127.0.0.1", port: int = 0
) -> asyncio.AbstractServer:
    """Bind the front door to a TCP port (port 0 picks a free one —
    read it back from `server.sockets[0].getsockname()`). The caller
    owns the returned server's lifetime."""
    door = FrontDoor(backend)

    async def handler(reader, writer):
        await _handle_connection(door, reader, writer)

    return await asyncio.start_server(handler, host, port)
