"""Streaming front-door server (asyncio submit/stream/cancel).

The serving stack below this file is a synchronous iteration loop; a
front door is the piece that turns it into a service: clients submit a
prompt, stream tokens back AS THEY COMMIT, and disconnect (or cancel)
at any moment without disturbing other streams. `FrontDoor` is that
adapter over any backend exposing the scheduler driving surface —
a single scheduler, a `ReplicaRouter`, or a `DisaggregatedPipeline`
(`submit` / `cancel` / `step` / `work_pending` duck type) — so every
lifecycle guarantee the lower layers prove (deadlines, deferred
cancel, terminal statuses, fault isolation) is what the wire sees.

Design rules:

* **One pump, many streams.** A single background task steps the
  backend and fans committed tokens out to per-request queues; client
  coroutines only await their own queue. The engine never runs
  per-client — exactly the continuous-batching posture.
* **Disconnect is cancel.** A client that stops consuming its stream
  (GeneratorExit / connection reset) cancels its request; the
  scheduler's deferred-cancel semantics retire it at the next safe
  boundary and its slot/pages free. No orphaned streams.
* **Terminal truth from the Request.** The stream's `done` event
  carries `Request.status` verbatim (finished / cancelled / timed_out
  / failed) — the audit trail clients see is the one the scheduler
  wrote.

The wire transport (`serve_tcp`) is deliberately minimal: newline-
delimited JSON over asyncio streams — an HTTP-ish request/streaming-
response shape without an HTTP dependency (the container rule: no new
deps). `{"op": "submit", "prompt": [...], ...}` answers
`{"event": "submitted", "rid": n}` then token events; `{"op":
"cancel", "rid": n}` cancels; closing the connection cancels every
stream it opened.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
from typing import AsyncIterator, Dict, List, Optional

from flexflow_tpu.serving.scheduler import (
    Request,
    TERMINAL_STATUSES,
)

__all__ = ["StreamEvent", "FrontDoor", "serve_tcp"]


@dataclasses.dataclass(frozen=True)
class StreamEvent:
    """One stream element: a committed token (`kind="token"`) or the
    terminal record (`kind="done"`, carrying the request's final
    status and error)."""

    rid: int
    kind: str  # "token" | "done"
    token: Optional[int] = None
    status: Optional[str] = None
    error: Optional[str] = None

    def to_wire(self) -> Dict[str, object]:
        out: Dict[str, object] = {"event": self.kind, "rid": self.rid}
        if self.kind == "token":
            out["token"] = self.token
        else:
            out["status"] = self.status
            if self.error:
                out["error"] = self.error
        return out


class FrontDoor:
    """Async submit/stream/cancel over a scheduler-shaped backend."""

    def __init__(self, backend, next_rid: int = 0):
        self.backend = backend
        self._next_rid = int(next_rid)
        self._requests: Dict[int, Request] = {}
        self._queues: Dict[int, asyncio.Queue] = {}
        self._published: Dict[int, int] = {}
        self._done: set = set()  # rids whose terminal event is queued
        self._pump_task: Optional[asyncio.Task] = None

    # -- client surface ------------------------------------------------------

    async def submit(
        self,
        prompt: List[int],
        max_new_tokens: int = 16,
        eos_token: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ) -> int:
        """Submit one request; returns its rid (stream with
        `stream(rid)`). A validation rejection surfaces on the stream
        as an immediate failed `done` event, not an exception here —
        the wire protocol has one error path, not two."""
        rid = self._next_rid
        self._next_rid += 1
        req = Request(
            rid=rid,
            prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            eos_token=eos_token,
            deadline_s=deadline_s,
        )
        self._requests[rid] = req
        self._queues[rid] = asyncio.Queue()
        self._published[rid] = 0
        self.backend.submit(req)
        self._ensure_pump()
        self._publish()  # a rejected submit is terminal already
        return rid

    async def stream(self, rid: int) -> AsyncIterator[StreamEvent]:
        """Yield this request's events until its terminal record. A
        consumer that stops early — client disconnect, GeneratorExit,
        task cancellation — CANCELS the request (deferred-cancel
        semantics below apply); a fully-consumed stream just cleans
        up."""
        queue = self._queues.get(rid)
        if queue is None:
            raise KeyError(f"unknown rid {rid}")
        try:
            while True:
                event = await queue.get()
                yield event
                if event.kind == "done":
                    return
        finally:
            self._detach(rid)

    async def cancel(self, rid: int) -> bool:
        return self.backend.cancel(rid)

    def request(self, rid: int) -> Optional[Request]:
        return self._requests.get(rid)

    async def drain(self) -> None:
        """Run the backend until every submitted stream is terminal
        (test/bench convenience — a live server just lets the pump
        idle)."""
        while self.backend.work_pending():
            self.backend.step()
            self._publish()
            await asyncio.sleep(0)
        self._publish()

    # -- engine pump ---------------------------------------------------------

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.ensure_future(self._pump())

    async def _pump(self) -> None:
        """THE engine driver: step, publish fresh commits, yield to the
        event loop (so client coroutines drain their queues between
        iterations), repeat until idle. Submissions restart it. A
        backend exception must not strand consumers on silent queues —
        every live stream gets a failed terminal event before the
        exception propagates into the task."""
        try:
            while self.backend.work_pending():
                self.backend.step()
                self._publish()
                await asyncio.sleep(0)
            self._publish()
        except Exception as exc:
            for rid, queue in list(self._queues.items()):
                if rid not in self._done:
                    queue.put_nowait(
                        StreamEvent(
                            rid=rid,
                            kind="done",
                            status="failed",
                            error=f"engine pump died: {exc!r}",
                        )
                    )
                    self._done.add(rid)
            raise

    def _publish(self) -> None:
        """Fan out every token committed since the last publish, then
        the terminal record. The scheduler appends to
        `Request.generated` as tokens commit; the cursor diff is the
        stream — no scheduler hook needed, and a burst (speculative
        accepts, chunk-final + decode) publishes as individual
        events."""
        for rid, queue in list(self._queues.items()):
            if rid in self._done:
                continue
            req = self._requests[rid]
            cursor = self._published[rid]
            fresh = req.generated[cursor:]
            for token in fresh:
                queue.put_nowait(
                    StreamEvent(rid=rid, kind="token", token=int(token))
                )
            self._published[rid] = cursor + len(fresh)
            if req.status in TERMINAL_STATUSES:
                # the queue stays registered (buffered events included)
                # until the consumer detaches — a client may open its
                # stream after a short request already finished
                queue.put_nowait(
                    StreamEvent(
                        rid=rid,
                        kind="done",
                        status=req.status,
                        error=req.error,
                    )
                )
                self._done.add(rid)

    def _detach(self, rid: int) -> None:
        """A consumer left. If the request is still live this is a
        disconnect: cancel it (the backend's deferred-cancel rules
        decide when it actually retires) and stop publishing to the
        dead queue."""
        req = self._requests.get(rid)
        if req is not None and req.status not in TERMINAL_STATUSES:
            self.backend.cancel(rid)
        self._queues.pop(rid, None)
        self._published.pop(rid, None)
        self._done.discard(rid)


# -- wire transport ----------------------------------------------------------


async def _handle_connection(
    door: FrontDoor, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
) -> None:
    """One client connection: newline-delimited JSON ops in, streamed
    events out. Submitted streams are served by concurrent writer
    tasks so several streams interleave on one connection; dropping
    the connection cancels every stream it still owns."""
    owned: List[int] = []
    stream_tasks: List[asyncio.Task] = []
    lock = asyncio.Lock()  # one writer at a time on the shared socket

    async def send(payload: Dict[str, object]) -> None:
        async with lock:
            writer.write(json.dumps(payload).encode() + b"\n")
            await writer.drain()

    async def run_stream(rid: int) -> None:
        async for event in door.stream(rid):
            await send(event.to_wire())

    try:
        while True:
            line = await reader.readline()
            if not line:
                break
            try:
                msg = json.loads(line)
                op = msg.get("op")
            except Exception:
                await send({"event": "error", "error": "bad json"})
                continue
            if op == "submit":
                rid = await door.submit(
                    prompt=list(msg.get("prompt", ())),
                    max_new_tokens=int(msg.get("max_new_tokens", 16)),
                    eos_token=msg.get("eos_token"),
                    deadline_s=msg.get("deadline_s"),
                )
                owned.append(rid)
                await send({"event": "submitted", "rid": rid})
                stream_tasks.append(asyncio.ensure_future(run_stream(rid)))
            elif op == "cancel":
                ok = await door.cancel(int(msg.get("rid", -1)))
                await send(
                    {"event": "cancelled", "rid": msg.get("rid"), "ok": ok}
                )
            else:
                await send({"event": "error", "error": f"unknown op {op!r}"})
    except (ConnectionResetError, asyncio.IncompleteReadError):
        pass
    finally:
        # connection gone: every stream it owns is a disconnect-cancel
        for task in stream_tasks:
            task.cancel()
        for rid in owned:
            req = door.request(rid)
            if req is not None and req.status not in TERMINAL_STATUSES:
                door.backend.cancel(rid)
        writer.close()


async def serve_tcp(
    backend, host: str = "127.0.0.1", port: int = 0
) -> asyncio.AbstractServer:
    """Bind the front door to a TCP port (port 0 picks a free one —
    read it back from `server.sockets[0].getsockname()`). The caller
    owns the returned server's lifetime."""
    door = FrontDoor(backend)

    async def handler(reader, writer):
        await _handle_connection(door, reader, writer)

    return await asyncio.start_server(handler, host, port)
