"""Prefix-affinity replica router (disaggregated serving front door).

One engine replica serves thousands of streams; millions of users need
N replicas behind a router. Placement is the whole game: two requests
sharing a prompt prefix served by the SAME replica share its published
prefix pages (one prefill, CoW decode divergence — kv_cache.py), while
the same pair split across replicas prefills twice. So the router
scores each alive replica by **prefix affinity** — how many published
pages its cache would map for this prompt (`match_prefix` over the
chained blake2b page keys, read-only) — and places the request on the
highest-affinity replica, breaking ties by **priced headroom**: the
replica with the most free capacity under its
`estimate_max_in_flight` ceiling (search/auto.py), so a hot prefix
cannot pile every tenant onto one replica past what its page pool
sustains. No-affinity requests degrade to pure least-loaded.

Replicas are in-process engine instances (the same simulated posture
as the pod placement's hosts in serving/distributed.py); each keeps
its own scheduler/cache/telemetry. Router-level telemetry mirrors the
pod's host labels with a `replica` label:

* `serve_router_requests_total{replica}` — placements;
* `serve_router_prefix_hits_total{replica}` — placements won by
  affinity (≥1 page matched);
* `serve_router_replica_down_total{replica}` — chaos kills;
* `serve_router_reroute_total{replica}` — evacuated streams re-placed
  ONTO that replica.

A killed replica (`kill_replica`, or a `FaultPlan.replica_down_iters`
schedule) evacuates every live request (`scheduler.evacuate`) and
re-routes the survivors' streams: RUNNING streams recompute their
committed history on the new replica (the dead pool is gone), queued
ones just requeue — zero lost requests, the generalized host_down
drain contract.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

from flexflow_tpu.serving.scheduler import (
    Request,
    RequestStatus,
    TERMINAL_STATUSES,
)

__all__ = ["EngineReplica", "ReplicaRouter"]


class EngineReplica:
    """One in-process engine replica: scheduler + engine + cache built
    from a compiled model, plus the router's view of it (alive flag,
    priced capacity ceiling, circuit-breaker state)."""

    def __init__(self, idx: int, model, serve, injector=None, journal=None):
        from flexflow_tpu.serving.api import build_scheduler

        self.idx = int(idx)
        self.scheduler, self.engine, self.cache = build_scheduler(
            model, serve, injector=injector, journal=journal
        )
        self.alive = True
        self.capacity = self._priced_capacity(model, serve)
        # circuit breaker (router-owned; see ReplicaRouter._probe):
        # closed -> open after `breaker_threshold` consecutive failed
        # health probes; open -> half_open after `breaker_cooldown`
        # router iterations; half_open -> closed on the first healthy
        # probe (or straight back to open on a failed one)
        self.breaker_state = "closed"
        self.breaker_failures = 0
        self.breaker_open_until = -1
        self._probe_faults = 0

    def _priced_capacity(self, model, serve) -> int:
        """The replica's in-flight ceiling from the capacity model —
        how many concurrent mean-shaped streams its KV bytes sustain —
        floored at 1 and defaulting to the slot count when the model
        carries no compiled graph to price."""
        try:
            from flexflow_tpu.search.auto import estimate_max_in_flight

            graph = getattr(model, "graph", None)
            if graph is None or not graph.nodes:
                return int(serve.max_seqs)
            spec = self.cache.spec
            cache_bytes = int(spec.total_bytes)
            est = estimate_max_in_flight(
                graph,
                cache_bytes,
                mean_prompt_len=max(1, spec.max_len // 2),
                mean_gen_len=max(1, spec.max_len // 4),
                max_len=spec.max_len,
                page_size=getattr(spec, "page_size", 0),
                admission=serve.admission,
                kv_dtype=getattr(spec, "kv_dtype", "fp32"),
            )
            return max(1, min(int(est), int(serve.max_seqs)))
        except Exception:
            return int(serve.max_seqs)

    @property
    def load(self) -> int:
        """Streams this replica currently owes work to."""
        s = self.scheduler
        return len(s.queue) + len(s.running)

    @property
    def headroom(self) -> int:
        return self.capacity - self.load


class ReplicaRouter:
    """Owns N `EngineReplica`s and a placement table. Presents the
    single-scheduler driving surface (`submit`/`cancel`/`step`/`run`/
    `work_pending`) so the front-door server drives a router exactly
    like one engine. `models` is one compiled model per replica —
    built identically (same seed) they are weight-identical, the
    multi-replica analog of the pod's per-host shards."""

    def __init__(
        self,
        models: Sequence,
        serve,
        injector=None,
        telemetry=None,
        journal=None,
        health_probe=None,
    ):
        if not models:
            raise ValueError("ReplicaRouter needs at least one replica")
        if telemetry is None:
            from flexflow_tpu.serving.api import build_telemetry

            telemetry = build_telemetry(serve)
        self.telemetry = telemetry
        if journal is None:
            from flexflow_tpu.serving.api import build_journal

            # ONE shared journal across replicas: the front door's rid
            # space is router-wide, so one durable record stream is the
            # recovery source of truth (per-replica journals would
            # interleave the same rids across files)
            journal = build_journal(serve, injector=injector,
                                    telemetry=telemetry)
        self.journal = journal
        self.replicas = [
            EngineReplica(i, m, serve, injector=injector, journal=journal)
            for i, m in enumerate(models)
        ]
        self.injector = injector
        self._owner: Dict[int, EngineReplica] = {}
        self.requests: Dict[int, Request] = {}
        self._iter = 0
        self.rerouted = 0
        # evacuation window (kill_replica): rid -> cancelled? while the
        # dead replica's requests are between schedulers; a cancel
        # landing here drops the rid from the re-submit batch
        self._evacuating: Dict[int, bool] = {}
        # requests finalized BY THE ROUTER (cancelled mid-evacuation —
        # they belong to no scheduler's `finished` list)
        self._orphans: List[Request] = []
        # per-replica circuit breaker: after `breaker_threshold`
        # consecutive failed health probes a replica stops taking
        # placements for `breaker_cooldown` router iterations, then
        # allows a half-open trial. The default probe is "no NEW
        # scheduler step faults since the last probe"; `health_probe`
        # overrides it with any `(replica) -> bool` (True = healthy).
        self.breaker_threshold = int(getattr(serve, "breaker_threshold", 0))
        self.breaker_cooldown = int(getattr(serve, "breaker_cooldown", 8))
        self.health_probe = health_probe
        self.breaker_opens = 0

    @property
    def classes(self):
        """The priority-class table (replicas are built identically) —
        the front door's shedding reads weights from it."""
        return self.replicas[0].scheduler.classes

    # -- placement -----------------------------------------------------------

    def route(self, request: Request) -> EngineReplica:
        """Pick the placement: max prefix affinity, then max headroom,
        then lowest index (deterministic). Raises RuntimeError with no
        alive replica — the router's analog of a full outage. Replicas
        whose circuit breaker is OPEN are excluded (half-open ones take
        the placement as their trial) — unless every alive replica is
        open, in which case the alive set routes anyway: availability
        over protection, the breaker must never manufacture an
        outage."""
        alive = [r for r in self.replicas if r.alive]
        if not alive:
            raise RuntimeError("no alive replica to route to")
        routable = [r for r in alive if r.breaker_state != "open"]
        alive = routable or alive
        affinity = {
            r.idx: (
                len(r.cache.match_prefix(request.prompt))
                if hasattr(r.cache, "match_prefix")
                else 0
            )
            for r in alive
        }
        best = max(affinity.values())
        pool = (
            [r for r in alive if affinity[r.idx] == best] if best else alive
        )
        target = max(pool, key=lambda r: (r.headroom, -r.idx))
        if self.telemetry is not None:
            reg = self.telemetry.registry
            labels = {"replica": str(target.idx)}
            reg.counter(
                "serve_router_requests_total",
                help="requests placed, by replica",
                labels=labels,
            ).inc()
            if best:
                reg.counter(
                    "serve_router_prefix_hits_total",
                    help="placements won by prefix affinity",
                    labels=labels,
                ).inc()
        return target

    # -- scheduler-compatible surface ----------------------------------------

    def submit(self, request: Request, strict: bool = True) -> bool:
        target = self.route(request)
        self.requests[request.rid] = request
        if not target.scheduler.submit(request, strict=strict):
            # strict=False validation reject: the request finalized on
            # `target` — record the owner so cancel/lookup see the
            # terminal record instead of an unknown rid
            self._owner[request.rid] = target
            return False
        self._owner[request.rid] = target
        return True

    def cancel(self, rid: int) -> bool:
        if rid in self._evacuating:
            # the rid is mid-evacuation — owned by no scheduler while
            # kill_replica re-places its batch. Mark it: the drain loop
            # drops it from the re-submit batch and finalizes it
            # CANCELLED at the router, so the cancel lands instead of
            # silently missing the ownership gap.
            self._evacuating[rid] = True
            return True
        owner = self._owner.get(rid)
        return owner is not None and owner.scheduler.cancel(rid)

    def request(self, rid: int) -> Optional[Request]:
        return self.requests.get(rid)

    def work_pending(self) -> bool:
        return any(
            r.alive and r.scheduler._work_pending() for r in self.replicas
        )

    def step(self) -> None:
        """One router iteration: fire any scheduled replica kill, then
        step every alive replica that has work (each replica is its own
        engine — in production they step concurrently; interleaving
        in-process preserves every ordering, as no state crosses
        replicas outside `kill_replica`)."""
        self._iter += 1
        if self.injector is not None:
            victim = self.injector.maybe_replica_down(self._iter)
            if victim is not None:
                self.kill_replica(victim)
        for rep in self.replicas:
            if rep.alive and rep.scheduler._work_pending():
                rep.scheduler.step()
        self._probe_breakers()

    def _probe_breakers(self) -> None:
        """One health probe per replica per router iteration, driving
        the breaker state machine. Default probe: a replica is healthy
        when its scheduler logged NO new step faults since the last
        probe — a replica failing whole steps (kernel faults, engine
        exceptions) trips open before it degrades every stream placed
        on it, while per-request faults (a NaN retiring one rid) don't
        count against it."""
        if not self.breaker_threshold:
            return
        for rep in self.replicas:
            if not rep.alive:
                continue
            if self.health_probe is not None:
                healthy = bool(self.health_probe(rep))
            else:
                faults = int(rep.scheduler.stats.step_faults)
                healthy = faults <= rep._probe_faults
                rep._probe_faults = faults
            if rep.breaker_state == "open":
                if self._iter >= rep.breaker_open_until:
                    rep.breaker_state = "half_open"
                continue
            if healthy:
                if rep.breaker_state == "half_open":
                    rep.breaker_state = "closed"
                rep.breaker_failures = 0
                continue
            rep.breaker_failures += 1
            if (
                rep.breaker_state == "half_open"
                or rep.breaker_failures >= self.breaker_threshold
            ):
                rep.breaker_state = "open"
                rep.breaker_open_until = self._iter + self.breaker_cooldown
                rep.breaker_failures = 0
                self.breaker_opens += 1
                if self.telemetry is not None:
                    self.telemetry.registry.counter(
                        "serve_breaker_open_total",
                        help="circuit-breaker open transitions, by replica",
                        labels={"replica": str(rep.idx)},
                    ).inc()

    def run(self, requests=None) -> List[Request]:
        for r in requests or ():
            self.submit(r)
        while self.work_pending():
            self.step()
        return self.finished

    @property
    def finished(self) -> List[Request]:
        done = [
            req for rep in self.replicas for req in rep.scheduler.finished
        ]
        done.extend(self._orphans)
        return sorted(done, key=lambda r: r.finish_time)

    # -- chaos: replica failure ----------------------------------------------

    def _finalize_orphan(self, req: Request, status: str) -> None:
        """Terminal transition for a request the router owns alone
        (cancelled mid-evacuation: no scheduler will ever see it
        again). Mirrors the scheduler's `_finalize` bookkeeping at the
        router grain — the request lands in `finished` with a terminal
        record, never silently vanishes."""
        if req.status in TERMINAL_STATUSES:
            return
        req.status = status
        req.finish_time = time.perf_counter()
        req.log(status, "cancelled during evacuation")
        self._owner.pop(req.rid, None)
        self._orphans.append(req)
        if self.telemetry is not None:
            self.telemetry.registry.counter(
                "serve_requests_total",
                help="terminal request transitions by status",
                labels={"status": status},
            ).inc()

    def kill_replica(self, idx: int) -> List[Request]:
        """A replica dies mid-stream: evacuate every live request and
        re-route each onto survivors, preserving the client's clock
        (submit_time — queue wait on the dead replica still counts
        against TTFT) and the committed stream (RUNNING evacuees
        recompute prompt + generated-so-far on arrival). Refuses to
        kill the last alive replica — zero survivors means the drain
        contract is unsatisfiable, same rule as the host injector."""
        rep = self.replicas[idx]
        alive = [r for r in self.replicas if r.alive]
        if not rep.alive or len(alive) <= 1:
            return []
        t0 = time.perf_counter()
        rep.alive = False
        moved = rep.scheduler.evacuate()
        # evacuation window: between evacuate() and each re-submit the
        # movers belong to NO scheduler — a cancel arriving now (client
        # disconnect racing the kill) must not fall into the ownership
        # gap. cancel() marks the rid here; the loop below drops marked
        # rids from the re-submit batch and finalizes them CANCELLED at
        # the router.
        self._evacuating = {req.rid: False for req in moved}
        for req in moved:
            if self._evacuating.get(req.rid):
                self._finalize_orphan(req, RequestStatus.CANCELLED)
                continue
            submit_time = req.submit_time
            target = self.route(req)
            # strict=False: a validation re-failure must finalize THIS
            # request on the target (per-request FAILED) — a strict
            # submit would raise and abort the drain loop, stranding
            # the rest of the batch ownerless
            if not target.scheduler.submit(req, strict=False):
                self._owner[req.rid] = target
                continue  # validation re-failure finalized it there
            req.submit_time = submit_time
            self._owner[req.rid] = target
            req.log("reroute", f"replica {idx} -> {target.idx}")
            self.rerouted += 1
            if self.telemetry is not None:
                self.telemetry.registry.counter(
                    "serve_router_reroute_total",
                    help="evacuated streams re-placed, by destination",
                    labels={"replica": str(target.idx)},
                ).inc()
        self._evacuating = {}
        if self.telemetry is not None:
            tele = self.telemetry
            tele.registry.counter(
                "serve_router_replica_down_total",
                help="replica kills the router drained",
                labels={"replica": str(idx)},
            ).inc()
            tele.tracer.complete(
                "replica_down drain",
                f"replica{idx}",
                t0,
                time.perf_counter(),
                tid=tele.tracer.replica_lane(idx),
                args={"replica": idx, "rerouted": len(moved)},
            )
        return moved
