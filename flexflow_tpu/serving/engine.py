"""Prefill + single-token decode over a compiled FFModel.

The engine re-executes the model's compiled PCG through
`Executor.forward_values` with ONE op hook: MULTIHEAD_ATTENTION. The hook
computes the exact training projections (ops/attention.mha_project_qkv /
mha_project_out — shared code, not a reimplementation) and swaps only the
attention core:

  * **prefill**: causal dense attention over the (padded) prompt, exactly
    the training forward — and captures each layer's K/V, scattered into
    the cache rows of the admitted slots. The last valid position's
    logits yield the first generated token, so admission itself produces
    a token (Orca's iteration-level view: a prefill is just a fat
    iteration).
  * **decode**: one query position per slot. The new K/V row is written
    at `lengths[slot]` via a per-row dynamic_update_slice, then
    `ops.attention.decode_attention` runs masked one-query attention
    against the cache (dense jnp path on CPU; `_decode_pallas_hook` is
    the TPU-kernel seam).

The engine serves BOTH cache layouts (kv_cache.KVCache slot-contiguous,
kv_cache.PagedKVCache block-paged) with the same hooks: the paged steps
route K/V rows through the slot's block table — prefill scatters each
captured row into `page * page_size + offset` of the flattened pool
(sentinel table entries produce out-of-bounds destinations that JAX
drops, so pad rows and unallocated positions never touch live pages),
decode writes the one new row the same way and attends via
`ops.attention.paged_decode_attention`. Block tables ride into the
jitted steps as an ordinary `[max_seqs, max_pages_per_seq]` int32
argument; the host-side allocator (PagedKVCache) mutates them between
steps, and `decode()` claims each sequence's next page BEFORE the step
when it is about to cross a page boundary (the admission reserve
guarantees that claim).

Both steps are jitted with static shapes: decode always runs at
`[max_seqs, 1]`, prefill at `[max_seqs, bucket]` per length bucket, so
compile count is 1 + #buckets for an entire serving session — paging
does not change the compile-count contract (tables are data, not
shape).

Greedy argmax is the default (temperature 0); temperature sampling
folds the serve seed into a per-step key so a fixed seed replays the
same stream.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import numpy as np

from flexflow_tpu.core.types import OperatorType


class GenerationEngine:
    """Step functions over (params, cache); all scheduling lives in
    serving.scheduler."""

    def __init__(self, model, cache, temperature: float = 0.0, seed: int = 0):
        import jax

        if model.executor is None:
            raise RuntimeError("compile() the model before serving")
        self.model = model
        self.executor = model.executor
        self.cache = cache
        self.temperature = float(temperature)
        self.seed = int(seed)
        graph = model.graph
        inputs = [
            graph.nodes[g]
            for g in self.executor.topo
            if graph.nodes[g].op_type == OperatorType.INPUT
            and not graph.nodes[g].inputs
        ]
        if len(inputs) != 1:
            raise ValueError(
                "serving needs a single token-id input tensor, model has "
                f"{len(inputs)} inputs"
            )
        self.input_name = inputs[0].name
        for g in cache.spec.layer_guids:
            node = graph.nodes[g]
            if not node.params.get("causal", False):
                raise ValueError(
                    f"attention node '{node.name}' is not causal; "
                    "autoregressive serving needs causal=True"
                )
            refs = {(r.guid, r.out_idx) for r in node.inputs}
            if len(refs) != 1:
                raise ValueError(
                    f"attention node '{node.name}' is cross-attention; "
                    "the KV-cache engine supports self-attention only"
                )
        self._logits_ref = self.executor.logits_ref
        # per-iteration dynamic seq truncation is a training knob; a stale
        # value would truncate serving activations mid-stack
        self.executor.set_seq_length(None)
        self.paged = bool(getattr(cache, "paged", False))
        self._decode_jit = jax.jit(
            self._decode_impl_paged if self.paged else self._decode_impl
        )
        # one jitted prefill per length bucket (jit caches by shape anyway;
        # the explicit dict makes the compile-count contract inspectable)
        self._prefill_cache: Dict[int, object] = {}

    # -- shared forward ------------------------------------------------------

    def _forward_logits(self, params, tokens, hook):
        values = self.executor.forward_values(
            params,
            {self.input_name: tokens},
            rng=None,
            train=False,
            op_hooks={OperatorType.MULTIHEAD_ATTENTION: hook},
            constrain=False,
        )
        return values[(self._logits_ref.guid, self._logits_ref.out_idx)]

    def _pick(self, logits, step):
        """logits [n, vocab] -> token ids [n]. Greedy at temperature 0,
        else categorical with the serve seed folded by the step counter
        (deterministic replay under a fixed seed)."""
        import jax
        import jax.numpy as jnp

        if self.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), step)
        return jax.random.categorical(
            key, logits.astype(jnp.float32) / self.temperature, axis=-1
        ).astype(jnp.int32)

    # -- prefill -------------------------------------------------------------

    def _prefill_impl(self, params, tokens, slot_ids, prompt_lens, ck, cv, step):
        """tokens [max_seqs, bucket] int32; slot_ids [max_seqs] (max_seqs
        = out-of-bounds sentinel for padding rows — JAX drops OOB scatter
        rows, so pad rows never touch live cache); prompt_lens [max_seqs]
        (>=1; pad rows use 1). Returns (ck', cv', next_tokens, last_logits)."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            scaled_dot_product_attention,
        )

        captured_k: Dict[int, object] = {}
        captured_v: Dict[int, object] = {}

        def hook(node, ins, ws, ctx):
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            captured_k[node.guid] = k
            captured_v[node.guid] = v
            attn = scaled_dot_product_attention(q, k, v, causal=True)
            return [
                mha_project_out(attn, ws, ctx, ins[0].dtype, use_bias=use_bias)
            ]

        logits = self._forward_logits(params, tokens, hook)
        bucket = tokens.shape[1]
        new_k, new_v = {}, {}
        for g in self.cache.spec.layer_guids:
            new_k[g] = ck[g].at[slot_ids, :bucket].set(
                captured_k[g].astype(ck[g].dtype)
            )
            new_v[g] = cv[g].at[slot_ids, :bucket].set(
                captured_v[g].astype(cv[g].dtype)
            )
        last = jnp.take_along_axis(
            logits, (prompt_lens - 1)[:, None, None], axis=1
        )[:, 0]
        return new_k, new_v, self._pick(last, step), last

    def _prefill_impl_paged(
        self, params, tokens, row_tables, prompt_lens, ck, cv, step
    ):
        """Paged twin of _prefill_impl. row_tables [max_seqs,
        ceil(bucket/page_size)] int32: the admitted slots' block-table
        prefixes (pad rows and unallocated entries carry the sentinel
        num_pages). Captured K/V rows scatter into the flattened pools at
        `page * page_size + offset`; sentinel pages put the destination
        out of bounds, which JAX drops — so bucket padding past a
        prompt's allocated pages writes nothing, where the slot layout
        writes (masked) garbage rows."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            scaled_dot_product_attention,
        )

        captured_k: Dict[int, object] = {}
        captured_v: Dict[int, object] = {}

        def hook(node, ins, ws, ctx):
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            captured_k[node.guid] = k
            captured_v[node.guid] = v
            attn = scaled_dot_product_attention(q, k, v, causal=True)
            return [
                mha_project_out(attn, ws, ctx, ins[0].dtype, use_bias=use_bias)
            ]

        logits = self._forward_logits(params, tokens, hook)
        spec = self.cache.spec
        ps = spec.page_size
        bucket = tokens.shape[1]
        pos = jnp.arange(bucket)
        # [max_seqs, bucket] flat pool destinations through the table
        dest = (row_tables[:, pos // ps] * ps + pos % ps).reshape(-1)
        new_k, new_v = {}, {}
        for g in spec.layer_guids:
            kp = ck[g].reshape(-1, spec.num_heads, spec.head_dim)
            vp = cv[g].reshape(-1, spec.num_heads, spec.head_dim)
            kr = captured_k[g].astype(ck[g].dtype).reshape(
                -1, spec.num_heads, spec.head_dim
            )
            vr = captured_v[g].astype(cv[g].dtype).reshape(
                -1, spec.num_heads, spec.head_dim
            )
            new_k[g] = kp.at[dest].set(kr).reshape(ck[g].shape)
            new_v[g] = vp.at[dest].set(vr).reshape(cv[g].shape)
        last = jnp.take_along_axis(
            logits, (prompt_lens - 1)[:, None, None], axis=1
        )[:, 0]
        return new_k, new_v, self._pick(last, step), last

    def prefill(
        self,
        params,
        prompts: Sequence[Sequence[int]],
        slots: Sequence[int],
        step: int = 0,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Run one admission batch; writes the cache in place (commit) and
        updates slot lengths. Returns (next_tokens [n], last_logits [n, V])
        for the n real rows."""
        import jax
        import jax.numpy as jnp

        spec = self.cache.spec
        n = len(prompts)
        if n == 0:
            raise ValueError("prefill needs at least one prompt")
        if n > spec.max_seqs:
            raise ValueError(f"{n} prompts > max_seqs {spec.max_seqs}")
        bucket = spec.bucket(max(len(p) for p in prompts))
        tokens = np.zeros((spec.max_seqs, bucket), dtype=np.int32)
        slot_ids = np.full(spec.max_seqs, spec.max_seqs, dtype=np.int32)
        plens = np.ones(spec.max_seqs, dtype=np.int32)
        for i, (p, s) in enumerate(zip(prompts, slots)):
            if not 0 < len(p) <= spec.max_len:
                raise ValueError(
                    f"prompt length {len(p)} outside (0, {spec.max_len}]"
                )
            tokens[i, : len(p)] = np.asarray(p, dtype=np.int32)
            slot_ids[i] = s
            plens[i] = len(p)
        fn = self._prefill_cache.get(bucket)
        if fn is None:
            fn = jax.jit(
                self._prefill_impl_paged if self.paged else self._prefill_impl
            )
            self._prefill_cache[bucket] = fn
        if self.paged:
            ps = spec.page_size
            width = -(-bucket // ps)
            row_tables = np.full(
                (spec.max_seqs, width), spec.num_pages, dtype=np.int32
            )
            for i, s in enumerate(slots):
                row_tables[i] = self.cache.block_tables[s, :width]
            route = jnp.asarray(row_tables)
        else:
            route = jnp.asarray(slot_ids)
        new_k, new_v, nxt, last = fn(
            params,
            jnp.asarray(tokens),
            route,
            jnp.asarray(plens),
            self.cache.k,
            self.cache.v,
            jnp.int32(step),
        )
        self.cache.commit(new_k, new_v)
        for p, s in zip(prompts, slots):
            self.cache.lengths[s] = len(p)
        return np.asarray(nxt[:n]), np.asarray(last[:n])

    # -- decode --------------------------------------------------------------

    def _decode_impl(self, params, tokens, lengths, active, ck, cv, step):
        """tokens [max_seqs, 1]; lengths [max_seqs] = cache position the
        incoming token is written at; active [max_seqs] bool masks cache
        writes for free slots."""
        import jax
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            decode_attention,
            mha_project_qkv,
            mha_project_out,
        )

        new_k = dict(ck)
        new_v = dict(cv)

        def row_update(cache, new):
            upd = jax.vmap(
                lambda c, nrow, pos: jax.lax.dynamic_update_slice(
                    c, nrow, (pos, 0, 0)
                )
            )(cache, new.astype(cache.dtype), lengths)
            return jnp.where(active[:, None, None, None], upd, cache)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            kc = row_update(ck[g], k)
            vc = row_update(cv[g], v)
            new_k[g] = kc
            new_v[g] = vc
            attn = decode_attention(q, kc, vc, lengths)
            return [
                mha_project_out(attn, ws, ctx, ins[0].dtype, use_bias=use_bias)
            ]

        logits = self._forward_logits(params, tokens, hook)[:, -1, :]
        return new_k, new_v, self._pick(logits, step), logits

    def _decode_impl_paged(
        self, params, tokens, lengths, active, tables, ck, cv, step
    ):
        """Paged twin of _decode_impl. tables [max_seqs,
        max_pages_per_seq] int32 block tables. The new K/V row scatters
        into `tables[slot, lengths // page_size] * page_size + lengths %
        page_size` of the flattened pool; inactive slots are routed to an
        out-of-bounds destination (dropped), replacing the contiguous
        path's where-mask."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            paged_decode_attention,
        )

        spec = self.cache.spec
        ps = spec.page_size
        oob = spec.num_pages * ps
        new_k = dict(ck)
        new_v = dict(cv)
        page = jnp.take_along_axis(tables, (lengths // ps)[:, None], axis=1)[
            :, 0
        ]
        dest = jnp.where(active, page * ps + lengths % ps, oob)

        def row_update(pool, new):
            flat = pool.reshape(-1, spec.num_heads, spec.head_dim)
            return flat.at[dest].set(new[:, 0].astype(pool.dtype)).reshape(
                pool.shape
            )

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            kc = row_update(ck[g], k)
            vc = row_update(cv[g], v)
            new_k[g] = kc
            new_v[g] = vc
            attn = paged_decode_attention(q, kc, vc, tables, lengths)
            return [
                mha_project_out(attn, ws, ctx, ins[0].dtype, use_bias=use_bias)
            ]

        logits = self._forward_logits(params, tokens, hook)[:, -1, :]
        return new_k, new_v, self._pick(logits, step), logits

    def decode(
        self,
        params,
        tokens: np.ndarray,
        active_mask: np.ndarray,
        step: int = 0,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One decode iteration over every slot. tokens [max_seqs] (last
        emitted token per slot; free slots can carry anything), active_mask
        [max_seqs] bool. Writes the cache, bumps active lengths, returns
        (next_tokens [max_seqs], logits [max_seqs, V])."""
        import jax.numpy as jnp

        args = []
        if self.paged:
            # claim the next page for any sequence about to cross a page
            # boundary BEFORE the jitted step (host-side allocator; the
            # admission reserve guarantees the claim succeeds)
            for slot in np.nonzero(np.asarray(active_mask))[0]:
                self.cache.ensure_position(
                    int(slot), int(self.cache.lengths[slot])
                )
            args = [jnp.asarray(self.cache.block_tables)]
        new_k, new_v, nxt, logits = self._decode_jit(
            params,
            jnp.asarray(tokens, dtype=jnp.int32)[:, None],
            jnp.asarray(self.cache.lengths),
            jnp.asarray(active_mask),
            *args,
            self.cache.k,
            self.cache.v,
            jnp.int32(step),
        )
        self.cache.commit(new_k, new_v)
        self.cache.lengths[np.asarray(active_mask)] += 1
        return np.asarray(nxt), np.asarray(logits)
