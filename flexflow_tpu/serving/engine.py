"""Prefill + single-token decode over a compiled FFModel.

The engine re-executes the model's compiled PCG through
`Executor.forward_values` with ONE op hook: MULTIHEAD_ATTENTION. The hook
computes the exact training projections (ops/attention.mha_project_qkv /
mha_project_out — shared code, not a reimplementation) and swaps only the
attention core:

  * **prefill**: causal dense attention over the (padded) prompt, exactly
    the training forward — and captures each layer's K/V, scattered into
    the cache rows of the admitted slots. The last valid position's
    logits yield the first generated token, so admission itself produces
    a token (Orca's iteration-level view: a prefill is just a fat
    iteration).
  * **decode**: one query position per slot. The new K/V row is written
    at `lengths[slot]` via a per-row dynamic_update_slice, then
    `ops.attention.decode_attention` runs masked one-query attention
    against the cache — the dense jnp path, or the Pallas flash-decode
    kernel (ops/pallas/decode_kernel.py) when the engine's
    `decode_kernel` mode selects it ("auto" on TPU, "pallas" forced,
    "dense" pinned).

The engine serves BOTH cache layouts (kv_cache.KVCache slot-contiguous,
kv_cache.PagedKVCache block-paged) with the same hooks: the paged steps
route K/V rows through the slot's block table — prefill scatters each
captured row into `page * page_size + offset` of the flattened pool
(sentinel table entries produce out-of-bounds destinations that JAX
drops, so pad rows and unallocated positions never touch live pages),
decode writes the one new row the same way and attends via
`ops.attention.paged_decode_attention`. Block tables ride into the
jitted steps as an ordinary `[max_seqs, max_pages_per_seq]` int32
argument; the host-side allocator (PagedKVCache) mutates them between
steps, and `decode()` claims each sequence's next page BEFORE the step
when it is about to cross a page boundary (the admission reserve
guarantees that claim).

A third step family serves speculative decoding (serving/spec.py):
**verify** scores w = k+1 token positions per slot (the last emitted
token plus k drafted tokens) through the KV cache in ONE prefill-shaped
call — K/V rows for all w positions are written (slot-scattered or
table-routed exactly like prefill), `ops.attention.verify_attention`
runs the staircase-masked w-query attention, and the caller accepts a
prefix of the drafts and commits/rolls back via
`cache.truncate(slot, new_len)` (verify itself never advances lengths).

A fourth family serves **chunked prefill** (Sarathi-style, the
scheduler's `--token-budget` path): a prompt chunk is exactly a wide
verify with nothing to accept — w prompt tokens per slot scatter into
the cache at the slot's prefill cursor and attend through the SAME
staircase-masked verify path (query_offset = tokens already
prefilled), so chunked prefill is token- and logit-identical to the
monolithic prefill above. Unlike verify, chunk rows ARE the prompt —
accepted by construction — so `prefill_chunk_dispatch` advances
`cache.lengths` at dispatch (no host data dependency between a
request's consecutive chunks: they pipeline under the async loop), and
only the FINAL chunk's sampled token means anything (the scheduler
discards the rest).

All steps are jitted with static shapes: decode always runs at
`[max_seqs, 1]`, prefill at `[max_seqs, bucket]` per length bucket,
verify at `[max_seqs, w]` per draft width, so compile count is
1 + #buckets + #draft-widths for an entire serving session — paging
does not change the compile-count contract (tables are data, not
shape).

Every decode/verify is split into **dispatch** (enqueue the jitted
step, commit the functional cache arrays, snapshot the mutable host
state onto an `InflightStep`) and **reconcile** (block on the device
futures one call — or, under the async scheduler, one iteration —
later). `decode()`/`verify()` are the synchronous wrappers; the async
loop holds the `InflightStep` across an iteration and chains the next
step's input tokens from its `device_next` so the inter-step data
dependency resolves entirely on device.

Greedy argmax is the default (temperature 0); temperature sampling
derives a PRNG key per (serve seed, slot, cache position), so a
request's sampled stream depends only on its slot and its own tokens —
reproducible under a fixed seed and independent of batch composition
(which requests happen to share the iteration), the property
rejection-sampling verify needs.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from flexflow_tpu.core.types import OperatorType


def snapshot(host_state: np.ndarray):
    """Immutable device-ready snapshot of mutable host scheduler state.

    ``jnp.asarray`` defers its host-buffer read behind the async
    dispatch queue, so handing it live state the scheduler mutates
    between steps (``cache.lengths``, allocator block tables) races
    the deferred read and corrupts the step under load — the PR 3 bug
    class. Every dispatch site routes mutable host arrays through this
    ONE helper; fxlint's dispatch-race rule
    (flexflow_tpu/analysis/dispatch_race.py) recognizes exactly this
    idiom (or an explicit ``.copy()``/``np.array``) as the blessed
    snapshot and flags everything else."""
    import jax.numpy as jnp

    return jnp.asarray(np.array(host_state))


class _JitCache:
    """Bounded keyed LRU over jitted step programs.

    The verify, chunked-prefill, and multi-step decode families each
    jit one program per shape key (draft width, compact batch, K
    bucket); widths churn with re-tuning and per-request budget caps,
    and an unbounded dict would keep every key's device executable
    alive for the engine's whole life. One helper owns the discipline
    all three caches previously hand-rolled: a hit refreshes recency, a
    miss calls `trace(key)` and evicts the least-recently-used entry
    past `max_entries`. Iteration/containment/len mirror the dict so
    the compile population stays inspectable (the
    `verify_cache_entries`-style gauges)."""

    def __init__(self, trace, max_entries: int = 8):
        self._trace = trace
        self.max_entries = max_entries
        self._entries: "OrderedDict[object, object]" = OrderedDict()

    def get(self, key):
        fn = self._entries.get(key)
        if fn is None:
            fn = self._trace(key)
            self._entries[key] = fn
            while len(self._entries) > max(1, int(self.max_entries)):
                self._entries.popitem(last=False)
        else:
            self._entries.move_to_end(key)
        return fn

    def clear(self) -> None:
        self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)

    def __iter__(self):
        return iter(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries


@dataclasses.dataclass
class InflightStep:
    """One dispatched-but-not-reconciled engine step.

    The async double-buffered loop splits every decode/verify into a
    *dispatch* (enqueue the jitted step on the device queue, commit the
    functional cache arrays, return immediately) and a *reconcile*
    (block on the device outputs, emit tokens, retire requests) that
    runs one iteration later. This record is the only thing allowed to
    cross that gap: it carries an immutable HOST SNAPSHOT of everything
    the reconcile needs — the pre-step lengths, the active mask, the
    participating Request identities — so reconcile logic never reads
    live scheduler/cache state the host has since mutated (fxlint FX103
    enforces exactly that discipline), plus the device futures the
    reconcile blocks on.
    """

    kind: str  # "decode" | "verify" | "chunk"
    dispatch_t: float  # wall clock at dispatch (overlap accounting)
    active: np.ndarray  # bool [max_seqs] — slots the step ran for
    lengths: np.ndarray  # int32 [max_seqs] — cache lengths BEFORE the step
    host_tokens: Optional[np.ndarray] = None  # decode: host-view input tokens
    draft_lens: Optional[np.ndarray] = None  # verify/chunk: rows per slot
    # chunked prefill: slot -> (start, size, final) — the prefill-cursor
    # snapshot the commit phase reads INSTEAD of live Request attrs
    # (fxlint FX105 holds reconcile code to this record)
    chunks: Optional[Dict[int, tuple]] = None
    # chunked prefill: slot -> prompt tokens at dispatch — what the
    # commit phase hands register_prefix (same FX105 discipline: the
    # prompt is immutable per request, but the SLOT can turn over while
    # the step is in flight, so even this read rides the snapshot)
    chunk_seqs: Optional[Dict[int, list]] = None
    # device futures (JAX arrays still computing behind the queue)
    device_next: object = None  # decode: sampled tokens [max_seqs]
    device_logits: object = None  # [max_seqs, V] or [max_seqs, w, V]
    # device-resident multi-step decode (kind "multistep"): the fused
    # window's per-step device outputs — sampled tokens / logits /
    # executed-step masks are [K, max_seqs] stacks, device_lengths the
    # end-of-window cache lengths, k_steps the window depth actually
    # dispatched, step_limits the per-slot fused-step caps the commit
    # rolls truncation against. Reconcile code consumes THESE, never a
    # live scheduler copy of the window bookkeeping (fxlint FX109).
    device_tokens: object = None  # [K, max_seqs] sampled token per step
    device_mask: object = None  # [K, max_seqs] bool — step ran for slot
    device_lengths: object = None  # [max_seqs] end-of-window lengths
    k_steps: int = 1  # fused steps dispatched in this window
    step_limits: Optional[np.ndarray] = None  # int32 [max_seqs] per-slot cap
    # scheduler-side snapshot: slot -> Request identity at dispatch,
    # verify draft plan, and the dispatching iteration (fault keying)
    participants: Dict[int, object] = dataclasses.field(default_factory=dict)
    plan: Optional[Dict[int, list]] = None
    iteration: int = -1
    # tree verify (kind "verify_tree"): the per-row parent table the
    # step was dispatched with (host copy of the device operand) and
    # slot -> DraftTree plan. Both are SNAPSHOTS taken at dispatch —
    # the reconcile walks the tree and compacts the cache against
    # THESE, never a live proposer/scheduler tree the host has since
    # rebuilt (fxlint FX103/FX109 hold tree-reconcile code to the step
    # record exactly like the multistep window state).
    tree_parents: Optional[np.ndarray] = None  # int32 [max_seqs, w]
    tree_plan: Optional[Dict[int, object]] = None  # slot -> DraftTree
    # dispatch sequence number (scheduler._note_dispatch): the trace
    # layer's step index — device in-flight windows alternate lanes by
    # its parity so overlapping async windows still render
    seq: int = -1


class GenerationEngine:
    """Step functions over (params, cache); all scheduling lives in
    serving.scheduler."""

    def __init__(
        self,
        model,
        cache,
        temperature: float = 0.0,
        seed: int = 0,
        decode_kernel: str = "auto",
        injector=None,
        telemetry=None,
        adapters=None,
    ):
        import jax

        from flexflow_tpu.ops.pallas.decode_kernel import MODES

        if model.executor is None:
            raise RuntimeError("compile() the model before serving")
        if decode_kernel not in MODES:
            raise ValueError(
                f"decode_kernel must be one of {MODES}, got {decode_kernel!r}"
            )
        self.model = model
        self.executor = model.executor
        self.cache = cache
        self.temperature = float(temperature)
        self.seed = int(seed)
        # resilience: a faults.FaultInjector seam before kernel-path
        # dispatches, plus the fallback ledger the chaos bench reads
        self.injector = injector
        self.kernel_fallbacks = 0
        self.kernel_fallback_error: str = ""
        # telemetry (flexflow_tpu.telemetry.Telemetry): None when
        # disabled — engine instrument points (prefill span, kernel
        # fallback) each cost one predicate on the disabled path
        self.telemetry = (
            telemetry
            if telemetry is not None and getattr(telemetry, "enabled", False)
            else None
        )
        # how the decode/verify attention core runs (threaded into every
        # ops.attention call below): "auto" = Pallas decode kernel on TPU
        # when the geometry supports() it, "pallas" = force the kernel
        # (interpret mode off-TPU), "dense" = always the jnp paths. A
        # trace-time constant: each engine owns its jitted steps, so two
        # engines with different modes coexist in one process.
        self.decode_kernel = decode_kernel
        # multi-tenant LoRA (serving.tenancy.adapters.AdapterPool):
        # None keeps every traced step byte-for-byte the base engine —
        # the adapter argument is simply never passed, so no select or
        # gather enters the HLO. With a pool, every step carries a
        # (tables, has, pools) pytree snapshotted at dispatch; rows
        # whose slot serves the base model (adapter_id -1) ride a
        # jnp.where select that returns the unmodified projection
        # elements, which is what the bit-identity gates pin down.
        self.adapters = adapters
        graph = model.graph
        inputs = [
            graph.nodes[g]
            for g in self.executor.topo
            if graph.nodes[g].op_type == OperatorType.INPUT
            and not graph.nodes[g].inputs
        ]
        if len(inputs) != 1:
            raise ValueError(
                "serving needs a single token-id input tensor, model has "
                f"{len(inputs)} inputs"
            )
        self.input_name = inputs[0].name
        for g in cache.spec.layer_guids:
            node = graph.nodes[g]
            if not node.params.get("causal", False):
                raise ValueError(
                    f"attention node '{node.name}' is not causal; "
                    "autoregressive serving needs causal=True"
                )
            refs = {(r.guid, r.out_idx) for r in node.inputs}
            if len(refs) != 1:
                raise ValueError(
                    f"attention node '{node.name}' is cross-attention; "
                    "the KV-cache engine supports self-attention only"
                )
        self._logits_ref = self.executor.logits_ref
        # per-iteration dynamic seq truncation is a training knob; a stale
        # value would truncate serving activations mid-stack
        self.executor.set_seq_length(None)
        self.paged = bool(getattr(cache, "paged", False))
        self._decode_jit = jax.jit(
            self._decode_impl_paged if self.paged else self._decode_impl
        )
        # one jitted prefill per length bucket / one jitted verify per
        # draft width (jit caches by shape anyway; the explicit caches
        # make the compile-count contract inspectable). The verify,
        # chunk, and multi-step caches are bounded LRUs (_JitCache):
        # draft widths vary with optimize_spec_k re-tuning and
        # per-request budget caps, chunk widths with the token budget,
        # K buckets with the scheduler's fusing horizon — unbounded
        # dicts kept every key's jitted program (and its device
        # executable) alive for the engine's whole life.
        self._prefill_cache: Dict[int, object] = {}
        self._verify_cache = _JitCache(
            lambda w: jax.jit(
                self._verify_impl_paged if self.paged else self._verify_impl
            )
        )
        # chunked-prefill programs, one per compact batch shape (B, w) —
        # the scheduler pads widths to multiples of chunk_size, so the
        # population is budget/chunk_size distinct widths at most
        self._chunk_cache = _JitCache(
            lambda key: jax.jit(
                self._chunk_impl_paged if self.paged else self._chunk_impl
            )
        )
        # multi-step decode scan programs, one per (B, K-bucket, layout)
        # key — K buckets are powers of two, so the population is
        # log2(max_fused_steps) at most
        self._multistep_cache = _JitCache(
            lambda key: jax.jit(
                functools.partial(
                    self._decode_multi_impl_paged
                    if self.paged
                    else self._decode_multi_impl,
                    key[1],
                )
            )
        )
        # tree-verify programs, one per row width w = 1 + tree nodes.
        # Kept apart from `_verify_cache` because the tree impl carries
        # an extra parent-table operand; the scheduler pins a single
        # node budget, so the steady-state population is one entry
        self._tree_cache = _JitCache(
            lambda w: jax.jit(
                self._verify_tree_impl_paged
                if self.paged
                else self._verify_tree_impl
            )
        )

    @property
    def verify_cache_entries(self) -> int:
        """Live jitted verify programs (LRU-bounded by
        `verify_cache_max`) — surfaced as a SchedulerStats field so a
        width-churning workload's compile footprint is observable."""
        return len(self._verify_cache)

    @property
    def tree_cache_entries(self) -> int:
        """Live jitted tree-verify programs — the `verify_cache_entries`
        twin for the tree-width family."""
        return len(self._tree_cache)

    @property
    def multistep_cache_entries(self) -> int:
        """Live jitted multi-step scan programs (LRU-bounded), the
        `verify_cache_entries` twin for the fused-decode family."""
        return len(self._multistep_cache)

    @property
    def verify_cache_max(self) -> int:
        return self._verify_cache.max_entries

    @verify_cache_max.setter
    def verify_cache_max(self, n: int) -> None:
        self._verify_cache.max_entries = int(n)

    @property
    def chunk_cache_max(self) -> int:
        return self._chunk_cache.max_entries

    @chunk_cache_max.setter
    def chunk_cache_max(self, n: int) -> None:
        self._chunk_cache.max_entries = int(n)

    def _verify_fn(self, w: int):
        """The jitted verify program for draft width `w` (LRU-managed
        by the shared _JitCache)."""
        return self._verify_cache.get(w)

    def _tree_fn(self, w: int):
        """The jitted tree-verify program for row width `w` (root + tree
        nodes) — same keyed-LRU discipline as `_verify_fn`."""
        return self._tree_cache.get(w)

    def _chunk_fn(self, key):
        """The jitted chunked-prefill program for compact batch shape
        `key` = (B, w) — same keyed-LRU discipline as `_verify_fn`."""
        return self._chunk_cache.get(key)

    # -- adapter gather args (multi-LoRA) ------------------------------------

    def _adapter_slot_args(self):
        """() without a pool, else a 1-tuple holding the slot-indexed
        (tables, has, pools) adapter gather for the decode/verify/
        multistep/chunk steps. The host tables snapshot at dispatch
        (FX103: the step rides its own copy — scheduler attach/detach
        between iterations never mutates an in-flight step's view); the
        device pools are immutable arrays, rebound wholesale by loads,
        so the step keeps whatever pool generation it captured."""
        if self.adapters is None:
            return ()
        tbl, has = self.adapters.slot_tables()
        return (
            (snapshot(tbl), snapshot(has), self.adapters.device_pools),
        )

    def _adapter_row_args(self, slots):
        """Prefill twin of `_adapter_slot_args`: batch row i serves slot
        `slots[i]`, pad rows gather the zero sentinel."""
        if self.adapters is None:
            return ()
        tbl, has = self.adapters.row_tables(slots, self.cache.spec.max_seqs)
        return (
            (snapshot(tbl), snapshot(has), self.adapters.device_pools),
        )

    # -- kernel-failure fallback ---------------------------------------------

    def _dispatch(self, site: str, call):
        """Run one jitted decode/verify step. On the dense paths this is
        just `call()`; on a Pallas-kernel path the outputs are forced
        first (surfacing async compile/runtime errors BEFORE the cache
        commits them) and ANY failure — injected through the fault seam
        or real — permanently falls the engine back to the dense paths
        and retries the step once. Serving survives a broken kernel at
        the cost of the dense path's speed; the fallback is recorded in
        `kernel_fallbacks` / `kernel_fallback_error`."""
        import jax

        if self.decode_kernel == "dense":
            return call()
        try:
            if self.injector is not None:
                self.injector.maybe_kernel_fault(site)
            out = call()
            jax.block_until_ready(out)
            return out
        except Exception as e:
            self._fall_back_to_dense(e)
            return call()

    def _fall_back_to_dense(self, error) -> None:
        import jax

        self.kernel_fallbacks += 1
        self.kernel_fallback_error = repr(error)
        if self.telemetry is not None:
            self.telemetry.registry.counter(
                "serve_kernel_fallbacks_total",
                help="Pallas dispatch failures answered by permanent "
                "dense fallback",
            ).inc()
            self.telemetry.tracer.instant(
                "kernel_fallback", "engine", args={"error": repr(error)}
            )
        self.decode_kernel = "dense"
        # the jitted steps baked the failed mode in at trace time;
        # rebuild them so the retry traces the dense attention cores
        # (prefill never touches the kernel, so its cache stands)
        self._decode_jit = jax.jit(
            self._decode_impl_paged if self.paged else self._decode_impl
        )
        self._verify_cache.clear()
        self._chunk_cache.clear()
        self._multistep_cache.clear()
        self._tree_cache.clear()

    # -- shared forward ------------------------------------------------------

    def _forward_logits(self, params, tokens, hook):
        values = self.executor.forward_values(
            params,
            {self.input_name: tokens},
            rng=None,
            train=False,
            op_hooks={OperatorType.MULTIHEAD_ATTENTION: hook},
            constrain=False,
        )
        return values[(self._logits_ref.guid, self._logits_ref.out_idx)]

    def _pick(self, logits, slots, positions):
        """logits [n, vocab] -> token ids [n]. Greedy at temperature 0,
        else categorical under a PER-ROW key derived as
        fold_in(fold_in(PRNGKey(seed), slot), position) — `positions` is
        the cache position each sampled token will occupy. The draw for
        a slot therefore depends only on (seed, slot, position), never
        on the global step counter or on which other requests share the
        batch: a fixed seed replays the same stream even when admission
        timing shifts, the reproducibility rejection-sampling verify
        builds on."""
        import jax
        import jax.numpy as jnp

        if self.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        base = jax.random.PRNGKey(self.seed)
        temp = self.temperature

        def one(slot, pos, row):
            key = jax.random.fold_in(jax.random.fold_in(base, slot), pos)
            return jax.random.categorical(
                key, row.astype(jnp.float32) / temp
            )

        return jax.vmap(one)(slots, positions, logits).astype(jnp.int32)

    # -- int8 pool writes ----------------------------------------------------

    def _quant_scatter(self, pool, scale, rows, dest):
        """Quantize `rows` [N, heads, head_dim] into the int8 `pool` at
        flat row indices `dest` [N] (out-of-bounds rows drop, exactly
        like the fp32 scatter). A page's fp32 scale is claimed exactly
        once, from the abs-max of its FIRST row (position page_size·p):
        sequential streaming guarantees a fresh page's first write
        contains that row, and the first row's content is a pure
        function of the token history — so the scale (and therefore the
        page's bytes) comes out identical no matter how the writes were
        batched into chunks, which request recomputed them, or whether
        the page arrived via COW (the copied scale equals what a fresh
        recompute would derive). Pages whose scale is already set (> 0)
        keep it; rows beyond ±127·scale clip — the documented int8
        tolerance. Returns (pool', scale', dequantized_rows): the round
        trip through int8, for callers (prefill) whose attention must
        read exactly what a later pool reader will see."""
        import jax.numpy as jnp

        spec = self.cache.spec
        page = dest // spec.page_size  # OOB dest -> OOB page, dropped
        f32 = rows.astype(jnp.float32)
        amax = jnp.max(jnp.abs(f32), axis=-1)  # [N, heads]
        first = (dest % spec.page_size == 0)[:, None]  # page-initial rows
        cand = jnp.zeros_like(scale).at[page].max(
            jnp.where(first, amax / 127.0, 0.0), mode="drop"
        )
        # a batch that writes a page's first row (RE)DERIVES its scale —
        # never trust a stored value then: freed pages keep stale scales
        # on device, and a reallocated page must quantize from its new
        # content, not its previous tenant's
        claimed = jnp.zeros_like(scale).at[page].max(
            jnp.where(first, 1.0, 0.0), mode="drop"
        )
        new_scale = jnp.where(claimed > 0.0, cand, scale)
        s = new_scale[jnp.clip(page, 0, spec.num_pages - 1)]  # [N, heads]
        safe = jnp.where(s > 0.0, s, 1.0)
        q = jnp.clip(jnp.round(f32 / safe[:, :, None]), -127, 127).astype(
            pool.dtype
        )
        flat = pool.reshape(-1, spec.num_heads, spec.head_dim)
        deq = q.astype(jnp.float32) * jnp.where(
            s > 0.0, s, 0.0
        )[:, :, None]
        return (
            flat.at[dest].set(q, mode="drop").reshape(pool.shape),
            new_scale,
            deq,
        )

    # -- prefill -------------------------------------------------------------

    def _prefill_impl(
        self, params, tokens, slot_ids, prompt_lens, ck, cv, ad=None
    ):
        """tokens [max_seqs, bucket] int32; slot_ids [max_seqs] (max_seqs
        = out-of-bounds sentinel for padding rows — JAX drops OOB scatter
        rows, so pad rows never touch live cache); prompt_lens [max_seqs]
        (>=1; pad rows use 1). `ad` is the optional batch-row-aligned
        adapter gather (tables, has, pools) — None leaves the traced HLO
        exactly the base engine's. Returns (ck', cv', next_tokens,
        last_logits)."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            scaled_dot_product_attention,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            apply_adapter_out,
            apply_adapter_qkv,
        )

        captured_k: Dict[int, object] = {}
        captured_v: Dict[int, object] = {}

        def hook(node, ins, ws, ctx):
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, node.guid)
            captured_k[node.guid] = k
            captured_v[node.guid] = v
            attn = scaled_dot_product_attention(q, k, v, causal=True)
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, node.guid)]

        logits = self._forward_logits(params, tokens, hook)
        bucket = tokens.shape[1]
        new_k, new_v = {}, {}
        for g in self.cache.spec.layer_guids:
            new_k[g] = ck[g].at[slot_ids, :bucket].set(
                captured_k[g].astype(ck[g].dtype)
            )
            new_v[g] = cv[g].at[slot_ids, :bucket].set(
                captured_v[g].astype(cv[g].dtype)
            )
        last = jnp.take_along_axis(
            logits, (prompt_lens - 1)[:, None, None], axis=1
        )[:, 0]
        # the sampled token will be written at cache position prompt_lens
        return new_k, new_v, self._pick(last, slot_ids, prompt_lens), last

    def _prefill_impl_paged(
        self, params, tokens, slot_ids, row_tables, prompt_lens, ck, cv,
        cks, cvs, ad=None,
    ):
        """Paged twin of _prefill_impl. row_tables [max_seqs,
        ceil(bucket/page_size)] int32: the admitted slots' block-table
        prefixes (pad rows and unallocated entries carry the sentinel
        num_pages). slot_ids only seed the per-slot sampling keys here —
        routing is entirely through the tables. Captured K/V rows scatter
        into the flattened pools at `page * page_size + offset`; sentinel
        pages put the destination out of bounds, which JAX drops — so
        bucket padding past a prompt's allocated pages writes nothing,
        where the slot layout writes (masked) garbage rows."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            scaled_dot_product_attention,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            apply_adapter_out,
            apply_adapter_qkv,
        )

        spec = self.cache.spec
        ps = spec.page_size
        bucket = tokens.shape[1]
        pos = jnp.arange(bucket)
        # [max_seqs, bucket] flat pool destinations through the table
        dest = (row_tables[:, pos // ps] * ps + pos % ps).reshape(-1)
        quant = getattr(self.cache, "quantized", False)
        new_k, new_v = {}, {}
        new_ks, new_vs = dict(cks), dict(cvs)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            if quant:
                # scatter inside the hook and attend over the int8
                # ROUND TRIP: a prefix-shared admission later reads
                # these rows dequantized from the pool, so the logits
                # computed here must come from the same lossy values or
                # shared and unshared streams would diverge
                kr = k.reshape(-1, spec.num_heads, spec.head_dim)
                vr = v.reshape(-1, spec.num_heads, spec.head_dim)
                new_k[g], new_ks[g], k_deq = self._quant_scatter(
                    ck[g], cks[g], kr, dest
                )
                new_v[g], new_vs[g], v_deq = self._quant_scatter(
                    cv[g], cvs[g], vr, dest
                )
                k = k_deq.reshape(k.shape).astype(k.dtype)
                v = v_deq.reshape(v.shape).astype(v.dtype)
            else:
                kp = ck[g].reshape(-1, spec.num_heads, spec.head_dim)
                vp = cv[g].reshape(-1, spec.num_heads, spec.head_dim)
                kr = k.reshape(-1, spec.num_heads, spec.head_dim)
                vr = v.reshape(-1, spec.num_heads, spec.head_dim)
                new_k[g] = kp.at[dest].set(kr.astype(ck[g].dtype)).reshape(
                    ck[g].shape
                )
                new_v[g] = vp.at[dest].set(vr.astype(cv[g].dtype)).reshape(
                    cv[g].shape
                )
            attn = scaled_dot_product_attention(q, k, v, causal=True)
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)
        last = jnp.take_along_axis(
            logits, (prompt_lens - 1)[:, None, None], axis=1
        )[:, 0]
        return (
            new_k,
            new_v,
            new_ks,
            new_vs,
            self._pick(last, slot_ids, prompt_lens),
            last,
        )

    def prefill(
        self,
        params,
        prompts: Sequence[Sequence[int]],
        slots: Sequence[int],
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Run one admission batch; writes the cache in place (commit) and
        updates slot lengths. Returns (next_tokens [n], last_logits [n, V])
        for the n real rows."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        spec = self.cache.spec
        n = len(prompts)
        if n == 0:
            raise ValueError("prefill needs at least one prompt")
        if n > spec.max_seqs:
            raise ValueError(f"{n} prompts > max_seqs {spec.max_seqs}")
        bucket = spec.bucket(max(len(p) for p in prompts))
        tokens = np.zeros((spec.max_seqs, bucket), dtype=np.int32)
        slot_ids = np.full(spec.max_seqs, spec.max_seqs, dtype=np.int32)
        plens = np.ones(spec.max_seqs, dtype=np.int32)
        for i, (p, s) in enumerate(zip(prompts, slots)):
            if not 0 < len(p) <= spec.max_len:
                raise ValueError(
                    f"prompt length {len(p)} outside (0, {spec.max_len}]"
                )
            tokens[i, : len(p)] = np.asarray(p, dtype=np.int32)
            slot_ids[i] = s
            plens[i] = len(p)
        fn = self._prefill_cache.get(bucket)
        if fn is None:
            fn = jax.jit(
                self._prefill_impl_paged if self.paged else self._prefill_impl
            )
            self._prefill_cache[bucket] = fn
        route = [jnp.asarray(slot_ids)]
        if self.paged:
            ps = spec.page_size
            width = -(-bucket // ps)
            row_tables = np.full(
                (spec.max_seqs, width), spec.num_pages, dtype=np.int32
            )
            for i, s in enumerate(slots):
                row_tables[i] = self.cache.block_tables[s, :width]
            route.append(jnp.asarray(row_tables))
            new_k, new_v, new_ks, new_vs, nxt, last = fn(
                params,
                jnp.asarray(tokens),
                *route,
                jnp.asarray(plens),
                self.cache.k,
                self.cache.v,
                self.cache.k_scale,
                self.cache.v_scale,
                *self._adapter_row_args(slots),
            )
            self.cache.commit(new_k, new_v, new_ks, new_vs)
        else:
            new_k, new_v, nxt, last = fn(
                params,
                jnp.asarray(tokens),
                *route,
                jnp.asarray(plens),
                self.cache.k,
                self.cache.v,
                *self._adapter_row_args(slots),
            )
            self.cache.commit(new_k, new_v)
        for p, s in zip(prompts, slots):
            self.cache.lengths[s] = len(p)
        out_nxt, out_last = np.asarray(nxt[:n]), np.asarray(last[:n])
        if self.telemetry is not None:
            # prefill is synchronous (the np.asarray reads above block
            # on the device), so one host-lane span covers it whole
            self.telemetry.tracer.complete(
                "prefill",
                "engine",
                t0,
                time.perf_counter(),
                args={"prompts": n, "bucket": bucket},
            )
        return out_nxt, out_last

    def prefill_suffix(
        self,
        params,
        prompts: Sequence[Sequence[int]],
        slots: Sequence[int],
        cursors: Sequence[int],
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Prefill only tokens[cursor:] of each prompt — the admission
        path for prefix-shared requests. The shared pages already hold
        positions [0, cursor) (alloc_shared mapped them and parked
        cache.lengths at the cursor), so this runs ONE chunked-prefill
        step over the unshared suffixes: the chunk core's staircase
        mask with query_offset = cursor reads the shared pages through
        the block table and is logit-identical to the monolithic
        prefill (PR 10's bit-identity argument), and the sampled token
        lands at each request's FULL prompt length — the same _pick key
        the monolithic path uses. Returns (next_tokens [n],
        last_logits [n, V]) in request order."""
        t0 = time.perf_counter()
        spec = self.cache.spec
        if not prompts:
            raise ValueError("prefill_suffix needs at least one prompt")
        suffixes = []
        for p, c in zip(prompts, cursors):
            c = int(c)
            if not 0 <= c < len(p):
                raise ValueError(
                    f"cursor {c} outside [0, {len(p)}) — at least one "
                    "prompt token must be recomputed for sampling logits"
                )
            suffixes.append(list(p[c:]))
        w = max(len(sfx) for sfx in suffixes)
        tokens = np.zeros((spec.max_seqs, w), dtype=np.int32)
        chunk_lens = np.zeros(spec.max_seqs, dtype=np.int32)
        for sfx, s in zip(suffixes, slots):
            tokens[s, : len(sfx)] = np.asarray(sfx, dtype=np.int32)
            chunk_lens[s] = len(sfx)
        nxt, logits = self.prefill_chunk(params, tokens, chunk_lens)
        if self.telemetry is not None:
            self.telemetry.tracer.complete(
                "prefill_suffix",
                "engine",
                t0,
                time.perf_counter(),
                args={"prompts": len(prompts), "width": w},
            )
        return (
            np.asarray([nxt[s] for s in slots]),
            np.stack([logits[s] for s in slots]),
        )

    # -- decode --------------------------------------------------------------

    def _decode_core(self, params, tokens, lengths, active, ck, cv, ad=None):
        """One decode forward over the slot-contiguous cache: write the
        new K/V row per active slot at `lengths`, run masked one-query
        attention, return (ck', cv', logits [max_seqs, V]). The
        single-step jit and the multi-step scan body both trace THIS
        function, so their HLO op sequence — and therefore their
        logits — match exactly (the token/logit-identity contract).
        `ad=None` (no adapter pool) leaves the traced HLO byte-for-byte
        what it was before multi-LoRA existed."""
        import jax
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            decode_attention,
            mha_project_qkv,
            mha_project_out,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            apply_adapter_out,
            apply_adapter_qkv,
        )

        new_k = dict(ck)
        new_v = dict(cv)

        def row_update(cache, new):
            upd = jax.vmap(
                lambda c, nrow, pos: jax.lax.dynamic_update_slice(
                    c, nrow, (pos, 0, 0)
                )
            )(cache, new.astype(cache.dtype), lengths)
            return jnp.where(active[:, None, None, None], upd, cache)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            # LoRA deltas land BEFORE the cache write: the K/V rows the
            # pool stores are the adapted values, so the attention
            # kernel (dense or Pallas) never needs to know adapters
            # exist — the out-projection delta below is the only
            # post-kernel epilogue
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            kc = row_update(ck[g], k)
            vc = row_update(cv[g], v)
            new_k[g] = kc
            new_v[g] = vc
            attn = decode_attention(
                q, kc, vc, lengths, kernel=self.decode_kernel
            )
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)[:, -1, :]
        return new_k, new_v, logits

    def _decode_impl(self, params, tokens, lengths, active, ck, cv, ad=None):
        """tokens [max_seqs, 1]; lengths [max_seqs] = cache position the
        incoming token is written at; active [max_seqs] bool masks cache
        writes for free slots."""
        import jax.numpy as jnp

        new_k, new_v, logits = self._decode_core(
            params, tokens, lengths, active, ck, cv, ad
        )
        slots = jnp.arange(lengths.shape[0])
        # the sampled token will be written at cache position lengths + 1
        return new_k, new_v, self._pick(logits, slots, lengths + 1), logits

    def _decode_core_paged(
        self, params, tokens, lengths, active, tables, ck, cv, cks, cvs,
        ad=None,
    ):
        """Paged twin of _decode_core. tables [max_seqs,
        max_pages_per_seq] int32 block tables. The new K/V row scatters
        into `tables[slot, lengths // page_size] * page_size + lengths %
        page_size` of the flattened pool; inactive slots are routed to an
        out-of-bounds destination (dropped), replacing the contiguous
        path's where-mask. Returns (ck', cv', cks', cvs', logits)."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            paged_decode_attention,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            apply_adapter_out,
            apply_adapter_qkv,
        )

        spec = self.cache.spec
        ps = spec.page_size
        oob = spec.num_pages * ps
        quant = getattr(self.cache, "quantized", False)
        new_k = dict(ck)
        new_v = dict(cv)
        new_ks, new_vs = dict(cks), dict(cvs)
        page = jnp.take_along_axis(tables, (lengths // ps)[:, None], axis=1)[
            :, 0
        ]
        dest = jnp.where(active, page * ps + lengths % ps, oob)

        def row_update(pool, new):
            flat = pool.reshape(-1, spec.num_heads, spec.head_dim)
            return flat.at[dest].set(new[:, 0].astype(pool.dtype)).reshape(
                pool.shape
            )

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            # adapted K/V go INTO the pool (delta precedes the scatter),
            # so the Pallas kernel reads adapter-aware pages unchanged
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            if quant:
                kc, new_ks[g], _ = self._quant_scatter(
                    ck[g], cks[g], k[:, 0], dest
                )
                vc, new_vs[g], _ = self._quant_scatter(
                    cv[g], cvs[g], v[:, 0], dest
                )
                attn = paged_decode_attention(
                    q, kc, vc, tables, lengths, kernel=self.decode_kernel,
                    k_scale=new_ks[g], v_scale=new_vs[g],
                )
            else:
                kc = row_update(ck[g], k)
                vc = row_update(cv[g], v)
                attn = paged_decode_attention(
                    q, kc, vc, tables, lengths, kernel=self.decode_kernel
                )
            new_k[g] = kc
            new_v[g] = vc
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)[:, -1, :]
        return new_k, new_v, new_ks, new_vs, logits

    def _decode_impl_paged(
        self, params, tokens, lengths, active, tables, ck, cv, cks, cvs,
        ad=None,
    ):
        """Paged twin of _decode_impl (the single-step jit target):
        one _decode_core_paged forward plus the per-slot sample."""
        import jax.numpy as jnp

        new_k, new_v, new_ks, new_vs, logits = self._decode_core_paged(
            params, tokens, lengths, active, tables, ck, cv, cks, cvs, ad
        )
        slots = jnp.arange(lengths.shape[0])
        return (
            new_k,
            new_v,
            new_ks,
            new_vs,
            self._pick(logits, slots, lengths + 1),
            logits,
        )

    # -- device-resident multi-step decode -----------------------------------

    def _decode_multi_impl(
        self, k_bucket, params, tokens, lengths, active, limits, eos, ck, cv,
        ad=None,
    ):
        """K fused decode iterations as ONE jitted `lax.scan` — the
        device-resident inner loop. tokens [max_seqs] int32 (the last
        emitted token per slot); lengths [max_seqs] pre-window cache
        lengths; active [max_seqs] bool; limits [max_seqs] int32
        PER-SLOT fused-step caps (a budget- or boundary-capped slot
        stops contributing at its own limit while deeper slots keep
        fusing); eos [max_seqs] int32 EOS token id per slot (-1 =
        none). Each scan step traces the SAME `_decode_core` the
        single-step jit traces, then samples with the identical
        position-derived `_pick` key — fold_in(fold_in(seed, slot),
        position) depends only on the running length, never the step
        counter, so the fused stream is identical-by-construction to
        step-at-a-time. EOS detection, length bumps, and
        retire-the-slot masking all live in the scan carry; `k_bucket`
        is the trace-time scan length (the pow-2 bucket the dispatch
        rounds K up to — steps past a slot's limit are masked out).

        Returns (ck', cv', final_lengths, final_tokens,
        tokens_ks [K, max_seqs], logits_ks [K, max_seqs, V],
        mask_ks [K, max_seqs]) — the per-step stacks the window
        reconcile slices to the true K."""
        import jax
        import jax.numpy as jnp

        slots = jnp.arange(lengths.shape[0])

        def body(carry, i):
            ck_c, cv_c, lens, toks, alive = carry
            act = alive & (i < limits)
            nk, nv, logits = self._decode_core(
                params, toks[:, None], lens, act, ck_c, cv_c, ad
            )
            nxt = self._pick(logits, slots, lens + 1)
            hit = act & (eos >= 0) & (nxt == eos)
            new_lens = jnp.where(act, lens + 1, lens)
            new_toks = jnp.where(act, nxt, toks)
            return (nk, nv, new_lens, new_toks, alive & ~hit), (
                nxt,
                logits,
                act,
            )

        carry0 = (ck, cv, lengths, tokens, active)
        (nk, nv, lens, toks, _), (toks_ks, logits_ks, mask_ks) = jax.lax.scan(
            body, carry0, jnp.arange(k_bucket)
        )
        return nk, nv, lens, toks, toks_ks, logits_ks, mask_ks

    def _decode_multi_impl_paged(
        self,
        k_bucket,
        params,
        tokens,
        lengths,
        active,
        limits,
        eos,
        tables,
        ck,
        cv,
        cks,
        cvs,
        ad=None,
    ):
        """Paged twin of _decode_multi_impl. The block tables ride in
        as ONE trace-time snapshot: the dispatch pre-claims every page
        the window can touch (the scheduler's per-slot limits never
        cross more than one fresh page — the page-boundary K cap), so
        the scan body recomputes each step's scatter destination from
        the carried lengths against STATIC tables. int8 scale pools
        ride the carry through `_quant_scatter` exactly like the
        single-step path."""
        import jax
        import jax.numpy as jnp

        slots = jnp.arange(lengths.shape[0])

        def body(carry, i):
            ck_c, cv_c, cks_c, cvs_c, lens, toks, alive = carry
            act = alive & (i < limits)
            nk, nv, nks, nvs, logits = self._decode_core_paged(
                params, toks[:, None], lens, act, tables, ck_c, cv_c,
                cks_c, cvs_c, ad,
            )
            nxt = self._pick(logits, slots, lens + 1)
            hit = act & (eos >= 0) & (nxt == eos)
            new_lens = jnp.where(act, lens + 1, lens)
            new_toks = jnp.where(act, nxt, toks)
            return (nk, nv, nks, nvs, new_lens, new_toks, alive & ~hit), (
                nxt,
                logits,
                act,
            )

        carry0 = (ck, cv, cks, cvs, lengths, tokens, active)
        (nk, nv, nks, nvs, lens, toks, _), (
            toks_ks,
            logits_ks,
            mask_ks,
        ) = jax.lax.scan(body, carry0, jnp.arange(k_bucket))
        return nk, nv, nks, nvs, lens, toks, toks_ks, logits_ks, mask_ks

    def decode_dispatch(
        self,
        params,
        tokens: np.ndarray,
        active_mask: np.ndarray,
        chain: Optional[InflightStep] = None,
        chain_mask: Optional[np.ndarray] = None,
    ) -> InflightStep:
        """Enqueue one decode iteration WITHOUT blocking on its outputs.

        tokens [max_seqs] (last emitted token per slot; free slots can
        carry anything), active_mask [max_seqs] bool. The functional
        cache arrays commit immediately (they are device futures — the
        next dispatch chains on them on-device) and active lengths bump,
        so the host's view is reserved-one-step-ahead; the sampled
        tokens/logits stay device futures on the returned InflightStep
        until `decode_reconcile`.

        `chain` + `chain_mask` pipeline two decode steps with no host
        round-trip: where chain_mask is set, the input token comes from
        the in-flight `chain` step's device_next instead of the host
        `tokens` row — the data dependency between step N and N+1
        resolves entirely on device."""
        import jax.numpy as jnp

        args = []
        if self.paged:
            # claim the next page for any sequence about to cross a page
            # boundary BEFORE the jitted step (host-side allocator; the
            # admission reserve guarantees the claim succeeds)
            for slot in np.nonzero(np.asarray(active_mask))[0]:
                self.cache.ensure_position(
                    int(slot), int(self.cache.lengths[slot])
                )
            args = [snapshot(self.cache.block_tables)]
        host_tokens = np.asarray(tokens, dtype=np.int32)
        mask = (
            np.asarray(chain_mask, dtype=bool)
            if chain is not None and chain_mask is not None
            else None
        )
        if mask is None or not mask.any():
            dev_tokens = jnp.asarray(host_tokens)
        elif mask.all() or np.array_equal(
            mask, np.asarray(active_mask, dtype=bool)
        ):
            # steady state: every stepped slot chains on the in-flight
            # step — its device_next IS the token vector (inactive rows
            # carry garbage the active mask already hides)
            dev_tokens = chain.device_next
        else:
            # device_next is already int32 (_pick's contract)
            dev_tokens = jnp.where(
                jnp.asarray(mask), chain.device_next, jnp.asarray(host_tokens)
            )
        lengths_snap = np.array(self.cache.lengths)
        # snapshot() every mutable host array (lengths += 1 below,
        # allocator table edits between iterations mutate behind the
        # async dispatch queue); the locals built above are fresh per
        # call and safe to hand over directly
        scale_args = (
            [self.cache.k_scale, self.cache.v_scale] if self.paged else []
        )
        step_args = (
            params,
            dev_tokens[:, None],
            snapshot(self.cache.lengths),
            jnp.asarray(active_mask),
            *args,
            self.cache.k,
            self.cache.v,
            *scale_args,
            *self._adapter_slot_args(),
        )
        if self.paged:
            new_k, new_v, new_ks, new_vs, nxt, logits = self._dispatch(
                "decode", lambda: self._decode_jit(*step_args)
            )
            self.cache.commit(new_k, new_v, new_ks, new_vs)
        else:
            new_k, new_v, nxt, logits = self._dispatch(
                "decode", lambda: self._decode_jit(*step_args)
            )
            self.cache.commit(new_k, new_v)
        self.cache.lengths[np.asarray(active_mask)] += 1
        # the in-flight window pins pages this step's snapshot tables
        # reference; decode_reconcile closes it
        self.cache.begin_inflight()
        return InflightStep(
            kind="decode",
            dispatch_t=time.perf_counter(),
            active=np.array(active_mask, dtype=bool),
            lengths=lengths_snap,
            host_tokens=host_tokens,
            device_next=nxt,
            device_logits=logits,
        )

    def decode_reconcile(
        self, step: InflightStep
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Block on a dispatched decode step's device outputs and close
        its in-flight window. Returns (next_tokens [max_seqs], logits
        [max_seqs, V]) as host arrays. Everything else the caller needs
        lives on the step record's snapshots — by the time this runs,
        live cache/scheduler state is one iteration ahead."""
        try:
            nxt = np.asarray(step.device_next)
            logits = np.asarray(step.device_logits)
        finally:
            self.cache.end_inflight()
        return nxt, logits

    def decode(
        self,
        params,
        tokens: np.ndarray,
        active_mask: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One decode iteration over every slot — the synchronous wrapper
        (dispatch + immediate reconcile); the async loop calls the two
        halves an iteration apart. Writes the cache, bumps active
        lengths, returns (next_tokens [max_seqs], logits [max_seqs, V])."""
        return self.decode_reconcile(
            self.decode_dispatch(params, tokens, active_mask)
        )

    def decode_multi_dispatch(
        self,
        params,
        tokens: np.ndarray,
        active_mask: np.ndarray,
        step_limits: np.ndarray,
        eos_tokens: Optional[np.ndarray] = None,
        chain: Optional[InflightStep] = None,
        chain_mask: Optional[np.ndarray] = None,
    ) -> InflightStep:
        """Enqueue ONE fused K-step decode window WITHOUT blocking.

        tokens [max_seqs] (last emitted token per slot), active_mask
        [max_seqs] bool, step_limits [max_seqs] int32 — how many fused
        steps each slot runs (K = max over active slots; the scan
        traces at the pow-2 bucket of K and masks steps past a slot's
        own limit). eos_tokens [max_seqs] int32 per-slot EOS ids (-1 =
        none): EOS retires the slot INSIDE the scan — it emits its
        final token, then contributes nothing past it.

        The host's view is reserved-K-steps-ahead: active lengths bump
        by their full limits at dispatch, and the paged allocator
        pre-claims every page the window can touch before the tables
        snapshot (the existing begin_inflight/end_inflight reserve
        window pins them for the window's whole life). The window
        reconcile rolls back what the device did not take
        (cache.truncate — EOS inside the window returns the surplus).
        `chain`/`chain_mask` pipeline a window onto an in-flight step's
        device_next exactly like decode_dispatch."""
        import jax.numpy as jnp

        spec = self.cache.spec
        limits = np.where(
            np.asarray(active_mask, dtype=bool),
            np.asarray(step_limits, dtype=np.int32),
            0,
        ).astype(np.int32)
        k = int(limits.max()) if limits.size else 0
        if k < 1:
            raise ValueError(
                "multi-step window needs at least one fused step"
            )
        lengths_snap = np.array(self.cache.lengths)
        for slot in np.nonzero(limits)[0]:
            if int(lengths_snap[slot]) + int(limits[slot]) > spec.max_len:
                raise ValueError(
                    f"slot {int(slot)}: {int(limits[slot])} fused steps "
                    f"overrun max_len {spec.max_len}"
                )
        # pow-2 K bucket: the scan length is a trace-time constant, so
        # bucketing keeps the compile population log-bounded; the
        # per-slot limits mask the bucket's surplus steps out
        k_bucket = 1 << (k - 1).bit_length()
        args = []
        if self.paged:
            # pre-claim every page the window can touch BEFORE the
            # jitted scan: the block tables ride in as one trace-time
            # snapshot, so all K steps' destinations must already map
            # (the admission reserve guarantees these claims; the
            # scheduler's page-boundary K cap keeps them to at most one
            # fresh page per slot)
            for slot in np.nonzero(limits)[0]:
                start = int(lengths_snap[slot])
                for p in range(start, start + int(limits[slot])):
                    self.cache.ensure_position(int(slot), p)
            args = [snapshot(self.cache.block_tables)]
        host_tokens = np.asarray(tokens, dtype=np.int32)
        eos = (
            np.asarray(eos_tokens, dtype=np.int32)
            if eos_tokens is not None
            else np.full(spec.max_seqs, -1, dtype=np.int32)
        )
        mask = (
            np.asarray(chain_mask, dtype=bool)
            if chain is not None and chain_mask is not None
            else None
        )
        if mask is None or not mask.any():
            dev_tokens = jnp.asarray(host_tokens)
        elif mask.all() or np.array_equal(
            mask, np.asarray(active_mask, dtype=bool)
        ):
            dev_tokens = chain.device_next
        else:
            dev_tokens = jnp.where(
                jnp.asarray(mask), chain.device_next, jnp.asarray(host_tokens)
            )
        # snapshot() every mutable host array (lengths += limits below,
        # allocator table edits between iterations mutate behind the
        # async dispatch queue); see decode_dispatch()
        scale_args = (
            [self.cache.k_scale, self.cache.v_scale] if self.paged else []
        )
        step_args = (
            params,
            dev_tokens,
            snapshot(self.cache.lengths),
            jnp.asarray(np.asarray(active_mask, dtype=bool)),
            jnp.asarray(limits),
            jnp.asarray(eos),
            *args,
            self.cache.k,
            self.cache.v,
            *scale_args,
            *self._adapter_slot_args(),
        )
        key = (spec.max_seqs, k_bucket, "paged" if self.paged else "slot")

        def call():
            # resolved inside the dispatch so a kernel fallback's
            # cleared cache re-traces with the dense attention core
            return self._multistep_cache.get(key)(*step_args)

        if self.paged:
            (
                new_k,
                new_v,
                new_ks,
                new_vs,
                d_lens,
                d_toks,
                toks_ks,
                logits_ks,
                mask_ks,
            ) = self._dispatch("multistep", call)
            self.cache.commit(new_k, new_v, new_ks, new_vs)
        else:
            new_k, new_v, d_lens, d_toks, toks_ks, logits_ks, mask_ks = (
                self._dispatch("multistep", call)
            )
            self.cache.commit(new_k, new_v)
        act = np.asarray(active_mask, dtype=bool)
        self.cache.lengths[act] += limits[act]
        # the in-flight window pins pages this window's snapshot tables
        # reference for all K steps; decode_multi_reconcile closes it
        self.cache.begin_inflight()
        return InflightStep(
            kind="multistep",
            dispatch_t=time.perf_counter(),
            active=np.array(active_mask, dtype=bool),
            lengths=lengths_snap,
            host_tokens=host_tokens,
            device_next=d_toks,
            device_logits=logits_ks,
            device_tokens=toks_ks,
            device_mask=mask_ks,
            device_lengths=d_lens,
            k_steps=k,
            step_limits=limits,
        )

    def decode_multi_reconcile(
        self, step: InflightStep
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Block on a fused window's device outputs and close its
        in-flight window. Returns (tokens_ks [K, max_seqs], logits_ks
        [K, max_seqs, V], mask_ks [K, max_seqs]) sliced to the
        window's true K (the scan ran the pow-2 bucket; rows past K
        are all-masked padding). Commit decisions — which tokens to
        emit, how far to roll lengths back — belong to the caller,
        made against the step record's snapshots ONLY: by the time
        this runs, live cache/scheduler state is a whole window
        ahead (fxlint FX109)."""
        try:
            toks_ks = np.asarray(step.device_tokens)
            logits_ks = np.asarray(step.device_logits)
            mask_ks = np.asarray(step.device_mask)
        finally:
            self.cache.end_inflight()
        k = int(step.k_steps)
        return toks_ks[:k], logits_ks[:k], mask_ks[:k]

    def decode_multi(
        self,
        params,
        tokens: np.ndarray,
        active_mask: np.ndarray,
        step_limits: np.ndarray,
        eos_tokens: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Synchronous fused window (dispatch + immediate reconcile).
        NOTE: the host lengths stay advanced by the FULL per-slot
        limits; callers roll back early-retired slots with
        cache.truncate(slot, lengths + taken) like the scheduler's
        window commit does."""
        return self.decode_multi_reconcile(
            self.decode_multi_dispatch(
                params, tokens, active_mask, step_limits, eos_tokens
            )
        )

    # -- verify (speculative decoding) ---------------------------------------

    def _verify_scatter_dest(
        self, w, lengths, draft_lens, tables, jnp, slot_ids=None
    ):
        """Flattened-cache destinations [batch * w] for the verify
        write: row j of batch row b lands at cache position
        lengths[b] + j when j < draft_lens[b] and the position is
        inside max_len; every other row routes out of bounds (JAX
        drops OOB scatter rows), so pad rows, inactive slots, and
        overflow never touch live cache. The batch is slot-indexed
        (batch row == slot) unless `slot_ids` maps a COMPACT batch's
        rows to their slots (the chunked-prefill path); the paged
        branch needs no ids because `tables` rows arrive already
        batch-aligned."""
        spec = self.cache.spec
        pos = lengths[:, None] + jnp.arange(w)[None, :]  # [batch, w]
        valid = (jnp.arange(w)[None, :] < draft_lens[:, None]) & (
            pos < spec.max_len
        )
        if self.paged:
            ps = spec.page_size
            page_idx = jnp.clip(pos // ps, 0, spec.max_pages_per_seq - 1)
            entry = jnp.take_along_axis(tables, page_idx, axis=1)
            # sentinel entries (num_pages) already land past the pool
            flat = entry * ps + pos % ps
            oob = spec.num_pages * ps
        else:
            rows = (
                jnp.arange(spec.max_seqs) if slot_ids is None else slot_ids
            )
            flat = rows[:, None] * spec.max_len + pos
            oob = spec.max_seqs * spec.max_len
        return jnp.where(valid, flat, oob).reshape(-1)

    def _verify_impl(
        self, params, tokens, lengths, draft_lens, ck, cv, ad=None
    ):
        """tokens [max_seqs, w] int32 — column 0 is each slot's last
        emitted (not yet cached) token, columns 1..draft_lens-1 the
        drafted continuation; lengths [max_seqs] = cache length BEFORE
        the step; draft_lens [max_seqs] = real rows per slot (0 for
        inactive slots). Writes all w K/V rows (masked via OOB scatter),
        runs staircase-masked verify attention, and returns
        (ck', cv', logits [max_seqs, w, V]) — logits[s, j] is the
        model's distribution for the token FOLLOWING tokens[s, j].
        Lengths are NOT advanced; acceptance commits via
        cache.truncate."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            verify_attention,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            apply_adapter_out,
            apply_adapter_qkv,
        )

        spec = self.cache.spec
        dest = self._verify_scatter_dest(
            tokens.shape[1], lengths, draft_lens, None, jnp
        )
        new_k = dict(ck)
        new_v = dict(cv)

        def row_update(cache, new):
            flat = cache.reshape(-1, spec.num_heads, spec.head_dim)
            rows = new.astype(cache.dtype).reshape(
                -1, spec.num_heads, spec.head_dim
            )
            return flat.at[dest].set(rows).reshape(cache.shape)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            kc = row_update(ck[g], k)
            vc = row_update(cv[g], v)
            new_k[g] = kc
            new_v[g] = vc
            attn = verify_attention(
                q, kc, vc, lengths, kernel=self.decode_kernel
            )
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)
        return new_k, new_v, logits

    def _verify_impl_paged(
        self, params, tokens, lengths, draft_lens, tables, ck, cv, cks, cvs,
        ad=None,
    ):
        """Paged twin of _verify_impl: rows route through the block
        tables into the flattened pools, attention gathers pages via
        ops.attention.paged_verify_attention. Under int8 pools the w
        fresh rows quantize through `_quant_scatter` and the per-page
        scales ride along to the attention gather."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            paged_verify_attention,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            apply_adapter_out,
            apply_adapter_qkv,
        )

        spec = self.cache.spec
        quant = getattr(self.cache, "quantized", False)
        dest = self._verify_scatter_dest(
            tokens.shape[1], lengths, draft_lens, tables, jnp
        )
        new_k = dict(ck)
        new_v = dict(cv)
        new_ks = dict(cks)
        new_vs = dict(cvs)

        def row_update(pool, new):
            flat = pool.reshape(-1, spec.num_heads, spec.head_dim)
            rows = new.astype(pool.dtype).reshape(
                -1, spec.num_heads, spec.head_dim
            )
            return flat.at[dest].set(rows).reshape(pool.shape)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            if quant:
                kc, new_ks[g], _ = self._quant_scatter(
                    ck[g],
                    cks[g],
                    k.reshape(-1, spec.num_heads, spec.head_dim),
                    dest,
                )
                vc, new_vs[g], _ = self._quant_scatter(
                    cv[g],
                    cvs[g],
                    v.reshape(-1, spec.num_heads, spec.head_dim),
                    dest,
                )
                new_k[g] = kc
                new_v[g] = vc
                attn = paged_verify_attention(
                    q,
                    kc,
                    vc,
                    tables,
                    lengths,
                    kernel=self.decode_kernel,
                    k_scale=new_ks[g],
                    v_scale=new_vs[g],
                )
            else:
                kc = row_update(ck[g], k)
                vc = row_update(cv[g], v)
                new_k[g] = kc
                new_v[g] = vc
                attn = paged_verify_attention(
                    q, kc, vc, tables, lengths, kernel=self.decode_kernel
                )
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)
        return new_k, new_v, new_ks, new_vs, logits

    def _verify_tree_impl(
        self, params, tokens, lengths, draft_lens, parents, ck, cv, ad=None
    ):
        """Tree twin of _verify_impl: tokens [max_seqs, w] where column
        0 is the slot's last emitted token (the tree ROOT's input) and
        columns 1..w-1 are draft-tree nodes in topological order;
        parents [max_seqs, w] int32 gives each row's parent ROW index
        (-1 for row 0). The per-token ancestor mask replaces the
        staircase: row j attends the committed prefix plus its own
        root-to-j chain only, so every branch scores exactly as if it
        were the lone continuation. K/V rows still land at positions
        lengths + j — branch tokens occupy scattered rows that
        cache.truncate(slot, new_len, src_rows) later compacts."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            verify_attention,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            apply_adapter_out,
            apply_adapter_qkv,
        )

        spec = self.cache.spec
        dest = self._verify_scatter_dest(
            tokens.shape[1], lengths, draft_lens, None, jnp
        )
        new_k = dict(ck)
        new_v = dict(cv)

        def row_update(cache, new):
            flat = cache.reshape(-1, spec.num_heads, spec.head_dim)
            rows = new.astype(cache.dtype).reshape(
                -1, spec.num_heads, spec.head_dim
            )
            return flat.at[dest].set(rows).reshape(cache.shape)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            kc = row_update(ck[g], k)
            vc = row_update(cv[g], v)
            new_k[g] = kc
            new_v[g] = vc
            attn = verify_attention(
                q,
                kc,
                vc,
                lengths,
                kernel=self.decode_kernel,
                tree_parents=parents,
            )
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)
        return new_k, new_v, logits

    def _verify_tree_impl_paged(
        self, params, tokens, lengths, draft_lens, parents, tables, ck, cv,
        cks, cvs, ad=None,
    ):
        """Paged twin of _verify_tree_impl — _verify_impl_paged with the
        parent table threaded into paged_verify_attention."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            paged_verify_attention,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            apply_adapter_out,
            apply_adapter_qkv,
        )

        spec = self.cache.spec
        quant = getattr(self.cache, "quantized", False)
        dest = self._verify_scatter_dest(
            tokens.shape[1], lengths, draft_lens, tables, jnp
        )
        new_k = dict(ck)
        new_v = dict(cv)
        new_ks = dict(cks)
        new_vs = dict(cvs)

        def row_update(pool, new):
            flat = pool.reshape(-1, spec.num_heads, spec.head_dim)
            rows = new.astype(pool.dtype).reshape(
                -1, spec.num_heads, spec.head_dim
            )
            return flat.at[dest].set(rows).reshape(pool.shape)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            if quant:
                kc, new_ks[g], _ = self._quant_scatter(
                    ck[g],
                    cks[g],
                    k.reshape(-1, spec.num_heads, spec.head_dim),
                    dest,
                )
                vc, new_vs[g], _ = self._quant_scatter(
                    cv[g],
                    cvs[g],
                    v.reshape(-1, spec.num_heads, spec.head_dim),
                    dest,
                )
                new_k[g] = kc
                new_v[g] = vc
                attn = paged_verify_attention(
                    q,
                    kc,
                    vc,
                    tables,
                    lengths,
                    kernel=self.decode_kernel,
                    k_scale=new_ks[g],
                    v_scale=new_vs[g],
                    tree_parents=parents,
                )
            else:
                kc = row_update(ck[g], k)
                vc = row_update(cv[g], v)
                new_k[g] = kc
                new_v[g] = vc
                attn = paged_verify_attention(
                    q,
                    kc,
                    vc,
                    tables,
                    lengths,
                    kernel=self.decode_kernel,
                    tree_parents=parents,
                )
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)
        return new_k, new_v, new_ks, new_vs, logits

    def verify_dispatch(
        self,
        params,
        tokens: np.ndarray,
        draft_lens: np.ndarray,
    ) -> InflightStep:
        """Enqueue one verify step (SpecInfer's scoring call) WITHOUT
        blocking on its logits. tokens [max_seqs, w]: column 0 is the
        slot's last emitted token (the one plain decode would feed),
        columns 1..draft_lens[s]-1 its drafted continuation; rows with
        draft_lens 0 are inactive. Writes the w K/V rows into the cache
        (paged slots claim the pages those rows need first — the
        admission reserve covers them as long as the caller keeps
        drafts inside the request's declared worst case) but does NOT
        advance lengths: `verify_reconcile` hands back the logits
        [max_seqs, w, V], and the caller accepts a prefix of the drafts
        against the step's SNAPSHOT lengths, committing/rolling back
        with cache.truncate(slot, new_len). One jitted program per
        draft width w, LRU-cached (`verify_cache_max`)."""
        import jax.numpy as jnp

        spec = self.cache.spec
        tokens = np.asarray(tokens, dtype=np.int32)
        draft_lens = np.asarray(draft_lens, dtype=np.int32)
        if tokens.ndim != 2 or tokens.shape[0] != spec.max_seqs:
            raise ValueError(
                f"tokens must be [max_seqs={spec.max_seqs}, w], "
                f"got {tokens.shape}"
            )
        w = tokens.shape[1]
        if w < 1:
            raise ValueError("verify needs at least one token column")
        if draft_lens.shape != (spec.max_seqs,):
            raise ValueError("draft_lens must be [max_seqs]")
        for slot in np.nonzero(draft_lens)[0]:
            need = int(self.cache.lengths[slot]) + int(draft_lens[slot])
            if draft_lens[slot] > w or need > spec.max_len:
                raise ValueError(
                    f"slot {int(slot)}: draft_lens {int(draft_lens[slot])} "
                    f"overruns width {w} or max_len {spec.max_len}"
                )
        args = []
        if self.paged:
            # claim every page the w fresh rows touch BEFORE the jitted
            # step (host-side allocator, like decode's boundary claim)
            for slot in np.nonzero(draft_lens)[0]:
                start = int(self.cache.lengths[slot])
                for p in range(start, start + int(draft_lens[slot])):
                    self.cache.ensure_position(int(slot), p)
            args = [snapshot(self.cache.block_tables)]
        lengths_snap = np.array(self.cache.lengths)
        # snapshot() lengths/tables: the caller truncates the cache
        # right after the reconcile, and jnp.asarray's host read is
        # deferred behind the dispatch queue — see decode_dispatch()
        scale_args = (
            [self.cache.k_scale, self.cache.v_scale] if self.paged else []
        )
        step_args = (
            params,
            jnp.asarray(tokens),
            snapshot(self.cache.lengths),
            jnp.asarray(draft_lens),
            *args,
            self.cache.k,
            self.cache.v,
            *scale_args,
            *self._adapter_slot_args(),
        )

        def call():
            # resolved inside the dispatch so a kernel fallback's
            # cleared cache re-traces with the dense attention core
            return self._verify_fn(w)(*step_args)

        if self.paged:
            new_k, new_v, new_ks, new_vs, logits = self._dispatch(
                "verify", call
            )
            self.cache.commit(new_k, new_v, new_ks, new_vs)
        else:
            new_k, new_v, logits = self._dispatch("verify", call)
            self.cache.commit(new_k, new_v)
        self.cache.begin_inflight()
        return InflightStep(
            kind="verify",
            dispatch_t=time.perf_counter(),
            active=np.asarray(draft_lens) > 0,
            lengths=lengths_snap,
            draft_lens=np.array(draft_lens),
            device_logits=logits,
        )

    def verify_reconcile(self, step: InflightStep) -> np.ndarray:
        """Block on a dispatched verify step's logits and close its
        in-flight window. Acceptance/rollback decisions belong to the
        caller, made against the step record's SNAPSHOT lengths."""
        try:
            return np.asarray(step.device_logits)
        finally:
            self.cache.end_inflight()

    def verify(
        self,
        params,
        tokens: np.ndarray,
        draft_lens: np.ndarray,
    ) -> np.ndarray:
        """Synchronous verify (dispatch + immediate reconcile): returns
        the logits [max_seqs, w, V] as a host array."""
        return self.verify_reconcile(
            self.verify_dispatch(params, tokens, draft_lens)
        )

    def verify_tree_dispatch(
        self,
        params,
        tokens: np.ndarray,
        draft_lens: np.ndarray,
        parents: np.ndarray,
    ) -> InflightStep:
        """Enqueue one tree-verify step (SpecInfer's tree-scoring call)
        WITHOUT blocking. tokens [max_seqs, w]: column 0 the slot's last
        emitted token, columns 1..draft_lens[s]-1 its draft-TREE nodes
        in topological order; parents [max_seqs, w] int32 maps each row
        to its parent row (-1 for the root, identity-chain padding past
        draft_lens). The ancestor mask is built from `parents` INSIDE
        the jitted step, so one compiled program serves every tree
        topology of width w. Page claims, cache commit, and the
        no-length-advance contract match verify_dispatch exactly; the
        returned step carries `tree_parents` (a host snapshot of the
        dispatched table) for the reconcile's tree walk."""
        import jax.numpy as jnp

        spec = self.cache.spec
        tokens = np.asarray(tokens, dtype=np.int32)
        draft_lens = np.asarray(draft_lens, dtype=np.int32)
        parents = np.asarray(parents, dtype=np.int32)
        if tokens.ndim != 2 or tokens.shape[0] != spec.max_seqs:
            raise ValueError(
                f"tokens must be [max_seqs={spec.max_seqs}, w], "
                f"got {tokens.shape}"
            )
        w = tokens.shape[1]
        if w < 1:
            raise ValueError("verify needs at least one token column")
        if draft_lens.shape != (spec.max_seqs,):
            raise ValueError("draft_lens must be [max_seqs]")
        if parents.shape != tokens.shape:
            raise ValueError(
                f"parents must match tokens shape {tokens.shape}, "
                f"got {parents.shape}"
            )
        if np.any(parents >= np.arange(w)[None, :]):
            raise ValueError(
                "parents must be topological: parents[:, j] < j"
            )
        for slot in np.nonzero(draft_lens)[0]:
            need = int(self.cache.lengths[slot]) + int(draft_lens[slot])
            if draft_lens[slot] > w or need > spec.max_len:
                raise ValueError(
                    f"slot {int(slot)}: draft_lens {int(draft_lens[slot])} "
                    f"overruns width {w} or max_len {spec.max_len}"
                )
        args = []
        if self.paged:
            for slot in np.nonzero(draft_lens)[0]:
                start = int(self.cache.lengths[slot])
                for p in range(start, start + int(draft_lens[slot])):
                    self.cache.ensure_position(int(slot), p)
            args = [snapshot(self.cache.block_tables)]
        lengths_snap = np.array(self.cache.lengths)
        scale_args = (
            [self.cache.k_scale, self.cache.v_scale] if self.paged else []
        )
        step_args = (
            params,
            jnp.asarray(tokens),
            snapshot(self.cache.lengths),
            jnp.asarray(draft_lens),
            jnp.asarray(parents),
            *args,
            self.cache.k,
            self.cache.v,
            *scale_args,
            *self._adapter_slot_args(),
        )

        def call():
            # resolved inside the dispatch so a kernel fallback's
            # cleared cache re-traces with the dense attention core
            return self._tree_fn(w)(*step_args)

        if self.paged:
            new_k, new_v, new_ks, new_vs, logits = self._dispatch(
                "verify", call
            )
            self.cache.commit(new_k, new_v, new_ks, new_vs)
        else:
            new_k, new_v, logits = self._dispatch("verify", call)
            self.cache.commit(new_k, new_v)
        self.cache.begin_inflight()
        return InflightStep(
            kind="verify_tree",
            dispatch_t=time.perf_counter(),
            active=np.asarray(draft_lens) > 0,
            lengths=lengths_snap,
            draft_lens=np.array(draft_lens),
            device_logits=logits,
            tree_parents=np.array(parents),
        )

    def verify_tree(
        self,
        params,
        tokens: np.ndarray,
        draft_lens: np.ndarray,
        parents: np.ndarray,
    ) -> np.ndarray:
        """Synchronous tree verify: returns logits [max_seqs, w, V] as a
        host array (reconcile shares verify_reconcile — the tree walk is
        the caller's, made against the step's snapshots)."""
        return self.verify_reconcile(
            self.verify_tree_dispatch(params, tokens, draft_lens, parents)
        )

    # -- chunked prefill -----------------------------------------------------

    def _chunk_impl(
        self, params, tokens, slot_ids, all_lengths, chunk_lens, ck, cv,
        ad=None,
    ):
        """tokens [B, w] int32 — the next chunk_lens[b] PROMPT tokens
        of each ACTIVE prefilling slot slot_ids[b] (0-padded);
        all_lengths [max_seqs] = every slot's cache cursor (the impl
        gathers its own rows). The batch is COMPACTED to chunking
        slots: a lone long prompt streaming through the budget costs
        B=1 rows of transformer compute per chunk step instead of
        max_seqs — the full-slot verify-style batch taxed every chunk
        step max_seqs/B x and erased the head-of-line win in wall
        clock. The verify core is otherwise verbatim — staircase mask
        with query_offset = cursor gives exact causal prefill
        semantics, and the same fp32 accumulation / -1e30 fill keeps
        chunked prefill logit-identical to the monolithic path (each
        batch row's reduction is independent, so compaction cannot
        move a logit) — plus the monolithic prefill's tail: the last
        valid position's logits are sampled at position cursor + chunk
        (== prompt length on the final chunk, so the first generated
        token matches _prefill_impl's exactly). Returns (ck', cv',
        next_tokens [B], last_logits [B, V]) in compact order;
        prefill_chunk_reconcile scatters them back to slot-indexed
        arrays."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            verify_attention,
        )

        from flexflow_tpu.serving.tenancy.adapters import (
            adapter_rows,
            apply_adapter_out,
            apply_adapter_qkv,
        )

        spec = self.cache.spec
        w = tokens.shape[1]
        lengths = all_lengths[slot_ids]  # [B] cursor per active slot
        # compact the slot-indexed adapter gather to the B batch rows
        ad = adapter_rows(ad, slot_ids)
        dest = self._verify_scatter_dest(
            w, lengths, chunk_lens, None, jnp, slot_ids=slot_ids
        )
        new_k = dict(ck)
        new_v = dict(cv)

        def row_update(cache, new):
            flat = cache.reshape(-1, spec.num_heads, spec.head_dim)
            rows = new.astype(cache.dtype).reshape(
                -1, spec.num_heads, spec.head_dim
            )
            return flat.at[dest].set(rows).reshape(cache.shape)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            kc = row_update(ck[g], k)
            vc = row_update(cv[g], v)
            new_k[g] = kc
            new_v[g] = vc
            # attention sees only the active slots' cache rows — the
            # update above already wrote the full cache for commit
            attn = verify_attention(
                q, kc[slot_ids], vc[slot_ids], lengths,
                kernel=self.decode_kernel,
            )
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)
        last = jnp.take_along_axis(
            logits, jnp.clip(chunk_lens - 1, 0, w - 1)[:, None, None], axis=1
        )[:, 0]
        # the sampling key matches _prefill_impl's _pick(last, slot_ids,
        # prompt_lens): on the final chunk cursor + chunk == prompt_len
        return (
            new_k,
            new_v,
            self._pick(last, slot_ids, lengths + chunk_lens),
            last,
        )

    def _chunk_impl_paged(
        self, params, tokens, slot_ids, all_lengths, chunk_lens, tables,
        ck, cv, cks, cvs, ad=None,
    ):
        """Paged twin of _chunk_impl: rows route through the block
        tables into the flattened pools, attention gathers pages via
        ops.attention.paged_verify_attention. Same compact batch —
        tables arrive full [max_seqs, pages] and the active rows are
        gathered here, so dest and attention both see batch-aligned
        tables."""
        import jax.numpy as jnp

        from flexflow_tpu.ops.attention import (
            mha_project_qkv,
            mha_project_out,
            paged_verify_attention,
        )
        from flexflow_tpu.serving.tenancy.adapters import (
            adapter_rows,
            apply_adapter_out,
            apply_adapter_qkv,
        )

        spec = self.cache.spec
        w = tokens.shape[1]
        lengths = all_lengths[slot_ids]  # [B] cursor per active slot
        ad = adapter_rows(ad, slot_ids)
        tables_g = tables[slot_ids]  # [B, pages] batch-aligned
        dest = self._verify_scatter_dest(
            w, lengths, chunk_lens, tables_g, jnp
        )
        quant = getattr(self.cache, "quantized", False)
        new_k = dict(ck)
        new_v = dict(cv)
        new_ks = dict(cks)
        new_vs = dict(cvs)

        def row_update(pool, new):
            flat = pool.reshape(-1, spec.num_heads, spec.head_dim)
            rows = new.astype(pool.dtype).reshape(
                -1, spec.num_heads, spec.head_dim
            )
            return flat.at[dest].set(rows).reshape(pool.shape)

        def hook(node, ins, ws, ctx):
            g = node.guid
            use_bias = node.params.get("bias", True)
            q, k, v = mha_project_qkv(ins, ws, ctx, use_bias=use_bias)
            q, k, v = apply_adapter_qkv(ins[0], q, k, v, ad, g)
            if quant:
                kc, new_ks[g], _ = self._quant_scatter(
                    ck[g],
                    cks[g],
                    k.reshape(-1, spec.num_heads, spec.head_dim),
                    dest,
                )
                vc, new_vs[g], _ = self._quant_scatter(
                    cv[g],
                    cvs[g],
                    v.reshape(-1, spec.num_heads, spec.head_dim),
                    dest,
                )
                new_k[g] = kc
                new_v[g] = vc
                attn = paged_verify_attention(
                    q,
                    kc,
                    vc,
                    tables_g,
                    lengths,
                    kernel=self.decode_kernel,
                    k_scale=new_ks[g],
                    v_scale=new_vs[g],
                )
            else:
                kc = row_update(ck[g], k)
                vc = row_update(cv[g], v)
                new_k[g] = kc
                new_v[g] = vc
                attn = paged_verify_attention(
                    q, kc, vc, tables_g, lengths, kernel=self.decode_kernel
                )
            out = mha_project_out(
                attn, ws, ctx, ins[0].dtype, use_bias=use_bias
            )
            return [apply_adapter_out(attn, out, ad, g)]

        logits = self._forward_logits(params, tokens, hook)
        last = jnp.take_along_axis(
            logits, jnp.clip(chunk_lens - 1, 0, w - 1)[:, None, None], axis=1
        )[:, 0]
        return (
            new_k,
            new_v,
            new_ks,
            new_vs,
            self._pick(last, slot_ids, lengths + chunk_lens),
            last,
        )

    def prefill_chunk_dispatch(
        self,
        params,
        tokens: np.ndarray,
        chunk_lens: np.ndarray,
    ) -> InflightStep:
        """Enqueue one chunked-prefill step WITHOUT blocking on its
        outputs. tokens [max_seqs, w]: the next chunk_lens[s] prompt
        tokens per chunking slot (rows with chunk_lens 0 are inactive).
        Writes the chunk K/V rows at each slot's cursor (paged slots
        claim the pages those rows need first) and — unlike verify —
        ADVANCES lengths at dispatch: the rows are prompt tokens,
        accepted by construction, so the next chunk for the same slot
        can dispatch before this one reconciles (chunks pipeline with
        no host data dependency). The sampled token on the returned
        step is meaningful only for a slot's FINAL chunk; the caller
        decides which via its own cursor snapshot (InflightStep.chunks,
        filled by the scheduler)."""
        import jax.numpy as jnp

        spec = self.cache.spec
        tokens = np.asarray(tokens, dtype=np.int32)
        chunk_lens = np.asarray(chunk_lens, dtype=np.int32)
        if tokens.ndim != 2 or tokens.shape[0] != spec.max_seqs:
            raise ValueError(
                f"tokens must be [max_seqs={spec.max_seqs}, w], "
                f"got {tokens.shape}"
            )
        w = tokens.shape[1]
        if w < 1:
            raise ValueError("chunk step needs at least one token column")
        if chunk_lens.shape != (spec.max_seqs,):
            raise ValueError("chunk_lens must be [max_seqs]")
        for slot in np.nonzero(chunk_lens)[0]:
            need = int(self.cache.lengths[slot]) + int(chunk_lens[slot])
            if chunk_lens[slot] > w or need > spec.max_len:
                raise ValueError(
                    f"slot {int(slot)}: chunk_lens {int(chunk_lens[slot])} "
                    f"overruns width {w} or max_len {spec.max_len}"
                )
        slot_ids = np.nonzero(chunk_lens)[0]
        if slot_ids.size == 0:
            raise ValueError("chunk step needs at least one active slot")
        args = []
        if self.paged:
            # claim every page the chunk rows touch BEFORE the jitted
            # step (host-side allocator, like verify's claim loop)
            for slot in slot_ids:
                start = int(self.cache.lengths[slot])
                for p in range(start, start + int(chunk_lens[slot])):
                    self.cache.ensure_position(int(slot), p)
            args = [snapshot(self.cache.block_tables)]
        lengths_snap = np.array(self.cache.lengths)
        # snapshot() lengths/tables: the cursor bump below mutates
        # lengths right after dispatch, and jnp.asarray's host read is
        # deferred behind the dispatch queue — see decode_dispatch().
        # The batch compacts to the chunking slots (tokens/chunk_lens
        # rows); the jitted impl gathers its lengths/tables rows from
        # the full snapshots by slot_ids.
        scale_args = (
            [self.cache.k_scale, self.cache.v_scale] if self.paged else []
        )
        step_args = (
            params,
            jnp.asarray(tokens[slot_ids]),
            jnp.asarray(slot_ids.astype(np.int32)),
            snapshot(self.cache.lengths),
            jnp.asarray(chunk_lens[slot_ids]),
            *args,
            self.cache.k,
            self.cache.v,
            *scale_args,
            *self._adapter_slot_args(),
        )

        def call():
            # resolved inside the dispatch so a kernel fallback's
            # cleared cache re-traces with the dense attention core
            return self._chunk_fn((slot_ids.size, w))(*step_args)

        if self.paged:
            new_k, new_v, new_ks, new_vs, nxt, last = self._dispatch(
                "chunk", call
            )
            self.cache.commit(new_k, new_v, new_ks, new_vs)
        else:
            new_k, new_v, nxt, last = self._dispatch("chunk", call)
            self.cache.commit(new_k, new_v)
        # prompt rows are committed by construction — advance the
        # cursors now so the NEXT chunk step dispatches against them
        active = chunk_lens > 0
        self.cache.lengths[active] += chunk_lens[active]
        self.cache.begin_inflight()
        return InflightStep(
            kind="chunk",
            dispatch_t=time.perf_counter(),
            active=np.array(active, dtype=bool),
            lengths=lengths_snap,
            draft_lens=np.array(chunk_lens),
            device_next=nxt,
            device_logits=last,
        )

    def prefill_chunk_reconcile(
        self, step: InflightStep
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Block on a dispatched chunk step's device outputs and close
        its in-flight window. The device arrays are in compact-batch
        order; this scatters them back to slot-indexed (next_tokens
        [max_seqs], logits [max_seqs, V]) via the step's own active
        mask — rows for slots that were not chunking are zero. Only
        final-chunk rows carry meaning either way; the caller's cursor
        snapshot on the step record says which."""
        try:
            nxt_c = np.asarray(step.device_next)
            logits_c = np.asarray(step.device_logits)
        finally:
            self.cache.end_inflight()
        spec = self.cache.spec
        slot_ids = np.nonzero(step.active)[0]  # == dispatch's compaction
        nxt = np.zeros(spec.max_seqs, dtype=nxt_c.dtype)
        logits = np.zeros(
            (spec.max_seqs, logits_c.shape[-1]), dtype=logits_c.dtype
        )
        nxt[slot_ids] = nxt_c
        logits[slot_ids] = logits_c
        return nxt, logits

    def prefill_chunk(
        self,
        params,
        tokens: np.ndarray,
        chunk_lens: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Synchronous chunk step (dispatch + immediate reconcile)."""
        return self.prefill_chunk_reconcile(
            self.prefill_chunk_dispatch(params, tokens, chunk_lens)
        )
