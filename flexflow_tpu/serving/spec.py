"""Speculative decoding (SpecInfer, Miao et al., ASPLOS 2024).

Decode is weight-bandwidth-bound (`CostModel.decode_op_cost`): every
generated token re-reads the whole weight set for ONE token of progress.
Speculative decoding buys more tokens per weight read — a cheap *draft*
proposes k continuation tokens, the target model scores all k+1
positions in one prefill-shaped **verify** call
(`GenerationEngine.verify`), and an acceptance rule keeps the longest
prefix the target agrees with plus one bonus token from the target's own
distribution. Greedy acceptance is exact-match, so greedy speculative
decode is token-for-token identical to plain greedy decode — the draft
only changes WHEN tokens arrive, never WHICH; under temperature the
rejection-sampling rule preserves the target distribution the same way.

Two draft sources implement the `DraftProposer` protocol:

* `NGramDraftProposer` — weight-free prompt-lookup (the "assisted
  generation" n-gram trick): find the most recent earlier occurrence of
  the sequence's trailing n-gram and propose what followed it. Free to
  run, surprisingly effective on repetitive continuations, and the CI
  preset (no second model to build).
* `ModelDraftProposer` — SpecInfer's small-model draft: a second
  compiled `build_decoder_lm` with its OWN KVCache + GenerationEngine,
  kept slot-aligned with the target (`KVCache.claim`) and rolled back
  with the same `truncate` API the target uses. The draft always
  decodes greedily, so its proposal is a point mass and the same
  acceptance rule covers both proposers.

Rollback is the cache-side half of the protocol: verify writes K/V rows
for ALL k+1 positions; `cache.truncate(slot, new_len)` then commits the
accepted prefix — the slot layout just moves the visible length (stale
rows are masked), the paged layout also returns the pages past the
accepted length to the free pool under the admission-reserve accounting.

The scheduler side lives in serving/scheduler.py (`proposer=`/`spec_k=`
on either scheduler class); `optimize_spec_k` (search/auto.py) picks k
from a measured acceptance rate via `CostModel.verify_op_cost`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


# -- acceptance --------------------------------------------------------------


def _rng(seed: int, slot: int, pos: int, sub: int) -> np.random.Generator:
    """Deterministic per-(seed, slot, position, draw) stream — the host
    mirror of the engine's fold_in(fold_in(key, slot), pos) discipline,
    so rejection sampling is reproducible and independent of batch
    composition."""
    return np.random.default_rng([seed & 0x7FFFFFFF, slot, pos, sub])


def _softmax(row: np.ndarray) -> np.ndarray:
    row = row.astype(np.float64)
    row = row - row.max()
    e = np.exp(row)
    return e / e.sum()


def accept_drafts(
    row_logits: np.ndarray,
    drafts: Sequence[int],
    temperature: float = 0.0,
    seed: int = 0,
    slot: int = 0,
    base_len: int = 0,
) -> Tuple[int, List[int]]:
    """Acceptance rule for one slot's verify output. row_logits
    [w >= len(drafts)+1, vocab] — row j is the target's distribution for
    the token following verify input j (input 0 is the last emitted
    token, inputs 1.. are the drafts). Returns (accepted, emitted):
    `accepted` drafts survive and `emitted` is drafts[:accepted] plus
    ONE token from the target itself (the correction at the first
    rejection, or the bonus after a full accept) — so every verify emits
    at least one token and plain decode is the drafts=[] special case.

    temperature 0: greedy exact-match (argmax), which makes speculative
    greedy decode token-identical to plain greedy decode. temperature >
    0: rejection sampling against the point-mass proposal both proposers
    emit (draft q is a delta): accept d with probability p(d); on
    rejection resample from p with d zeroed out (= norm(max(0, p - q)))
    — the Leviathan/Chen rule, which preserves the target distribution.
    base_len is the cache position of the last emitted token; it seeds
    the per-position RNG streams."""
    k = len(drafts)
    if temperature <= 0.0:
        preds = np.argmax(row_logits[: k + 1], axis=-1)
        accepted = 0
        while accepted < k and int(drafts[accepted]) == int(preds[accepted]):
            accepted += 1
        return accepted, [int(t) for t in drafts[:accepted]] + [
            int(preds[accepted])
        ]
    emitted: List[int] = []
    for i in range(k):
        p = _softmax(row_logits[i] / temperature)
        d = int(drafts[i])
        # position the decided token will occupy: base_len + 1 + i
        u = _rng(seed, slot, base_len + 1 + i, 0).random()
        if u <= p[d]:
            emitted.append(d)
            continue
        residual = p.copy()
        residual[d] = 0.0
        total = residual.sum()
        if total <= 0.0:  # p was a delta at d — accept after all
            emitted.append(d)
            continue
        t = int(
            _rng(seed, slot, base_len + 1 + i, 1).choice(
                residual.size, p=residual / total
            )
        )
        emitted.append(t)
        return i, emitted
    p = _softmax(row_logits[k] / temperature)
    t = int(_rng(seed, slot, base_len + 1 + k, 0).choice(p.size, p=p))
    emitted.append(t)
    return k, emitted


# -- token trees (SpecInfer tree-verify) --------------------------------------


@dataclasses.dataclass
class DraftTree:
    """One slot's branching draft: a token tree rooted at the LAST
    EMITTED token (the root is implicit — it is verify row 0 and never
    appears in the node lists). tokens[i] is node i's token; parents[i]
    is its parent NODE index, -1 for children of the root. Nodes are
    topologically ordered (every parent index < its child's index) —
    `from_chains` builds them that way, and the verify mask
    (ops/attention.tree_ancestor_matrix), the acceptance walk, and the
    truncate compaction all rely on it. Node i occupies verify row
    1 + i and cache position lengths[slot] + 1 + i during the verify.

    A single chain (parents == [-1, 0, 1, ...]) is the degenerate tree
    the linear spec path already handles — schedulers route it through
    the existing staircase program so branch-1 trees stay bit-identical
    to linear speculative decoding."""

    tokens: List[int]
    parents: List[int]

    def __post_init__(self):
        if len(self.tokens) != len(self.parents):
            raise ValueError("tokens and parents must have equal length")
        for i, p in enumerate(self.parents):
            if not -1 <= p < i:
                raise ValueError(
                    f"node {i}: parent {p} breaks topological order"
                )

    @classmethod
    def from_chains(cls, chains: Sequence[Sequence[int]]) -> "DraftTree":
        """Trie-merge candidate chains, deduping shared prefixes: two
        chains agreeing on their first j tokens share j nodes and
        branch at the divergence — the dedup that makes a tree cheaper
        to verify than its chains separately. Chain order is
        deterministic (first chain's nodes come first), so the same
        chains always produce the same tree."""
        tokens: List[int] = []
        parents: List[int] = []
        kids: Dict[int, Dict[int, int]] = {}
        for chain in chains:
            cur = -1
            for t in chain:
                t = int(t)
                node = kids.setdefault(cur, {}).get(t)
                if node is None:
                    node = len(tokens)
                    tokens.append(t)
                    parents.append(cur)
                    kids[cur][t] = node
                cur = node
        return cls(tokens, parents)

    @property
    def nodes(self) -> int:
        return len(self.tokens)

    def depth(self) -> int:
        """Longest root-to-leaf path in nodes (the linear-k
        equivalent: a chain of k drafts has depth k)."""
        best = 0
        d = [0] * len(self.tokens)
        for i, p in enumerate(self.parents):
            d[i] = 1 if p < 0 else d[p] + 1
            best = max(best, d[i])
        return best

    def children(self, node: int) -> List[int]:
        """Child node indices of `node` (-1 = the root), in proposal
        order — the acceptance walk's candidate order, which is what
        keeps branch-1 trees draw-for-draw identical to the linear
        rejection-sampling path."""
        return [i for i, p in enumerate(self.parents) if p == node]

    def is_chain(self) -> bool:
        return all(p == i - 1 for i, p in enumerate(self.parents))

    def chains(self) -> List[List[int]]:
        """Root-to-leaf token paths (testing/debugging view)."""
        kids_of: Dict[int, List[int]] = {}
        for i, p in enumerate(self.parents):
            kids_of.setdefault(p, []).append(i)
        out: List[List[int]] = []

        def walk(node: int, path: List[int]) -> None:
            ks = kids_of.get(node, [])
            if not ks:
                out.append(path)
                return
            for c in ks:
                walk(c, path + [int(self.tokens[c])])

        walk(-1, [])
        return [p for p in out if p]

    def row_parents(self, w: Optional[int] = None) -> List[int]:
        """Per-VERIFY-ROW parent table of width `w` (>= 1 + nodes):
        row 0 is the root (-1), row 1 + i is node i, padding rows chain
        (parent j - 1) so their mask degenerates to the staircase. This
        is the [w] slice the engine stacks into the [max_seqs, w]
        tree_parents operand."""
        n = len(self.tokens)
        w = 1 + n if w is None else int(w)
        if w < 1 + n:
            raise ValueError(f"width {w} < 1 + {n} nodes")
        rows = [-1] + [0 if p < 0 else 1 + p for p in self.parents]
        rows += list(range(n, w - 1))  # chain padding: row j's parent j-1
        return rows

    def prune(
        self,
        max_nodes: Optional[int] = None,
        max_depth: Optional[int] = None,
    ) -> "DraftTree":
        """Drop nodes past a depth and/or node budget (token-budget and
        horizon caps at dispatch). Topological order means keeping a
        prefix of the node list keeps every survivor's parent, and the
        depth filter keeps ancestors by construction (depth(parent) <
        depth(child))."""
        d = [0] * len(self.tokens)
        for i, p in enumerate(self.parents):
            d[i] = 1 if p < 0 else d[p] + 1
        idx_map: Dict[int, int] = {}
        tokens: List[int] = []
        parents: List[int] = []
        for i, p in enumerate(self.parents):
            if max_nodes is not None and len(tokens) >= max_nodes:
                break
            if max_depth is not None and d[i] > max_depth:
                continue
            if p >= 0 and p not in idx_map:
                continue  # orphaned by the node cap
            idx_map[i] = len(tokens)
            tokens.append(int(self.tokens[i]))
            parents.append(-1 if p < 0 else idx_map[p])
        return DraftTree(tokens, parents)


def accept_tree(
    row_logits: np.ndarray,
    tree: DraftTree,
    temperature: float = 0.0,
    seed: int = 0,
    slot: int = 0,
    base_len: int = 0,
) -> Tuple[List[int], List[int]]:
    """Tree acceptance for one slot's verify output — the multi-branch
    generalization of accept_drafts. row_logits [w >= 1 + nodes, vocab]:
    row 0 is the target's distribution after the last emitted token,
    row 1 + i its distribution after node i's root-to-node path.
    Returns (path, emitted): `path` is the surviving root-to-leaf node
    index prefix (the rows truncate compacts into the cache) and
    `emitted` is its tokens plus ONE token from the target (the
    correction where the tree ran out of matching children, or the
    bonus at a fully-accepted leaf) — every verify emits at least one
    token, exactly like the linear rule.

    temperature 0: walk greedily — descend to the child whose token
    equals the argmax; the emitted stream is argmax-after-committed-
    prefix at every step, so greedy tree spec is token-identical to
    plain greedy decode. temperature > 0: multi-candidate rejection
    sampling (SpecInfer / Leviathan-Chen): at each node, candidates are
    tried in proposal order against the running residual r (initially
    p) — candidate c accepts with probability r[c]/sum(r), a rejection
    zeroes r[c] — and if all candidates reject, the correction samples
    from the final residual. With one candidate this is draw-for-draw
    the accept_drafts rule (same per-(seed, slot, position) RNG
    streams: sub 0 for the first candidate, 1 for the correction,
    2+ordinal for later candidates, and the leaf bonus reuses sub 0 at
    the one-past-leaf position, exactly like the linear bonus), so
    branch-1 trees reproduce linear spec decoding bit-for-bit."""
    if temperature <= 0.0:
        path: List[int] = []
        emitted: List[int] = []
        cur = -1
        while True:
            row = 0 if cur < 0 else 1 + cur
            pred = int(np.argmax(row_logits[row]))
            emitted.append(pred)
            nxt = None
            for c in tree.children(cur):
                if int(tree.tokens[c]) == pred:
                    nxt = c
                    break
            if nxt is None:
                return path, emitted
            path.append(nxt)
            cur = nxt
    path = []
    emitted = []
    cur = -1
    depth = 0
    while True:
        row = 0 if cur < 0 else 1 + cur
        # position the decided token will occupy: base_len + 1 + depth
        pos = base_len + 1 + depth
        p = _softmax(row_logits[row] / temperature)
        kids = tree.children(cur)
        if not kids:  # fully-accepted leaf: bonus from the target
            t = int(_rng(seed, slot, pos, 0).choice(p.size, p=p))
            emitted.append(t)
            return path, emitted
        residual = p.copy()
        accepted_node = None
        for ordinal, c in enumerate(kids):
            d = int(tree.tokens[c])
            total = residual.sum()
            if total <= 0.0:  # p was a delta on rejected candidates
                accepted_node = c
                break
            u = _rng(
                seed, slot, pos, 0 if ordinal == 0 else 2 + ordinal
            ).random()
            # ordinal 0 compares against p[d] itself (total == 1), the
            # EXACT comparison accept_drafts makes — not p[d]/sum(p),
            # whose float64 rounding could flip a knife-edge draw
            thresh = residual[d] if ordinal == 0 else residual[d] / total
            if u <= thresh:
                accepted_node = c
                break
            residual[d] = 0.0
        if accepted_node is None:
            total = residual.sum()
            if total <= 0.0:  # delta at the last rejected candidate
                accepted_node = kids[-1]
            else:
                t = int(
                    _rng(seed, slot, pos, 1).choice(
                        residual.size, p=residual / total
                    )
                )
                emitted.append(t)
                return path, emitted
        path.append(accepted_node)
        emitted.append(int(tree.tokens[accepted_node]))
        cur = accepted_node
        depth += 1


# -- draft proposers ----------------------------------------------------------


class DraftProposer:
    """Protocol for draft sources. `propose` maps running slots to draft
    token lists (up to k each; shorter or empty is fine — the verify
    degrades to plain decode). The lifecycle hooks exist for proposers
    with their own cache state (ModelDraftProposer); the base
    implementations are no-ops so stateless proposers only implement
    propose(). `retire` fires for EVERY slot release — terminal
    statuses and preemptions alike (a preempted request re-enters via
    `admit` with its recompute history).

    `stateless` marks proposers whose drafts are a pure function of the
    token sequence they are shown — no per-slot cache to keep
    consistent. The async engine only pre-drafts (proposing for verify
    N+1 against N's PREDICTED outcome, while N is still in flight) on
    stateless proposers, through `propose_sequences`: a misprediction
    there costs nothing to roll back, where a stateful proposer would
    have fed phantom tokens into its draft cache."""

    stateless = False

    def telemetry_counters(self) -> Dict[str, int]:
        """Monotone proposer-side counters for the metrics registry
        (`serve_draft_*` series) — the per-iteration sampler mirrors
        them via set_monotonic, so a proposer only needs to keep plain
        int ledgers. Base: nothing to report."""
        return {}

    def admit(self, requests: Sequence) -> None:  # pragma: no cover
        pass

    def retire(self, request) -> None:  # pragma: no cover
        pass

    def rollback(self, slot: int, new_len: int) -> None:  # pragma: no cover
        pass

    def propose(self, running: Dict[int, object], k: int) -> Dict[int, List[int]]:
        raise NotImplementedError

    def propose_trees(
        self, running: Dict[int, object], k: int, branch: int
    ) -> Dict[int, DraftTree]:
        """Branching drafts for tree verification: up to `branch`
        candidate chains of up to k tokens per slot, deduped on shared
        prefixes into one DraftTree. The base implementation wraps
        propose() — a single chain IS the branch == 1 tree — so every
        proposer supports tree mode; proposers with a real notion of
        alternates override it to emit wider trees."""
        out: Dict[int, DraftTree] = {}
        for slot, drafts in self.propose(running, k).items():
            tree = DraftTree.from_chains([drafts])
            if tree.nodes:
                out[slot] = tree
        return out

    def propose_sequences(
        self, seqs: Dict[int, List[int]], k: int
    ) -> Dict[int, List[int]]:
        """Draft up to k continuation tokens for explicit token
        sequences (slot -> prompt+generated+predicted history) instead
        of live Request state. Stateless proposers implement this; the
        default refuses so stateful proposers are never pre-drafted."""
        raise NotImplementedError(
            "propose_sequences is only available on stateless proposers"
        )


class NGramDraftProposer(DraftProposer):
    """Weight-free prompt-lookup draft: propose the continuation that
    followed the most recent earlier occurrence of the sequence's
    trailing `n`-gram (prompt + generated so far). Repetitive text —
    code, structured output, or a greedy model that has entered a cycle
    — yields near-1 acceptance for zero draft cost; novel text yields no
    match and the iteration degrades to plain decode. `max_history`
    bounds the backward scan so long sequences stay O(max_history)."""

    stateless = True

    def __init__(self, n: int = 2, max_history: int = 4096):
        if n < 1:
            raise ValueError("n-gram size must be >= 1")
        self.n = int(n)
        self.max_history = int(max_history)
        # telemetry ledgers: lookups attempted vs lookups that found a
        # continuation — the hit rate is the "is prompt-lookup even
        # firing on this workload" signal, upstream of acceptance
        self.lookups = 0
        self.lookup_hits = 0

    def telemetry_counters(self) -> Dict[str, int]:
        return {
            "serve_draft_lookups_total": self.lookups,
            "serve_draft_lookup_hits_total": self.lookup_hits,
        }

    def _lookup(self, seq: List[int], k: int) -> List[int]:
        if len(seq) > self.max_history:
            seq = seq[-self.max_history :]
        n = self.n
        if len(seq) <= n:
            return []
        tail = seq[-n:]
        # most recent earlier occurrence wins (locality: loops and
        # copied spans repeat their NEAREST context)
        for i in range(len(seq) - n - 1, -1, -1):
            if seq[i : i + n] == tail:
                return [int(t) for t in seq[i + n : i + n + k]]
        return []

    def _lookup_chains(
        self, seq: List[int], k: int, branch: int
    ) -> List[List[int]]:
        """Up to `branch` DISTINCT continuations from distinct earlier
        occurrences of the trailing n-gram, most recent first — the
        first chain is exactly what _lookup returns, so branch == 1
        tree proposals match linear proposals chain-for-chain. Distinct
        matches that disagree early give the tree its branches; matches
        that agree merge in DraftTree.from_chains."""
        if len(seq) > self.max_history:
            seq = seq[-self.max_history :]
        n = self.n
        if len(seq) <= n:
            return []
        tail = seq[-n:]
        chains: List[List[int]] = []
        for i in range(len(seq) - n - 1, -1, -1):
            if seq[i : i + n] == tail:
                cont = [int(t) for t in seq[i + n : i + n + k]]
                if cont and cont not in chains:
                    chains.append(cont)
                if len(chains) >= branch:
                    break
        return chains

    def propose_trees(
        self, running, k: int, branch: int
    ) -> Dict[int, DraftTree]:
        return self.propose_tree_sequences(
            {
                slot: list(req.prompt) + list(req.generated)
                for slot, req in running.items()
            },
            k,
            branch,
        )

    def propose_tree_sequences(
        self, seqs: Dict[int, List[int]], k: int, branch: int
    ) -> Dict[int, DraftTree]:
        """Tree analog of propose_sequences (stateless, so usable for
        pre-proposal the same way)."""
        out: Dict[int, DraftTree] = {}
        for slot, seq in seqs.items():
            self.lookups += 1
            chains = self._lookup_chains(list(seq), k, branch)
            if chains:
                self.lookup_hits += 1
                out[slot] = DraftTree.from_chains(chains)
        return out

    def propose(self, running, k: int) -> Dict[int, List[int]]:
        return self.propose_sequences(
            {
                slot: list(req.prompt) + list(req.generated)
                for slot, req in running.items()
            },
            k,
        )

    def propose_sequences(
        self, seqs: Dict[int, List[int]], k: int
    ) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {}
        for slot, seq in seqs.items():
            self.lookups += 1
            cont = self._lookup(list(seq), k)
            if cont:
                self.lookup_hits += 1
                out[slot] = cont
        return out


class ModelDraftProposer(DraftProposer):
    """Small-model draft (SpecInfer's SSM): a second compiled decoder LM
    with its own slot-layout KVCache and GenerationEngine, slot-aligned
    with the target via `KVCache.claim`. Drafting is k greedy decode
    steps of the draft engine; between verify iterations the draft cache
    is rolled back to the target's accepted length with the same
    `truncate` call, and the next propose() replays whatever accepted
    tokens the draft cache is missing (catch-up feeds) before drafting
    fresh ones — so draft state always extends a prefix of the target's
    committed history, never a rejected branch.

    The draft model must share the target's vocabulary. The draft engine
    always runs greedily (temperature 0), making its proposal a point
    mass — the acceptance rule in accept_drafts covers point-mass
    proposals exactly."""

    def __init__(
        self,
        draft_model,
        max_seqs: int,
        max_len: int,
        buckets=None,
        decode_kernel: str = "auto",
    ):
        from flexflow_tpu.serving.engine import GenerationEngine
        from flexflow_tpu.serving.kv_cache import KVCache

        self.model = draft_model
        self.cache = KVCache.from_model(
            draft_model, max_seqs=max_seqs, max_len=max_len, buckets=buckets
        )
        # the draft's k decode steps live in the same memory-bound regime
        # as the target's — the Pallas decode-kernel toggle rides along
        self.engine = GenerationEngine(
            draft_model, self.cache, temperature=0.0,
            decode_kernel=decode_kernel,
        )
        self.params = draft_model.params
        # telemetry ledgers: draft-engine decode steps, split into
        # catch-up feeds (replaying tokens the target committed) vs
        # fresh draft tokens — the catch-up share is the price of a
        # rollback, invisible in acceptance_rate alone
        self.draft_steps = 0
        self.catchup_feeds = 0
        self.draft_tokens = 0

    def telemetry_counters(self) -> Dict[str, int]:
        return {
            "serve_draft_steps_total": self.draft_steps,
            "serve_draft_catchup_feeds_total": self.catchup_feeds,
            "serve_draft_tokens_total": self.draft_tokens,
        }

    # -- lifecycle -----------------------------------------------------------

    def admit(self, requests) -> None:
        """Mirror the target's admission: claim the SAME slot ids and
        prefill the draft cache with each request's committed history —
        the prompt, plus any tokens already generated when a preempted
        request re-admits for recompute (serving/scheduler.py); feeding
        them here in one prefill is the draft-side recompute that would
        otherwise replay token-by-token as catch-up feeds. The
        prefill's own next-token output is unused — drafts start from
        the target's last emitted token at the next propose()."""
        for req in requests:
            self.cache.claim(req.slot)
        self.engine.prefill(
            self.params,
            [list(r.prompt) + list(r.generated) for r in requests],
            [r.slot for r in requests],
        )

    def retire(self, request) -> None:
        self.cache.free(request.slot)

    def rollback(self, slot: int, new_len: int) -> None:
        """Keep the prefix of the draft cache that matches the target's
        committed history. The draft may hold FEWER positions than the
        target committed (full-accept: the last draft token was never
        written to the draft cache) — the gap is replayed as catch-up
        feeds in the next propose()."""
        self.cache.truncate(
            slot, min(int(new_len), int(self.cache.lengths[slot]))
        )

    # -- drafting ------------------------------------------------------------

    def propose(self, running, k: int) -> Dict[int, List[int]]:
        if not running or k < 1:
            return {}
        spec = self.cache.spec
        # per-slot feed script: first the committed tokens the draft
        # cache hasn't seen yet (always at least the last emitted token),
        # then the draft's own greedy continuations
        pending: Dict[int, List[int]] = {}
        drafts: Dict[int, List[int]] = {}
        for slot, req in running.items():
            hist = list(req.prompt) + list(req.generated)
            done = int(self.cache.lengths[slot])
            pending[slot] = [int(t) for t in hist[done:]]
            drafts[slot] = []
        while True:
            feeds: Dict[int, int] = {}
            for slot in running:
                if int(self.cache.lengths[slot]) >= spec.max_len:
                    continue  # draft cache horizon reached
                if pending[slot]:
                    feeds[slot] = pending[slot][0]
                elif drafts[slot] and len(drafts[slot]) < k:
                    feeds[slot] = drafts[slot][-1]
            if not feeds:
                break
            tokens = np.zeros(spec.max_seqs, dtype=np.int32)
            active = np.zeros(spec.max_seqs, dtype=bool)
            for slot, tok in feeds.items():
                tokens[slot] = tok
                active[slot] = True
            nxt, _ = self.engine.decode(self.params, tokens, active)
            self.draft_steps += 1
            for slot in feeds:
                if pending[slot]:
                    pending[slot].pop(0)
                    self.catchup_feeds += 1
                    if pending[slot]:
                        continue  # catch-up feed: prediction is known
                self.draft_tokens += 1
                drafts[slot].append(int(nxt[slot]))
        return {s: d for s, d in drafts.items() if d}

    def propose_trees(
        self, running, k: int, branch: int
    ) -> Dict[int, DraftTree]:
        """Tree drafts from the draft model: the greedy spine propose()
        would emit, plus up to branch - 1 single-node ALTERNATES at the
        root — the runners-up of the draft's first fresh distribution.
        Root alternates are where tree verification pays most (a
        mispredicted first token kills a whole linear chain), and they
        cost no extra draft decode steps: the alternate tokens fall out
        of the same logits row the spine's first token came from, and
        they never enter the draft cache (only the spine is fed back),
        so rollback stays the linear protocol."""
        if not running or k < 1:
            return {}
        spec = self.cache.spec
        pending: Dict[int, List[int]] = {}
        drafts: Dict[int, List[int]] = {}
        root_logits: Dict[int, np.ndarray] = {}
        for slot, req in running.items():
            hist = list(req.prompt) + list(req.generated)
            done = int(self.cache.lengths[slot])
            pending[slot] = [int(t) for t in hist[done:]]
            drafts[slot] = []
        while True:
            feeds: Dict[int, int] = {}
            for slot in running:
                if int(self.cache.lengths[slot]) >= spec.max_len:
                    continue
                if pending[slot]:
                    feeds[slot] = pending[slot][0]
                elif drafts[slot] and len(drafts[slot]) < k:
                    feeds[slot] = drafts[slot][-1]
            if not feeds:
                break
            tokens = np.zeros(spec.max_seqs, dtype=np.int32)
            active = np.zeros(spec.max_seqs, dtype=bool)
            for slot, tok in feeds.items():
                tokens[slot] = tok
                active[slot] = True
            nxt, logits = self.engine.decode(self.params, tokens, active)
            self.draft_steps += 1
            for slot in feeds:
                if pending[slot]:
                    pending[slot].pop(0)
                    self.catchup_feeds += 1
                    if pending[slot]:
                        continue
                self.draft_tokens += 1
                if not drafts[slot]:
                    root_logits[slot] = np.asarray(logits[slot])
                drafts[slot].append(int(nxt[slot]))
        out: Dict[int, DraftTree] = {}
        for slot, spine in drafts.items():
            if not spine:
                continue
            chains: List[List[int]] = [list(spine)]
            row = root_logits.get(slot)
            if row is not None and branch > 1:
                for t in np.argsort(row)[::-1]:
                    if len(chains) >= branch:
                        break
                    if int(t) != spine[0]:
                        chains.append([int(t)])
            out[slot] = DraftTree.from_chains(chains)
        return out
