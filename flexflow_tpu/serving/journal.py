"""Write-ahead request journal: durable serving past the process boundary.

The resilience contract below this file (scheduler/router/faults) stops
at the process: every injected fault retires one request or degrades one
path, but an engine-process crash loses every in-flight stream and its
committed tokens. Orca-style iteration-level scheduling is exactly what
makes recovery cheap — a request's restartable state between iterations
is just (prompt, committed tokens, cursor) — so this module journals
that state as it is created and rebuilds it after a crash:

* **submit records** — rid, client request-key, prompt, sampling/limit
  params, tenant/class/adapter — appended the moment the scheduler
  accepts (or strict=False-rejects) a request;
* **commit records** — the accepted token RUN per request per host
  sync, written at the reconcile grain: a fused multi-step window or a
  tree-verify batch journals its whole accepted run as one record, a
  plain decode one token — the journal's granularity is the engine's,
  not per-token;
* **terminal records** — final status + error, written by `_finalize`
  (the scheduler's only terminal transition) so no request can end
  without a durable verdict;
* **snapshot records** — an optional journal-referenced copy of a
  request's committed KV pages (`PagedKVCache.snapshot_swap`, the
  non-destructive sibling of `export_swap`), letting recovery restore
  KV over the swap-in path instead of recomputing when the cost model
  prices the copy under the recompute.

Framing is torn-tail-tolerant by construction: one record per line,
`<crc32 hex> <json>\\n`. A crash mid-append leaves at most one partial
final line; the reader verifies each line's CRC and JSON and drops ONLY
a broken LAST line (counted as torn) — a broken interior line is real
corruption and raises. fsync policy (`--journal-fsync`):

* ``commit`` — flush + fsync after every record (durability per event);
* ``batch`` — flush + fsync once per host sync (the default: one
  fsync per reconcile, the same grain the commits are batched at);
* ``off`` — flush to the OS per host sync, never fsync (survives a
  process crash, not a host power loss).

**Journal-before-publish** (fxlint FX111): the only writer of a
request's stream-visible token list (`Request.generated`) is the
scheduler's `_emit`, which notes each token here BEFORE the front
door's published-cursor diff can observe it; the journal flush runs
inside `scheduler.step()`, the publish after it returns. A token a
client saw is therefore always a token the journal recorded, which is
what makes the restart contract exact: deterministic greedy decode
re-derives everything past the committed cursor, the published-cursor
dedup in frontend/server.py replays everything before it, and the
client sees no duplicates and no gaps.

A journal WRITE failure (disk full, injected `journal_fail` fault)
degrades, never kills: the journal marks itself degraded, stops
appending, and serving continues undurable — availability over
durability, with the degradation visible in `degraded_reason`.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "JournalCorrupt",
    "RequestJournal",
    "read_journal",
    "RecoveredRequest",
    "RecoveryState",
    "recover_journal",
    "readmit",
    "encode_swap_record",
    "decode_swap_record",
    "FSYNC_MODES",
]

FSYNC_MODES = ("commit", "batch", "off")


class JournalCorrupt(ValueError):
    """An INTERIOR journal record failed its CRC or JSON parse — not a
    torn tail (which the reader tolerates) but real corruption."""


# -- KV snapshot (de)serialization --------------------------------------------


def _enc_array(a: np.ndarray) -> Dict[str, object]:
    a = np.ascontiguousarray(a)
    return {
        "b": base64.b64encode(a.tobytes()).decode("ascii"),
        "dtype": str(a.dtype),
        "shape": list(a.shape),
    }


def _dec_array(d: Dict[str, object]) -> np.ndarray:
    buf = base64.b64decode(d["b"])
    return np.frombuffer(buf, dtype=np.dtype(d["dtype"])).reshape(
        [int(s) for s in d["shape"]]
    )


def encode_swap_record(rec: Dict[str, object]) -> Dict[str, object]:
    """JSON-encodable form of a `snapshot_swap`/`export_swap` record:
    the per-layer numpy pools become base64 blobs keyed by stringified
    layer guid; scalars and the geometry fingerprint pass through."""
    out: Dict[str, object] = {}
    for pool in ("k", "v", "k_scale", "v_scale"):
        out[pool] = {
            str(g): _enc_array(np.asarray(a)) for g, a in rec[pool].items()
        }
    for key in ("length", "pages", "bytes", "gen_len"):
        if key in rec:
            out[key] = int(rec[key])
    fp = rec.get("fingerprint")
    if fp is not None:
        out["fingerprint"] = [list(fp[0])] + [fp[1], fp[2], fp[3], fp[4]]
    return out


def decode_swap_record(doc: Dict[str, object]) -> Dict[str, object]:
    """Inverse of `encode_swap_record`, restoring the exact record
    shape `PagedKVCache.import_swap` validates (tuple fingerprint,
    int-guid-keyed numpy pools)."""
    rec: Dict[str, object] = {}
    for pool in ("k", "v", "k_scale", "v_scale"):
        rec[pool] = {
            int(g): _dec_array(d) for g, d in doc.get(pool, {}).items()
        }
    for key in ("length", "pages", "bytes", "gen_len"):
        if key in doc:
            rec[key] = int(doc[key])
    fp = doc.get("fingerprint")
    if fp is not None:
        rec["fingerprint"] = (
            tuple(fp[0]),
            int(fp[1]),
            int(fp[2]),
            int(fp[3]),
            str(fp[4]),
        )
    return rec


# -- the journal --------------------------------------------------------------


def _frame(payload: Dict[str, object]) -> bytes:
    body = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x} {body}\n".encode("utf-8")


def _unframe(line: bytes) -> Optional[Dict[str, object]]:
    """Decoded payload, or None when the line is broken (torn or
    corrupt — the caller decides which by position)."""
    try:
        text = line.decode("utf-8")
        crc_hex, body = text.split(" ", 1)
        body = body.rstrip("\n")
        if len(crc_hex) != 8:
            return None
        if int(crc_hex, 16) != (zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF):
            return None
        doc = json.loads(body)
        return doc if isinstance(doc, dict) else None
    except (ValueError, UnicodeDecodeError):
        return None


class RequestJournal:
    """Append-only write-ahead journal over one file. The scheduler is
    the writer: `submitted` at admission-queue entry, `note` per emitted
    token (buffered), `commit_pending` once per host sync (one commit
    record per request with fresh tokens), `finalize` at the terminal
    transition, `snapshot` when a KV snapshot is taken. A front door
    reads it back with `recover_journal` after a crash.

    `injector` threads the chaos harness's `maybe_journal_fail` through
    every append; `registry` (a telemetry.MetricsRegistry) keeps the
    `serve_journal_bytes` gauge current."""

    def __init__(
        self,
        path: str,
        fsync: str = "batch",
        injector=None,
        registry=None,
    ):
        if fsync not in FSYNC_MODES:
            raise ValueError(
                f"journal fsync must be one of {FSYNC_MODES}, got {fsync!r}"
            )
        self.path = str(path)
        self.fsync = fsync
        self.injector = injector
        self._f = open(self.path, "ab")
        self.bytes_written = int(self._f.tell())
        self.records_written = 0
        self.degraded = False
        self.degraded_reason: Optional[str] = None
        # rid -> tokens emitted since that rid's last commit record
        self._pending: Dict[int, List[int]] = {}
        self._gauge = None
        if registry is not None:
            # pre-create the whole durability catalog, not just our
            # gauge: recovery metrics are read AFTER a crash, when an
            # absent series is indistinguishable from a zero one
            from flexflow_tpu.telemetry.registry import (
                register_durability_metrics,
            )

            register_durability_metrics(registry)
            self._gauge = registry.gauge("serve_journal_bytes")
            self._gauge.set(self.bytes_written)

    # -- write path ----------------------------------------------------------

    def _append(self, payload: Dict[str, object]) -> bool:
        """One framed record. Returns False (and enters degraded mode)
        on an injected or real write failure — the serving path never
        raises out of a journal append."""
        if self.degraded:
            return False
        fail = getattr(self.injector, "maybe_journal_fail", None)
        if fail is not None and fail():
            self._degrade("injected journal write failure")
            return False
        try:
            data = _frame(payload)
            self._f.write(data)
            if self.fsync == "commit":
                self._f.flush()
                os.fsync(self._f.fileno())
        except OSError as e:
            self._degrade(f"journal write failed: {e!r}")
            return False
        self.bytes_written += len(data)
        self.records_written += 1
        if self._gauge is not None:
            self._gauge.set(self.bytes_written)
        return True

    def _degrade(self, reason: str) -> None:
        self.degraded = True
        self.degraded_reason = reason
        self._pending.clear()

    def _sync(self) -> None:
        """Batch-grain durability point (one per host sync)."""
        if self.degraded:
            return
        try:
            self._f.flush()
            if self.fsync == "batch":
                os.fsync(self._f.fileno())
        except OSError as e:
            self._degrade(f"journal flush failed: {e!r}")

    def submitted(self, req) -> None:
        """Submit record: everything a restart needs to rebuild and
        re-validate the request, including the client request-key the
        idempotent-resubmission dedup matches on."""
        self._append(
            {
                "type": "submit",
                "rid": int(req.rid),
                "key": getattr(req, "request_key", None),
                "prompt": [int(t) for t in req.prompt],
                "max_new_tokens": int(req.max_new_tokens),
                "eos_token": (
                    int(req.eos_token) if req.eos_token is not None else None
                ),
                "deadline_s": (
                    float(req.deadline_s)
                    if req.deadline_s is not None
                    else None
                ),
                "tenant": req.tenant,
                "cls": req.priority_class,
                "adapter_id": int(req.adapter_id),
                # a RECOVERED request re-enters with its committed run
                # already in `generated`; carrying it in the new submit
                # record makes a second crash-recovery fold correctly
                # (the fresh submit would otherwise reset the cursor)
                "committed": [int(t) for t in req.generated],
            }
        )
        self._sync()

    def note(self, rid: int, token: int) -> None:
        """Buffer one committed token; `commit_pending` writes the run.
        Called by the scheduler's `_emit` — the blessed stream writer
        (fxlint FX111) — so every stream-visible token passes through
        here before the front door can publish it."""
        if self.degraded:
            return
        self._pending.setdefault(int(rid), []).append(int(token))

    def commit_pending(self, iteration: int) -> None:
        """One commit record per request with fresh tokens — the
        per-host-sync grain: a K-step fused window's or a tree-verify
        round's whole accepted run lands as one record."""
        if self.degraded or not self._pending:
            return
        # detach the batch first: a write failure mid-loop degrades the
        # journal (which clears `_pending`) — iterating the live dict
        # here would blow up instead of degrading gracefully
        pending, self._pending = self._pending, {}
        for rid in sorted(pending):
            run = pending[rid]
            if not run:
                continue
            if not self._append(
                {
                    "type": "commit",
                    "rid": rid,
                    "tokens": run,
                    "it": int(iteration),
                }
            ):
                return  # degraded: the rest of the batch is lost with it
        self._sync()

    def finalize(
        self,
        rid: int,
        status: str,
        error: Optional[str] = None,
        iteration: int = -1,
    ) -> None:
        """Terminal record, preceded by the rid's still-buffered commit
        run (a request must never end with published-but-unjournaled
        tokens)."""
        run = self._pending.pop(int(rid), None)
        if run:
            self._append(
                {
                    "type": "commit",
                    "rid": int(rid),
                    "tokens": run,
                    "it": int(iteration),
                }
            )
        self._append(
            {
                "type": "terminal",
                "rid": int(rid),
                "status": str(status),
                "error": error,
            }
        )
        self._sync()

    def snapshot(self, rid: int, record: Dict[str, object]) -> None:
        """Journal-referenced KV snapshot (from `snapshot_swap`): the
        latest one per rid wins at recovery, and is honored only when
        its `gen_len` still matches the committed run (commits past the
        snapshot make restoring it a double-decode — recompute wins)."""
        self._append(
            {
                "type": "snapshot",
                "rid": int(rid),
                "record": encode_swap_record(record),
            }
        )
        self._sync()

    def close(self) -> None:
        """Close the file WITHOUT flushing pending token runs: pending
        tokens at close time only exist mid-iteration (a crash path),
        and committing them here would fake a durability the crash
        didn't have — a graceful shutdown's pending buffer is empty
        because `_end_iteration` flushed it."""
        self._pending.clear()
        try:
            self._f.close()
        except OSError:
            pass


# -- read / recovery ----------------------------------------------------------


def read_journal(path: str) -> Tuple[List[Dict[str, object]], int]:
    """(records, torn): every valid record in order, plus how many
    trailing torn records were dropped (0 or 1 — the framing makes more
    than one impossible without interior corruption, which raises
    JournalCorrupt)."""
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    records: List[Dict[str, object]] = []
    for i, line in enumerate(lines):
        doc = _unframe(line + b"\n")
        if doc is None:
            if i == len(lines) - 1:
                return records, 1  # torn tail: drop only the torn record
            raise JournalCorrupt(
                f"{path}: corrupt interior record at line {i + 1}"
            )
        records.append(doc)
    return records, 0


@dataclasses.dataclass
class RecoveredRequest:
    """One live (non-terminal) request rebuilt from the journal: the
    recompute cursor is (prompt, committed); `snapshot` is the decoded
    KV record when one is usable."""

    rid: int
    key: Optional[str]
    prompt: List[int]
    max_new_tokens: int
    eos_token: Optional[int]
    deadline_s: Optional[float]
    tenant: str = ""
    priority_class: str = ""
    adapter_id: int = -1
    committed: List[int] = dataclasses.field(default_factory=list)
    snapshot: Optional[Dict[str, object]] = None

    @property
    def complete(self) -> bool:
        """The committed run already satisfies the request's stopping
        rule (crash after the last commit, before/without its terminal
        record) — re-admitting would emit a duplicate token."""
        if len(self.committed) >= self.max_new_tokens:
            return True
        return bool(
            self.committed
            and self.eos_token is not None
            and self.committed[-1] == self.eos_token
        )


@dataclasses.dataclass
class RecoveryState:
    """What a fresh front door / engine rebuilds from: the live set
    with recompute cursors, the terminal verdicts (for request-key
    dedup of retried submits), and the rid watermark."""

    live: Dict[int, RecoveredRequest]
    terminals: Dict[int, Dict[str, object]]  # rid -> {status,error,tokens,key}
    key_to_rid: Dict[str, int]
    next_rid: int
    torn: int
    records: int

    @property
    def replayed_tokens(self) -> int:
        return sum(len(r.committed) for r in self.live.values())


def recover_journal(path: str) -> RecoveryState:
    """Fold the journal into the live set: submits open requests,
    commits extend their committed runs, terminals close them (keeping
    status + tokens for dedup replay), snapshots attach the latest KV
    record. A torn tail drops only the torn record."""
    records, torn = read_journal(path)
    live: Dict[int, RecoveredRequest] = {}
    terminals: Dict[int, Dict[str, object]] = {}
    key_to_rid: Dict[str, int] = {}
    next_rid = 0
    for rec in records:
        rtype = rec.get("type")
        rid = int(rec.get("rid", -1))
        next_rid = max(next_rid, rid + 1)
        if rtype == "submit":
            live[rid] = RecoveredRequest(
                rid=rid,
                key=rec.get("key"),
                prompt=[int(t) for t in rec.get("prompt", ())],
                max_new_tokens=int(rec.get("max_new_tokens", 16)),
                eos_token=(
                    int(rec["eos_token"])
                    if rec.get("eos_token") is not None
                    else None
                ),
                deadline_s=rec.get("deadline_s"),
                tenant=rec.get("tenant", ""),
                priority_class=rec.get("cls", ""),
                adapter_id=int(rec.get("adapter_id", -1)),
                committed=[int(t) for t in rec.get("committed", ())],
            )
            if rec.get("key"):
                key_to_rid[str(rec["key"])] = rid
        elif rtype == "commit":
            rr = live.get(rid)
            if rr is not None:
                rr.committed.extend(int(t) for t in rec.get("tokens", ()))
        elif rtype == "terminal":
            rr = live.pop(rid, None)
            terminals[rid] = {
                "status": rec.get("status"),
                "error": rec.get("error"),
                "tokens": list(rr.committed) if rr is not None else [],
                "key": rr.key if rr is not None else None,
            }
        elif rtype == "snapshot":
            rr = live.get(rid)
            if rr is not None:
                rr.snapshot = decode_swap_record(rec.get("record", {}))
    return RecoveryState(
        live=live,
        terminals=terminals,
        key_to_rid=key_to_rid,
        next_rid=next_rid,
        torn=torn,
        records=len(records),
    )


def readmit(scheduler, state: RecoveryState, decider=None):
    """Re-admit the recovered live set into a fresh scheduler with
    recompute cursors: each request re-enters as (prompt, committed)
    — `_admit` recomputes exactly that history, and deterministic
    greedy decode makes the resumed stream token-identical from the
    cursor. When a request carries a usable KV snapshot and `decider`
    (a `(cache, record, resume_len) -> bool` from
    `api.build_restore_decider`; None = always restore) prices the
    copy under the recompute, the snapshot rides `import_swap` and the
    swap-in admission path restores it with NO re-prefill.

    Returns (resubmitted, completed): `completed` are requests whose
    committed run already satisfied their stopping rule — finalizing
    them through the scheduler would emit a duplicate token, so they
    come back terminal for the front door to replay."""
    from flexflow_tpu.serving.scheduler import Request, RequestStatus

    resubmitted = []
    completed = []
    cache = getattr(scheduler, "cache", None)
    for rid in sorted(state.live):
        rr = state.live[rid]
        req = Request(
            rid=rr.rid,
            prompt=list(rr.prompt),
            max_new_tokens=rr.max_new_tokens,
            eos_token=rr.eos_token,
            # the original deadline's clock died with the old process;
            # re-arming it fresh would silently extend it, so recovery
            # drops it — the operator's journal keeps the recorded value
            deadline_s=None,
            tenant=rr.tenant,
            priority_class=rr.priority_class,
            adapter_id=rr.adapter_id,
            request_key=rr.key,
            generated=list(rr.committed),
        )
        if rr.complete:
            req.status = RequestStatus.FINISHED
            completed.append(req)
            continue
        snap = rr.snapshot
        if (
            snap is not None
            and cache is not None
            and hasattr(cache, "import_swap")
            and int(snap.get("gen_len", -1)) == len(rr.committed)
        ):
            resume_len = len(rr.prompt) + len(rr.committed)
            try:
                use = decider is None or decider(cache, snap, resume_len)
                if use:
                    handle = cache.import_swap(dict(snap))
                    if handle is not None:
                        req.swap_handle = handle
            except ValueError:
                pass  # geometry mismatch: the recompute path still works
        scheduler.submit(req, strict=False)
        resubmitted.append(req)
    return resubmitted, completed
