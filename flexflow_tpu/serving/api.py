"""User-facing serving surface: ServeConfig + generate().

`FFModel.generate` (runtime/model.py) delegates here, mirroring how the
reference grew FlexFlow Serve on top of the training FFModel. ServeConfig
rides FFConfig flag parsing (`--max-seqs`, `--max-seq-len`,
`--serve-scheduler`, `--eos-token`, `--spec-draft`, `--spec-k`), so
serving scripts configure the engine with the same CLI the training
examples use.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from flexflow_tpu.serving.engine import GenerationEngine
from flexflow_tpu.serving.kv_cache import KVCache, PagedKVCache
from flexflow_tpu.serving.scheduler import (
    AsyncContinuousBatchingScheduler,
    ContinuousBatchingScheduler,
    Request,
    StaticBatchingScheduler,
)

_SCHEDULERS = {
    "continuous": ContinuousBatchingScheduler,
    "static": StaticBatchingScheduler,
}

_SPEC_DRAFTS = ("", "ngram", "model")


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (reference: RequestManager configuration in FlexFlow
    Serve; Orca's max_batch_size / max_seq_len pair)."""

    max_seqs: int = 8  # KV-cache slots = max in-flight requests
    max_seq_len: int = 256  # max tokens per sequence (prompt + generation)
    scheduler: str = "continuous"  # "continuous" | "static"
    eos_token: Optional[int] = None
    temperature: float = 0.0  # 0 = greedy
    seed: int = 0
    prefill_buckets: Tuple[int, ...] = ()  # () = powers of two
    # KV-cache layout (PagedAttention, SOSP'23): "paged" pools pages and
    # routes them through block tables; "slot" is the PR-1 contiguous
    # [max_seqs, max_len] layout, kept as the equivalence/bench baseline.
    kv_layout: str = "paged"
    kv_page_size: int = 0  # 0 = auto (vLLM-style 16, halved to divide max_len)
    kv_pages: int = 0  # 0 = max_seqs * max_seq_len / page_size (same capacity)
    # K/V pool element type (--kv-dtype): "int8" quantizes both pools
    # (fp32 scale per page per head in side pools, dequant fused into
    # the per-chunk attention loop) for ~4x cache bytes; paged layout
    # only — the slot layout has no per-page scale granularity.
    kv_dtype: str = "fp32"
    # hashed prefix-page cache (--prefix-cache): admissions map full
    # pages whose chained content hash matches an already-resident
    # prefix (refcounted, copy-on-write on first divergent write)
    # instead of recomputing them; paged layout only — sharing is
    # page-aligned by construction.
    prefix_cache: bool = False
    # speculative decoding (SpecInfer, ASPLOS'24; serving/spec.py):
    # "" = off, "ngram" = weight-free prompt-lookup draft, "model" = a
    # second compiled decoder LM (pass it as build_scheduler/generate's
    # draft_model). spec_k is the draft length per verify step;
    # spec_ngram the lookup n-gram size.
    # spec_branch > 1 switches to token-TREE speculation: each verify
    # scores a deduped tree of up to spec_k * spec_branch draft nodes
    # (depth spec_k, spec_branch alternatives per level) and accepts
    # the longest surviving root-to-leaf path; 1 keeps the linear
    # chain path bit-for-bit.
    spec_draft: str = ""
    spec_k: int = 4
    spec_branch: int = 1
    spec_ngram: int = 2
    # chunked prefill (Sarathi-Serve; serving/scheduler.py):
    # token_budget > 0 caps each iteration's token work — prompts
    # stream into the cache in chunk_size-aligned chunks interleaved
    # with in-flight decodes instead of one monolithic admission
    # prefill (the head-of-line blocking fix). 0 = off. Requires the
    # continuous scheduler; auto.optimize_token_budget picks a budget
    # that meets slo_ttft_ms / slo_itl_ms from the cost model.
    token_budget: int = 0
    chunk_size: int = 16
    # decode/verify attention core (ops/pallas/decode_kernel.py):
    # "auto" = the Pallas flash-decode kernel on TPU when the geometry
    # supports() it (dense otherwise), "pallas" = force the kernel
    # (interpret mode off-TPU — the CI/parity path), "dense" = always
    # the jnp paths.
    decode_kernel: str = "auto"
    # admission policy for the paged layout (serving/scheduler.py):
    # "reserve" gates each admit on its worst-case page need on top of
    # every in-flight reservation (preemption-free); "optimistic"
    # admits on the pages needed NOW and answers later pool exhaustion
    # with preemption-by-recompute, bounded by max_preemptions per
    # request before hard FAILED. The slot layout ignores both.
    admission: str = "reserve"
    max_preemptions: int = 3
    # async double-buffered engine (--serve-async): overlap host
    # scheduling with device steps — dispatch step N+1 while N is in
    # flight, reconcile terminal events one step late
    # (AsyncContinuousBatchingScheduler). Continuous scheduler only;
    # the sync loop stays the token-identical reference.
    serve_async: bool = False
    # debug: re-run cache.check_invariants() after every scheduler
    # iteration (--check-invariants). Off by default — the full
    # allocator re-derivation is O(slots × pages) per iteration, a
    # debugging/CI posture rather than a serving one.
    debug_invariants: bool = False
    # telemetry (flexflow_tpu.telemetry): setting ANY of these attaches
    # a Telemetry bundle to the engine + scheduler. metrics_out writes
    # Prometheus text exposition at flush; metrics_jsonl streams one
    # sample row per scheduler iteration; trace writes a Chrome
    # trace-event JSON (Perfetto-loadable) of engine phases + request
    # lifecycles; slo_ttft_ms / slo_itl_ms (milliseconds, 0 = no
    # threshold) feed serve_slo_violations_total from rolling windows
    # of slo_window observations. `telemetry=True` force-enables the
    # in-memory bundle with no output paths (tests, embedding callers).
    metrics_out: str = ""
    metrics_jsonl: str = ""
    trace: str = ""
    slo_ttft_ms: float = 0.0
    slo_itl_ms: float = 0.0
    slo_window: int = 1024
    telemetry: bool = False
    # pod serving (serving/distributed.py): serve_mesh = "dp,tp" applies
    # a (data, model) serving mesh via FFModel.compile_for_serving;
    # serve_hosts > 0 partitions slots and the page pool across that
    # many host shards (0 = auto: jax.process_count(), else dp). The
    # multihost KV partition is paged-layout only — the slot layout has
    # no page pool to shard.
    serve_mesh: str = ""
    serve_hosts: int = 0
    # graceful degradation under pressure (serving/kv_cache.py +
    # scheduler.py). kv_swap (--kv-swap): a preemption victim's
    # committed pages are staged to host buffers and restored
    # page-for-page at re-admission — no re-prefill — whenever the cost
    # model prices the copy under the recompute; kv_swap_bytes
    # (--kv-swap-bytes) caps the host bytes held at once (0 =
    # unbounded). prefix_evict (--prefix-evict): "lru" lets published
    # prefix pages whose refcount is publication-only be reclaimed
    # (last-use LRU order) before any live request is preempted;
    # "cost" reclaims the page CHEAPEST to recompute instead (priced
    # by CostModel.prefill_chunk_cost over the page's token span —
    # deep chain tails stay warm); "none" retains them forever (the
    # pre-PR-14 behavior).
    kv_swap: bool = False
    kv_swap_bytes: int = 0
    prefix_evict: str = "none"
    # device-resident multi-step decode (--decode-multistep):
    # scheduler-invariant runs of decode iterations fuse into ONE
    # jitted lax.scan window of up to max_fused_steps
    # (--max-fused-steps) steps, reconciled in a single host sync —
    # token/logit-identical to step-at-a-time, ~K fewer host
    # round-trips per committed token on quiet stretches.
    decode_multistep: bool = False
    max_fused_steps: int = 8
    # multi-tenant serving (serving/tenancy/). adapters (--adapters):
    # > 0 attaches a paged multi-LoRA AdapterPool sized for that many
    # resident adapters of rank <= adapter_rank (--adapter-rank);
    # requests pick one via Request.adapter_id (-1 = base model,
    # bit-identical to serving without a pool). classes (--classes):
    # "name:weight[:ttft_ms[:itl_ms]]" entries, comma-separated — more
    # than one class switches admission + chunk grants to weighted-fair
    # deficit round-robin, preemption victims to class-priced cost, and
    # attaches per-class SLO monitors under {"class": name} labels.
    adapters: int = 0
    adapter_rank: int = 8
    classes: str = ""
    # durable serving (serving/journal.py). journal (--journal): path
    # of the append-only write-ahead request journal ("" = off) —
    # submit/commit/terminal records at the host-sync grain, the state
    # a crash-restart rebuilds token-identical streams from.
    # journal_fsync (--journal-fsync): "commit" fsyncs every record,
    # "batch" once per host sync (default), "off" flushes but never
    # fsyncs. journal_snapshot_every (--journal-snapshot-every): > 0
    # journals a KV snapshot of every running slot each N iterations
    # (paged layout), letting recovery restore KV over import_swap
    # instead of recomputing when build_restore_decider prices the
    # copy cheaper. door_max_pending (--door-max-pending): bounds the
    # front door's admission backlog; past it, per-class weighted-share
    # shedding refuses new streams with a retry_after hint (0 =
    # unbounded). breaker_threshold / breaker_cooldown
    # (--breaker-threshold / --breaker-cooldown): consecutive failed
    # health probes before a replica's circuit breaker opens, and the
    # router iterations it stays open before a half-open trial
    # placement (threshold 0 = breaker off).
    journal: str = ""
    journal_fsync: str = "batch"
    journal_snapshot_every: int = 0
    door_max_pending: int = 0
    breaker_threshold: int = 0
    breaker_cooldown: int = 8

    def __post_init__(self):
        if self.scheduler not in _SCHEDULERS:
            raise ValueError(
                f"scheduler must be one of {sorted(_SCHEDULERS)}, "
                f"got {self.scheduler!r}"
            )
        if self.max_seqs < 1 or self.max_seq_len < 2:
            raise ValueError("max_seqs >= 1 and max_seq_len >= 2 required")
        if self.serve_async and self.scheduler != "continuous":
            raise ValueError(
                "serve_async requires the continuous scheduler (the "
                "static baseline is deliberately synchronous)"
            )
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        if self.admission not in ("reserve", "optimistic"):
            raise ValueError(
                f"admission must be 'reserve' or 'optimistic', "
                f"got {self.admission!r}"
            )
        if self.max_preemptions < 0:
            raise ValueError("max_preemptions must be >= 0")
        if self.kv_layout not in ("paged", "slot"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'slot', got {self.kv_layout!r}"
            )
        if self.kv_page_size < 0 or self.kv_pages < 0:
            raise ValueError("kv_page_size and kv_pages must be >= 0")
        if self.kv_page_size and self.max_seq_len % self.kv_page_size:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} is not divisible by "
                f"kv_page_size {self.kv_page_size}"
            )
        if self.kv_dtype not in ("fp32", "int8"):
            raise ValueError(
                f"kv_dtype must be 'fp32' or 'int8', got {self.kv_dtype!r}"
            )
        if self.kv_dtype == "int8" and self.kv_layout != "paged":
            raise ValueError(
                "kv_dtype='int8' requires kv_layout='paged' (the scale "
                "side pools are per page per head)"
            )
        if self.prefix_cache and self.kv_layout != "paged":
            raise ValueError(
                "prefix_cache requires kv_layout='paged' (sharing is "
                "page-aligned: whole pages map through block tables)"
            )
        if self.spec_draft not in _SPEC_DRAFTS:
            raise ValueError(
                f"spec_draft must be one of {_SPEC_DRAFTS}, "
                f"got {self.spec_draft!r}"
            )
        if self.spec_draft and self.spec_k < 1:
            raise ValueError("spec_k must be >= 1 when spec_draft is set")
        if self.spec_branch < 1:
            raise ValueError(
                f"spec_branch must be >= 1, got {self.spec_branch}"
            )
        if self.spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        if self.token_budget < 0 or self.chunk_size < 1:
            raise ValueError(
                "token_budget must be >= 0 and chunk_size >= 1, got "
                f"token_budget={self.token_budget} "
                f"chunk_size={self.chunk_size}"
            )
        if self.token_budget:
            if self.scheduler != "continuous":
                raise ValueError(
                    "token_budget (chunked prefill) requires the "
                    "continuous scheduler"
                )
            if self.token_budget < self.chunk_size:
                raise ValueError(
                    f"token_budget {self.token_budget} < chunk_size "
                    f"{self.chunk_size}: an iteration could never fit "
                    f"one chunk"
                )
            # mirror decode_kernel.supports(): a kernel-active config
            # with a misaligned chunk width would route every chunk to
            # the dense fallback — reject it here, where the flag
            # surface can still tell the operator which knob to turn
            from flexflow_tpu.ops.pallas.decode_kernel import SUBLANES

            if self.decode_kernel != "dense" and self.chunk_size % SUBLANES:
                raise ValueError(
                    f"chunk_size {self.chunk_size} must be a multiple "
                    f"of {SUBLANES} when decode_kernel is "
                    f"{self.decode_kernel!r}"
                )
        from flexflow_tpu.ops.pallas.decode_kernel import MODES

        if self.decode_kernel not in MODES:
            raise ValueError(
                f"decode_kernel must be one of {MODES}, "
                f"got {self.decode_kernel!r}"
            )
        if self.slo_ttft_ms < 0 or self.slo_itl_ms < 0:
            raise ValueError("SLO thresholds must be >= 0 (0 = disabled)")
        if self.slo_window < 1:
            raise ValueError(
                f"slo_window must be >= 1, got {self.slo_window}"
            )
        if self.serve_hosts < 0:
            raise ValueError(
                f"serve_hosts must be >= 0 (0 = auto), got "
                f"{self.serve_hosts}"
            )
        if self.serve_hosts > 1 and self.kv_layout != "paged":
            raise ValueError(
                "multihost serving requires kv_layout='paged' (the host "
                "partition shards the page pool; the slot layout has no "
                "pool to shard)"
            )
        if self.serve_mesh:
            from flexflow_tpu.serving.distributed import parse_serve_mesh

            parse_serve_mesh(self.serve_mesh)  # raises on malformed text
        if self.kv_swap and self.kv_layout != "paged":
            raise ValueError(
                "kv_swap requires kv_layout='paged' (swap stages whole "
                "pages; the slot layout has none)"
            )
        if self.kv_swap_bytes < 0:
            raise ValueError(
                f"kv_swap_bytes must be >= 0 (0 = unbounded), got "
                f"{self.kv_swap_bytes}"
            )
        if self.prefix_evict not in ("none", "lru", "cost"):
            raise ValueError(
                f"prefix_evict must be 'none', 'lru', or 'cost', got "
                f"{self.prefix_evict!r}"
            )
        if self.prefix_evict != "none" and not self.prefix_cache:
            raise ValueError(
                "prefix_evict needs prefix_cache=True (only published "
                "prefix pages are ever evictable)"
            )
        if self.max_fused_steps < 1:
            raise ValueError(
                f"max_fused_steps must be >= 1, got "
                f"{self.max_fused_steps}"
            )
        if self.decode_multistep and self.scheduler == "static":
            raise ValueError(
                "decode_multistep requires the continuous scheduler "
                "(the static baseline is the reference the fused loop "
                "is proved identical against)"
            )
        if self.adapters < 0:
            raise ValueError(
                f"adapters must be >= 0 (0 = no pool), got {self.adapters}"
            )
        if self.adapters and self.adapter_rank < 1:
            raise ValueError(
                f"adapter_rank must be >= 1, got {self.adapter_rank}"
            )
        if self.classes:
            from flexflow_tpu.serving.tenancy.fairness import parse_classes

            parse_classes(self.classes)  # raises on malformed text
        from flexflow_tpu.serving.journal import FSYNC_MODES

        if self.journal_fsync not in FSYNC_MODES:
            raise ValueError(
                f"journal_fsync must be one of {FSYNC_MODES}, "
                f"got {self.journal_fsync!r}"
            )
        if self.journal_snapshot_every < 0:
            raise ValueError(
                f"journal_snapshot_every must be >= 0 (0 = off), got "
                f"{self.journal_snapshot_every}"
            )
        if self.journal_snapshot_every and self.kv_layout != "paged":
            raise ValueError(
                "journal_snapshot_every requires kv_layout='paged' "
                "(snapshots ride snapshot_swap, which stages whole pages)"
            )
        if self.door_max_pending < 0:
            raise ValueError(
                f"door_max_pending must be >= 0 (0 = unbounded), got "
                f"{self.door_max_pending}"
            )
        if self.breaker_threshold < 0:
            raise ValueError(
                f"breaker_threshold must be >= 0 (0 = breaker off), got "
                f"{self.breaker_threshold}"
            )
        if self.breaker_cooldown < 1:
            raise ValueError(
                f"breaker_cooldown must be >= 1, got "
                f"{self.breaker_cooldown}"
            )

    @property
    def telemetry_requested(self) -> bool:
        """True when any telemetry knob asks for the bundle."""
        return bool(
            self.telemetry
            or self.metrics_out
            or self.metrics_jsonl
            or self.trace
            or self.slo_ttft_ms
            or self.slo_itl_ms
        )

    @staticmethod
    def from_config(cfg) -> "ServeConfig":
        """Lift the serve_* fields FFConfig.parse_args fills."""
        return ServeConfig(
            max_seqs=cfg.serve_max_seqs,
            max_seq_len=cfg.serve_max_seq_len,
            scheduler=cfg.serve_scheduler,
            eos_token=(
                cfg.serve_eos_token if cfg.serve_eos_token >= 0 else None
            ),
            seed=cfg.seed,
            kv_layout=cfg.serve_kv_layout,
            kv_page_size=cfg.serve_kv_page_size,
            kv_pages=cfg.serve_kv_pages,
            kv_dtype=cfg.serve_kv_dtype,
            prefix_cache=cfg.serve_prefix_cache,
            spec_draft=cfg.serve_spec_draft,
            spec_k=cfg.serve_spec_k,
            spec_branch=cfg.serve_spec_branch,
            token_budget=cfg.serve_token_budget,
            chunk_size=cfg.serve_chunk_size,
            decode_kernel=cfg.serve_decode_kernel,
            admission=cfg.serve_admission,
            max_preemptions=cfg.serve_max_preemptions,
            serve_async=cfg.serve_async,
            debug_invariants=cfg.serve_check_invariants,
            metrics_out=cfg.serve_metrics_out,
            metrics_jsonl=cfg.serve_metrics_jsonl,
            trace=cfg.serve_trace,
            slo_ttft_ms=cfg.serve_slo_ttft_ms,
            slo_itl_ms=cfg.serve_slo_itl_ms,
            telemetry=cfg.serve_telemetry,
            serve_mesh=cfg.serve_mesh,
            serve_hosts=cfg.serve_hosts,
            kv_swap=cfg.serve_kv_swap,
            kv_swap_bytes=cfg.serve_kv_swap_bytes,
            prefix_evict=cfg.serve_prefix_evict,
            decode_multistep=cfg.serve_decode_multistep,
            max_fused_steps=cfg.serve_max_fused_steps,
            adapters=cfg.serve_adapters,
            adapter_rank=cfg.serve_adapter_rank,
            classes=cfg.serve_classes,
            journal=cfg.serve_journal,
            journal_fsync=cfg.serve_journal_fsync,
            journal_snapshot_every=cfg.serve_journal_snapshot_every,
            door_max_pending=cfg.serve_door_max_pending,
            breaker_threshold=cfg.serve_breaker_threshold,
            breaker_cooldown=cfg.serve_breaker_cooldown,
        )


def build_telemetry(serve: ServeConfig):
    """The Telemetry bundle a ServeConfig asks for, or None when every
    telemetry knob is off — the scheduler/engine then skip every
    instrument point on a single predicate (the ≤2%-overhead contract
    bench_serve.py --telemetry gates). Thin wrapper over the generic
    telemetry.build_telemetry, which also accepts an FFConfig or plain
    kwargs (the training/search entry points use it directly)."""
    from flexflow_tpu.telemetry import build_telemetry as _build

    return _build(serve)


def build_proposer(serve: ServeConfig, draft_model=None):
    """The DraftProposer a ServeConfig asks for (None when spec decoding
    is off). A "model" draft needs a second compiled decoder LM sharing
    the target's vocabulary."""
    if not serve.spec_draft:
        return None
    from flexflow_tpu.serving.spec import (
        ModelDraftProposer,
        NGramDraftProposer,
    )

    if serve.spec_draft == "ngram":
        return NGramDraftProposer(n=serve.spec_ngram)
    if draft_model is None:
        raise ValueError(
            "spec_draft='model' needs a compiled draft_model "
            "(a small decoder LM with the target's vocabulary)"
        )
    return ModelDraftProposer(
        draft_model,
        max_seqs=serve.max_seqs,
        max_len=serve.max_seq_len,
        buckets=serve.prefill_buckets or None,
        decode_kernel=serve.decode_kernel,
    )


def build_journal(serve: ServeConfig, injector=None, telemetry=None):
    """The RequestJournal a ServeConfig asks for, or None when
    durability is off. `injector` threads the chaos harness's
    journal-write-failure site through every append; `telemetry` keeps
    the `serve_journal_bytes` gauge current."""
    if not serve.journal:
        return None
    from flexflow_tpu.serving.journal import RequestJournal

    registry = None
    if telemetry is not None and getattr(telemetry, "enabled", False):
        registry = telemetry.registry
    return RequestJournal(
        serve.journal,
        fsync=serve.journal_fsync,
        injector=injector,
        registry=registry,
    )


def build_scheduler(
    model,
    serve: ServeConfig,
    draft_model=None,
    injector=None,
    telemetry=None,
    scheduler_cls=None,
    journal=None,
):
    """(scheduler, engine, cache) wired to a compiled model — the pieces
    generate() uses, exposed for callers that drive iterations themselves
    (bench_serve.py, tests). With serve.spec_draft set, the scheduler
    runs the speculative draft/verify loop (serving/spec.py). `injector`
    threads a faults.FaultInjector through the engine and scheduler
    seams — the chaos harness's entry point. `telemetry` threads a
    flexflow_tpu.telemetry.Telemetry bundle through the same seams
    (built from the serve config's telemetry knobs when omitted); the
    attached bundle is reachable as `scheduler.telemetry`.
    `scheduler_cls` overrides the scheduler class the config would pick
    (the disaggregated front door's prefill tier swaps in its
    chunk-only loop this way); it must subclass a serving scheduler.
    `journal` attaches an already-open RequestJournal (a restart reuses
    the one it recovered from); None builds one from `serve.journal`."""
    if (
        (serve.serve_mesh or serve.serve_hosts)
        and getattr(model, "serving_placement", None) is None
        and hasattr(model, "compile_for_serving")
    ):
        # --serve-mesh / --serve-hosts end-to-end path: apply the serving
        # mesh before the cache is built so from_model picks the
        # placement up (idempotent — an explicit compile_for_serving()
        # call beforehand wins)
        model.compile_for_serving(serve_config=serve)
    placement = getattr(model, "serving_placement", None)
    if (
        placement is not None
        and placement.num_hosts > 1
        and serve.kv_layout != "paged"
    ):
        raise ValueError(
            "multihost serving requires kv_layout='paged' (the host "
            "partition shards the page pool; the slot layout has no "
            "pool to shard)"
        )
    if serve.kv_layout == "paged":
        cache = PagedKVCache.from_model(
            model,
            max_seqs=serve.max_seqs,
            max_len=serve.max_seq_len,
            buckets=serve.prefill_buckets or None,
            page_size=serve.kv_page_size,
            num_pages=serve.kv_pages,
            kv_dtype=serve.kv_dtype,
            prefix_cache=serve.prefix_cache,
            prefix_evict=serve.prefix_evict,
            swap_bytes_budget=serve.kv_swap_bytes,
            evict_pricer=(
                build_evict_pricer(model)
                if serve.prefix_evict == "cost"
                else None
            ),
        )
    else:
        cache = KVCache.from_model(
            model,
            max_seqs=serve.max_seqs,
            max_len=serve.max_seq_len,
            buckets=serve.prefill_buckets or None,
        )
    if telemetry is None:
        telemetry = build_telemetry(serve)
    adapters = None
    if serve.adapters:
        from flexflow_tpu.serving.tenancy.adapters import AdapterPool

        adapters = AdapterPool.from_model(
            model,
            max_seqs=serve.max_seqs,
            max_adapters=serve.adapters,
            max_rank=serve.adapter_rank,
        )
    engine = GenerationEngine(
        model,
        cache,
        temperature=serve.temperature,
        seed=serve.seed,
        decode_kernel=serve.decode_kernel,
        injector=injector,
        telemetry=telemetry,
        adapters=adapters,
    )
    classes = None
    if serve.classes:
        from flexflow_tpu.serving.tenancy.fairness import parse_classes

        classes = parse_classes(serve.classes)
    cls = _SCHEDULERS[serve.scheduler]
    if serve.serve_async:
        # __post_init__ already pinned serve_async to the continuous
        # scheduler; the async loop is its double-buffered subclass
        cls = AsyncContinuousBatchingScheduler
    if scheduler_cls is not None:
        cls = scheduler_cls
    sched = cls(
        engine,
        proposer=build_proposer(serve, draft_model),
        spec_k=serve.spec_k,
        spec_branch=serve.spec_branch,
        admission=serve.admission,
        max_preemptions=serve.max_preemptions,
        injector=injector,
        debug_invariants=serve.debug_invariants,
        telemetry=telemetry,
        token_budget=serve.token_budget,
        chunk_size=serve.chunk_size,
        kv_swap=serve.kv_swap,
        swap_decider=(
            build_swap_decider(model) if serve.kv_swap else None
        ),
        decode_multistep=serve.decode_multistep,
        max_fused_steps=serve.max_fused_steps,
        classes=classes,
        victim_pricer=(
            build_victim_pricer(model)
            if classes and len(classes) > 1
            else None
        ),
        journal=(
            journal
            if journal is not None
            else build_journal(serve, injector=injector, telemetry=telemetry)
        ),
        journal_snapshot_every=serve.journal_snapshot_every,
    )
    return sched, engine, cache


def build_victim_pricer(model):
    """A `(cache, request) -> float` callable pricing one preemption
    victim's recompute bill (seconds) for the class-priced victim rule:
    estimate_recompute_step over the victim's resident history, the
    same modeled step time build_swap_decider prices swap against. The
    scheduler multiplies the result by the victim's class weight. Falls
    back to None — resident-token-count pricing — when the model
    carries no compiled graph/cost-model context; a pricing failure at
    pick time falls back the same way (the scheduler catches it)."""
    try:
        from flexflow_tpu.core.machine import MachineSpec
        from flexflow_tpu.search.auto import estimate_recompute_step
        from flexflow_tpu.search.cost_model import CostModel
        from flexflow_tpu.search.machine_model import build_machine_model

        graph = getattr(model, "graph", None)
        cfg = getattr(model, "config", None)
        if graph is None or cfg is None or not graph.nodes:
            return None
        spec = MachineSpec(
            num_nodes=max(1, cfg.num_nodes),
            chips_per_node=1,
            chip=cfg.chip,
        )
        cm = CostModel(spec, machine_model=build_machine_model(cfg, spec))
        placement = getattr(model, "serving_placement", None)
        dp = max(1, int(getattr(placement, "dp", 1)))
        tp = max(1, int(getattr(placement, "tp", 1)))
    except Exception:
        return None

    def price(cache, req) -> float:
        resume_len = len(req.prompt) + len(req.generated)
        cost = estimate_recompute_step(
            graph,
            cm,
            dp,
            tp,
            resume_len,
            page_size=getattr(cache.spec, "page_size", 0),
            decode_kernel="dense",
        )
        if cost is None:
            # nothing to price against: fall back to the token count
            return float(resume_len)
        return float(cost.step_time)

    return price


def build_swap_decider(model):
    """A `(cache, request) -> bool` callable pricing swap vs recompute
    for one preemption victim: True when staging the victim's pages out
    AND back in (2x swap_bytes_for over the host link,
    CostModel.swap_cost) beats recomputing its committed history at
    re-admission (estimate_recompute_step's modeled step time). Falls
    back to None — always-swap — when the model carries no compiled
    graph/cost-model context to price against; a pricing failure at
    preempt time must never lose the victim, so the scheduler also
    treats a raising decider as a refusal."""
    try:
        from flexflow_tpu.core.machine import MachineSpec
        from flexflow_tpu.search.auto import estimate_recompute_step
        from flexflow_tpu.search.cost_model import CostModel
        from flexflow_tpu.search.machine_model import build_machine_model

        graph = getattr(model, "graph", None)
        cfg = getattr(model, "config", None)
        if graph is None or cfg is None or not graph.nodes:
            return None
        spec = MachineSpec(
            num_nodes=max(1, cfg.num_nodes),
            chips_per_node=1,
            chip=cfg.chip,
        )
        cm = CostModel(spec, machine_model=build_machine_model(cfg, spec))
        placement = getattr(model, "serving_placement", None)
        dp = max(1, int(getattr(placement, "dp", 1)))
        tp = max(1, int(getattr(placement, "tp", 1)))
    except Exception:
        return None

    def decide(cache, req) -> bool:
        resume_len = len(req.prompt) + len(req.generated)
        cost = estimate_recompute_step(
            graph,
            cm,
            dp,
            tp,
            resume_len,
            page_size=getattr(cache.spec, "page_size", 0),
            decode_kernel="dense",
        )
        if cost is None:
            return True  # nothing to price against: prefer the copy
        swap_s = cm.swap_cost(2 * cache.swap_bytes_for(req.slot))
        return swap_s < cost.step_time

    return decide


def build_restore_decider(model):
    """A `(cache, record, resume_len) -> bool` callable pricing a
    crash-recovery KV restore against the recompute: True when adopting
    the journal's snapshot record over the host link (one
    CostModel.swap_cost copy of the record's staged bytes — the journal
    read itself is off the serving path) beats recomputing `resume_len`
    tokens of committed history (estimate_recompute_step's modeled step
    time). The recovery twin of build_swap_decider: same cost model,
    but the copy is 1x the record bytes (journal -> pool) where a
    preemption swap pays 2x (out AND back in). Falls back to None —
    journal.readmit then always restores an available snapshot — when
    the model carries no compiled graph/cost-model context."""
    try:
        from flexflow_tpu.core.machine import MachineSpec
        from flexflow_tpu.search.auto import estimate_recompute_step
        from flexflow_tpu.search.cost_model import CostModel
        from flexflow_tpu.search.machine_model import build_machine_model

        graph = getattr(model, "graph", None)
        cfg = getattr(model, "config", None)
        if graph is None or cfg is None or not graph.nodes:
            return None
        spec = MachineSpec(
            num_nodes=max(1, cfg.num_nodes),
            chips_per_node=1,
            chip=cfg.chip,
        )
        cm = CostModel(spec, machine_model=build_machine_model(cfg, spec))
        placement = getattr(model, "serving_placement", None)
        dp = max(1, int(getattr(placement, "dp", 1)))
        tp = max(1, int(getattr(placement, "tp", 1)))
    except Exception:
        return None

    def decide(cache, record, resume_len) -> bool:
        cost = estimate_recompute_step(
            graph,
            cm,
            dp,
            tp,
            int(resume_len),
            page_size=getattr(cache.spec, "page_size", 0),
            decode_kernel="dense",
        )
        if cost is None:
            return True  # nothing to price against: prefer the copy
        restore_s = cm.swap_cost(int(record.get("bytes", 0)))
        return restore_s < cost.step_time

    return decide


def build_evict_pricer(model):
    """A `(cursor, chunk) -> seconds` callable pricing the recompute of
    one published prefix page for the cost-aware eviction policy
    (`prefix_evict="cost"`): the page's tokens re-enter as one chunked-
    prefill step of `chunk` positions appended at cache cursor `cursor`
    (CostModel.prefill_chunk_cost summed over the graph, the same shape
    auto.optimize_token_budget prices), so the allocator can reclaim
    the cheapest-to-recompute page first. Falls back to None — the
    cache then orders by cursor, the same monotone order unpriced —
    when the model carries no compiled graph/cost-model context, same
    posture as build_swap_decider."""
    try:
        from flexflow_tpu.core.machine import MachineSpec
        from flexflow_tpu.core.types import OperatorType
        from flexflow_tpu.search.cost_model import CostModel
        from flexflow_tpu.search.machine_model import build_machine_model

        graph = getattr(model, "graph", None)
        cfg = getattr(model, "config", None)
        if graph is None or cfg is None or not graph.nodes:
            return None
        spec = MachineSpec(
            num_nodes=max(1, cfg.num_nodes),
            chips_per_node=1,
            chip=cfg.chip,
        )
        cm = CostModel(spec, machine_model=build_machine_model(cfg, spec))
        nodes = [
            n
            for n in graph.nodes.values()
            if n.op_type != OperatorType.INPUT and not n.is_parallel_op
        ]
        if not nodes:
            return None
    except Exception:
        return None

    def price(cursor: int, chunk: int) -> float:
        return sum(
            cm.prefill_chunk_cost(n, 1, int(cursor), int(chunk)).forward_time
            for n in nodes
        )

    return price


def generate(
    model,
    prompts: Sequence[Sequence[int]],
    max_new_tokens: int = 16,
    serve: Optional[ServeConfig] = None,
    eos_token: Optional[int] = None,
    draft_model=None,
) -> List[List[int]]:
    """Generate continuations for token-id prompts; returns the generated
    tokens (prompt excluded) in the prompts' order. Greedy by default —
    the cache-equivalence contract (tests/test_serving.py) holds for
    greedy decoding, with or without speculative drafting
    (tests/test_spec_decode.py).

    Per-request fault isolation: an invalid request in the batch (e.g. a
    prompt whose prompt + max_new_tokens exceeds the cache horizon)
    becomes a FAILED entry with an empty continuation instead of an
    exception that loses the whole batch — the serving-surface contract
    (one bad client request must not take down its neighbors)."""
    serve = serve or ServeConfig()
    if eos_token is None:
        eos_token = serve.eos_token
    sched, _, _ = build_scheduler(model, serve, draft_model=draft_model)
    reqs = [
        Request(
            rid=i,
            prompt=list(map(int, p)),
            max_new_tokens=max_new_tokens,
            eos_token=eos_token,
        )
        for i, p in enumerate(prompts)
    ]
    for r in reqs:
        sched.submit(r, strict=False)
    done = sched.run()
    by_rid = {r.rid: r for r in done}
    return [by_rid[i].generated for i in range(len(reqs))]
