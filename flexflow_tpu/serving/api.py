"""User-facing serving surface: ServeConfig + generate().

`FFModel.generate` (runtime/model.py) delegates here, mirroring how the
reference grew FlexFlow Serve on top of the training FFModel. ServeConfig
rides FFConfig flag parsing (`--max-seqs`, `--max-seq-len`,
`--serve-scheduler`, `--eos-token`), so serving scripts configure the
engine with the same CLI the training examples use.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from flexflow_tpu.serving.engine import GenerationEngine
from flexflow_tpu.serving.kv_cache import KVCache
from flexflow_tpu.serving.scheduler import (
    ContinuousBatchingScheduler,
    Request,
    StaticBatchingScheduler,
)

_SCHEDULERS = {
    "continuous": ContinuousBatchingScheduler,
    "static": StaticBatchingScheduler,
}


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (reference: RequestManager configuration in FlexFlow
    Serve; Orca's max_batch_size / max_seq_len pair)."""

    max_seqs: int = 8  # KV-cache slots = max in-flight requests
    max_seq_len: int = 256  # cache length per slot (prompt + generation)
    scheduler: str = "continuous"  # "continuous" | "static"
    eos_token: Optional[int] = None
    temperature: float = 0.0  # 0 = greedy
    seed: int = 0
    prefill_buckets: Tuple[int, ...] = ()  # () = powers of two

    def __post_init__(self):
        if self.scheduler not in _SCHEDULERS:
            raise ValueError(
                f"scheduler must be one of {sorted(_SCHEDULERS)}, "
                f"got {self.scheduler!r}"
            )
        if self.max_seqs < 1 or self.max_seq_len < 2:
            raise ValueError("max_seqs >= 1 and max_seq_len >= 2 required")

    @staticmethod
    def from_config(cfg) -> "ServeConfig":
        """Lift the serve_* fields FFConfig.parse_args fills."""
        return ServeConfig(
            max_seqs=cfg.serve_max_seqs,
            max_seq_len=cfg.serve_max_seq_len,
            scheduler=cfg.serve_scheduler,
            eos_token=(
                cfg.serve_eos_token if cfg.serve_eos_token >= 0 else None
            ),
            seed=cfg.seed,
        )


def build_scheduler(model, serve: ServeConfig):
    """(scheduler, engine, cache) wired to a compiled model — the pieces
    generate() uses, exposed for callers that drive iterations themselves
    (bench_serve.py, tests)."""
    cache = KVCache.from_model(
        model,
        max_seqs=serve.max_seqs,
        max_len=serve.max_seq_len,
        buckets=serve.prefill_buckets or None,
    )
    engine = GenerationEngine(
        model, cache, temperature=serve.temperature, seed=serve.seed
    )
    sched = _SCHEDULERS[serve.scheduler](engine)
    return sched, engine, cache


def generate(
    model,
    prompts: Sequence[Sequence[int]],
    max_new_tokens: int = 16,
    serve: Optional[ServeConfig] = None,
    eos_token: Optional[int] = None,
) -> List[List[int]]:
    """Generate continuations for token-id prompts; returns the generated
    tokens (prompt excluded) in the prompts' order. Greedy by default —
    the cache-equivalence contract (tests/test_serving.py) holds for
    greedy decoding."""
    serve = serve or ServeConfig()
    if eos_token is None:
        eos_token = serve.eos_token
    sched, _, _ = build_scheduler(model, serve)
    reqs = [
        Request(
            rid=i,
            prompt=list(map(int, p)),
            max_new_tokens=max_new_tokens,
            eos_token=eos_token,
        )
        for i, p in enumerate(prompts)
    ]
    done = sched.run(reqs)
    by_rid = {r.rid: r for r in done}
    return [by_rid[i].generated for i in range(len(reqs))]
