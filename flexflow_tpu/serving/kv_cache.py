"""KV caches for the serving engine: slot-contiguous and block-paged.

Two layouts share one spec/geometry derivation:

* `KVCache` — the PR-1 "static" layout: one pair of
  `[max_seqs, max_len, heads, head_dim]` arrays per attention layer. A
  *slot* is one row of the leading dim; every admitted request reserves
  `max_len` worth of HBM regardless of how many tokens it generates.

* `PagedKVCache` — the PagedAttention layout (Kwon et al., SOSP'23 /
  vLLM): K/V live in `[num_pages, page_size, heads, head_dim]` *pools*,
  a host-side free-page allocator hands pages to sequences on demand,
  and a per-slot *block table* (`[max_seqs, max_pages_per_seq]` int32,
  padded with the sentinel `num_pages`) maps logical cache positions to
  pool pages. A short request holds only the pages its tokens fill, so
  the same byte budget admits more concurrent short requests — the
  serving-capacity lever continuous batching turns into throughput.

  Admission supports two policies. The default *reserve* policy is
  preemption-free: a request is admitted only when the free pool covers
  its worst case (`ceil((prompt + max_new_tokens) / page_size)` pages)
  on top of every in-flight request's outstanding worst case, so a
  mid-flight decode can ALWAYS claim its next page — no preemption/swap
  path needed. The opt-in *optimistic* policy (vLLM's posture) admits on
  the pages a request needs NOW and reserves nothing for its growth;
  when the pool later runs dry mid-decode, `ensure_position` raises
  `PagePoolExhausted` and the scheduler preempts a victim — frees its
  pages and requeues it for prefill-from-recompute
  (serving/scheduler.py). Optimistic slots never contribute to the
  reserve ledger, so the two policies compose: reserve-admitted slots
  keep their guarantee even while optimistic slots gamble.

Prompt lengths are *bucketed* in both layouts: prefill pads each
admission batch's prompts up to the next bucket (powers of two by
default), so the number of compiled prefill programs is bounded by the
bucket count, not by the number of distinct prompt lengths the traffic
happens to contain.

Sharding: both layouts derive their specs from the compiled model's
ParallelTensor annotations — if the strategy shards attention heads (the
head-parallel replica-dim rewrite, ops/attention.py), the cache's heads
dim rides the same mesh axis, so TP-over-heads serving (the decode
search's batch-1 winner, search/auto.py optimize_serving) keeps each
chip's cache slice local.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flexflow_tpu.core.types import OperatorType


class PagePoolExhausted(RuntimeError):
    """The free-page pool cannot supply a page a sequence needs NOW.

    Under the reserve admission policy this means the allocator invariant
    was violated (something outside the accounting drained the pool — a
    fault, not a workload); under the optimistic policy it is an expected
    runtime condition the scheduler answers with preemption-by-recompute.
    """


def default_buckets(max_len: int, smallest: int = 16) -> Tuple[int, ...]:
    """Powers of two from `smallest` up to (and including) max_len."""
    out = []
    b = smallest
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


def default_page_size(max_len: int, target: int = 16) -> int:
    """Largest power of two <= target that divides max_len (vLLM's
    default block size is 16; halve until the geometry is divisible)."""
    ps = target
    while ps > 1 and max_len % ps:
        ps //= 2
    return ps


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Static geometry of the cache, derived from the compiled model.

    page_size == 0 means the slot-contiguous layout; page_size > 0 means
    the paged layout with `num_pages` pool pages. `itemsize` is the
    cache dtype's element width in bytes (set from the actual dtype at
    cache construction, so bytes_per_layer/total_bytes price bf16
    caches at 2 bytes, not a hardcoded 4)."""

    layer_guids: Tuple[int, ...]  # MHA node guids, topo order
    max_seqs: int
    max_len: int
    num_heads: int
    head_dim: int
    buckets: Tuple[int, ...]
    page_size: int = 0
    num_pages: int = 0
    itemsize: int = 4
    kv_dtype: str = "fp32"  # "fp32" | "int8" (int8 is paged-only)

    def bucket(self, length: int) -> int:
        """Smallest bucket >= length (prefill pad target)."""
        for b in self.buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt length {length} exceeds max_len {self.max_len}"
        )

    @property
    def paged(self) -> bool:
        return self.page_size > 0

    @property
    def max_pages_per_seq(self) -> int:
        if not self.paged:
            raise ValueError("max_pages_per_seq is a paged-layout property")
        return self.max_len // self.page_size

    @property
    def total_rows(self) -> int:
        """Cache positions the layout can hold (pool rows)."""
        if self.paged:
            return self.num_pages * self.page_size
        return self.max_seqs * self.max_len

    @property
    def bytes_per_layer(self) -> int:
        base = (
            2 * self.itemsize * self.total_rows * self.num_heads * self.head_dim
        )
        if self.kv_dtype == "int8":
            # fp32 dequant scales ride in a side pool, one per page per
            # head for K and V each — they are part of the cache's HBM
            # bill even though the token pools shrink 4x
            base += 2 * 4 * self.num_pages * self.num_heads
        return base

    @property
    def total_bytes(self) -> int:
        """Whole-cache footprint across layers — the number
        optimize_serving's capacity estimate divides the HBM budget by."""
        return self.bytes_per_layer * len(self.layer_guids)


def _validate_page_geometry(max_seqs, max_len, page_size, num_pages):
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    if max_len % page_size:
        raise ValueError(
            f"max_len {max_len} is not divisible by page_size {page_size}"
        )
    if num_pages < max_len // page_size:
        raise ValueError(
            f"num_pages {num_pages} cannot hold even one max_len sequence "
            f"({max_len // page_size} pages of {page_size})"
        )


def _derive_geometry(model):
    """(layer_guids, heads, head_dim, head_axis, executor) from a
    compiled FFModel. Every MULTIHEAD_ATTENTION node must agree on
    (heads, head_dim) — one cache block size per model, like the
    reference serve stack. The sharding comes from the Wq weight's head
    dim: if the chosen strategy partitioned heads (parallel_idx -> mesh
    axis), the cache heads dim shards on that axis; otherwise the cache
    is replicated."""
    if model.executor is None:
        raise RuntimeError("compile() the model before building a KVCache")
    graph = model.graph
    executor = model.executor
    guids = [
        g
        for g in executor.topo
        if graph.nodes[g].op_type == OperatorType.MULTIHEAD_ATTENTION
    ]
    if not guids:
        raise ValueError("model has no attention layers to cache")
    geom = set()
    head_axis = None
    for g in guids:
        node = graph.nodes[g]
        heads = int(node.params["num_heads"])
        head_dim = int(node.params["embed_dim"]) // heads
        geom.add((heads, head_dim))
        wq = node.weight_shapes[0] if node.weight_shapes else None
        if wq is not None and len(wq.dims) == 3:
            hd = wq.dims[1]
            if hd.degree > 1 and 0 <= hd.parallel_idx < len(
                executor.mesh_config.axis_names
            ):
                head_axis = executor.mesh_config.axis_names[hd.parallel_idx]
    if len(geom) != 1:
        raise ValueError(
            f"attention layers disagree on (heads, head_dim): {geom}"
        )
    heads, head_dim = geom.pop()
    return guids, heads, head_dim, head_axis, executor


def _heads_sharding(executor, head_axis):
    """NamedSharding placing dim 2 (heads) on the strategy's head axis.

    Always place the cache on the mesh (replicated when heads are not
    sharded): uncommitted fresh zeros would give the first engine step a
    different jit signature than every later step (committed jit
    outputs) and buy a pointless recompile."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(
        executor.mesh, PartitionSpec(None, None, head_axis, None)
    )


class KVCache:
    """Slot-contiguous device arrays + host-side slot bookkeeping.

    The arrays are functional (each engine step returns fresh ones;
    `commit` swaps them in); the slot free-list and per-slot lengths are
    plain host state the scheduler mutates between steps.
    """

    paged = False

    def __init__(self, spec: KVCacheSpec, dtype, shardings=None):
        import jax
        import jax.numpy as jnp

        self.spec = dataclasses.replace(
            spec, itemsize=jnp.dtype(dtype).itemsize
        )
        spec = self.spec
        self.dtype = dtype
        shape = (spec.max_seqs, spec.max_len, spec.num_heads, spec.head_dim)
        self.k: Dict[int, object] = {}
        self.v: Dict[int, object] = {}
        for g in spec.layer_guids:
            k = jnp.zeros(shape, dtype)
            v = jnp.zeros(shape, dtype)
            if shardings is not None:
                k = jax.device_put(k, shardings)
                v = jax.device_put(v, shardings)
            self.k[g] = k
            self.v[g] = v
        # host bookkeeping: lengths[i] = tokens currently cached in slot i.
        # _free is a min-heap so alloc pops the lowest free id (dense,
        # deterministic slot reuse) and free is O(log n) — no full re-sort
        # per release.
        self.lengths = np.zeros(spec.max_seqs, dtype=np.int32)
        self._free: List[int] = list(range(spec.max_seqs))
        self._active: set = set()
        self._inflight_depth = 0
        # host-partition parity with PagedKVCache: the slot layout is
        # single-host only (serving.api rejects --serve-hosts > 1 on it)
        self.num_hosts = 1

    def host_of_slot(self, slot: int) -> int:
        return 0

    def free_pages_by_host(self) -> List[int]:
        return [0]

    # -- in-flight window (async dispatch) -----------------------------------

    def begin_inflight(self) -> None:
        """Open an in-flight window: a dispatched-but-not-reconciled step
        references this cache's state. The slot layout needs no pinning
        — a stale write from an in-flight step lands at a position the
        next occupant overwrites before its lengths mask ever exposes it
        — so the window is pure depth bookkeeping here; the paged twin
        pins freed pages for the window's duration."""
        self._inflight_depth += 1

    def end_inflight(self) -> None:
        if self._inflight_depth <= 0:
            raise RuntimeError("end_inflight without a matching begin_inflight")
        self._inflight_depth -= 1

    @property
    def pinned_pages(self) -> int:
        """Signature parity with PagedKVCache (the slot layout pins
        nothing)."""
        return 0

    # -- slot management (host side) ----------------------------------------

    @property
    def num_active(self) -> int:
        return len(self._active)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def active_slots(self) -> List[int]:
        return sorted(self._active)

    def can_admit(
        self,
        prompt_len: int = 1,
        total_len: int = 0,
        optimistic: bool = False,
    ) -> bool:
        """A slot layout admits whenever a slot is free (every slot holds
        max_len positions, so length arguments — and the admission policy
        — cannot change the verdict; they exist for signature parity with
        PagedKVCache)."""
        return bool(self._free)

    def alloc(
        self,
        prompt_len: Optional[int] = None,
        total_len: Optional[int] = None,
        optimistic: bool = False,
    ) -> Optional[int]:
        """Take a free slot (None when full). Lowest-free-id pop so slot
        ids stay dense and deterministic under a fixed request stream.
        The length/policy arguments are accepted (and ignored) so the
        scheduler drives both layouts through one call — a slot pins
        max_len rows either way, so the slot layout has no page pressure
        and nothing to admit optimistically against."""
        if not self._free:
            return None
        slot = heapq.heappop(self._free)
        self._active.add(slot)
        self.lengths[slot] = 0
        return slot

    def claim(self, slot: int) -> None:
        """Allocate a SPECIFIC free slot. Speculative decoding keeps the
        draft model's cache slot-aligned with the target's
        (serving/spec.py ModelDraftProposer), so the draft mirrors the
        target's admission instead of running its own allocator."""
        if slot in self._active:
            raise ValueError(f"slot {slot} is already active")
        if slot not in self._free:
            raise ValueError(f"slot {slot} is not a valid free slot")
        self._free.remove(slot)
        heapq.heapify(self._free)
        self._active.add(slot)
        self.lengths[slot] = 0

    def free(self, slot: int) -> None:
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        self._active.remove(slot)
        self.lengths[slot] = 0
        heapq.heappush(self._free, slot)

    def truncate(
        self, slot: int, new_len: int, src_rows: Optional[Sequence[int]] = None
    ) -> None:
        """Roll the slot's visible length to `new_len` (speculative-decode
        rollback: verify writes k+1 rows, acceptance keeps a prefix).
        Rows past new_len stay in HBM as stale data — the lengths mask in
        decode/verify attention hides them and later writes overwrite
        them, so no device work is needed. new_len may also EXCEED the
        current length: verify commits its accepted rows through this
        same call.

        src_rows (tree-verify commit): absolute cache positions, in
        path order, holding the ACCEPTED root-to-leaf rows of a token
        tree — scattered across the verify window because dead branches
        sit between them. They are compacted into the contiguous tail
        positions [new_len - len(src_rows), new_len) before the length
        moves, so the committed cache is indistinguishable from a
        linear decode of the accepted path (K/V rows carry no positional
        encoding — attention context is the mask's job — so the row
        copy is value-exact). Positions must be non-decreasing and each
        source must sit at-or-after its destination (topological node
        order guarantees both); src_rows == destinations is a no-op, so
        chain trees never touch the device."""
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        if not 0 <= new_len <= self.spec.max_len:
            raise ValueError(
                f"new_len {new_len} outside [0, {self.spec.max_len}]"
            )
        if src_rows is not None and len(src_rows):
            self._compact_rows(slot, new_len, src_rows)
        self.lengths[slot] = new_len

    def _compact_rows(
        self, slot: int, new_len: int, src_rows: Sequence[int]
    ) -> None:
        """Move the accepted tree rows into the contiguous tail of the
        committed prefix. Functional rebind (fresh dicts, gather before
        scatter), not in-place mutation: already-queued steps read the
        OLD arrays, and the new arrays chain behind the verify step's
        committed outputs on the device queue — the commit() discipline."""
        import jax.numpy as jnp

        srcs = [int(p) for p in src_rows]
        dests = list(range(new_len - len(srcs), new_len))
        if dests[0] < 0:
            raise ValueError(
                f"{len(srcs)} compacted rows do not fit under new_len "
                f"{new_len}"
            )
        for s, d in zip(srcs, dests):
            if not d <= s < self.spec.max_len:
                raise ValueError(
                    f"source row {s} outside [{d}, {self.spec.max_len})"
                )
        if srcs == dests:
            return
        si = jnp.asarray(np.asarray(srcs, dtype=np.int32))
        di = jnp.asarray(np.asarray(dests, dtype=np.int32))
        nk, nv = dict(self.k), dict(self.v)
        for g in self.spec.layer_guids:
            nk[g] = nk[g].at[slot, di].set(nk[g][slot, si])
            nv[g] = nv[g].at[slot, di].set(nv[g][slot, si])
        self.k, self.v = nk, nv

    def commit(self, new_k: Dict[int, object], new_v: Dict[int, object]):
        """Swap in the arrays a jitted step returned."""
        self.k = dict(new_k)
        self.v = dict(new_v)

    def telemetry_gauges(self) -> Dict[str, float]:
        """Point-in-time allocator gauges the per-iteration telemetry
        sampler exports (`kv_*` series). Reads the same ledgers
        `check_invariants` re-derives its truth from, so the KV-gauge
        tests can hold the two to exact agreement. The slot layout has
        no pages: occupancy is row-based (a slot pins max_len rows, so
        `kv_occupancy` is the fraction of reserved rows actually
        holding tokens) and the page gauges sit at zero for series
        parity with the paged layout."""
        spec = self.spec
        used = int(self.lengths.sum())
        return {
            "kv_slots_active": len(self._active),
            "kv_slots_free": len(self._free),
            "kv_rows_used": used,
            "kv_occupancy": used / spec.total_rows if spec.total_rows else 0.0,
            "kv_pages_live": 0,
            "kv_pages_pinned": 0,
            "kv_free_heap_depth": 0,
            "kv_pages_reserved": 0,
            "kv_inflight_depth": self._inflight_depth,
            "kv_prefix_pages_shared": 0,
            "kv_swapped_pages": 0,
            "kv_pages_pub_only": 0,
        }

    def telemetry_counters(self) -> Dict[str, int]:
        """Series parity with PagedKVCache (the slot layout never
        shares, swaps, or evicts pages)."""
        return {
            "kv_prefix_hits_total": 0,
            "kv_cow_copies_total": 0,
            "kv_swap_out_total": 0,
            "kv_swap_in_total": 0,
            "kv_swap_bytes_total": 0,
            "kv_prefix_evictions_total": 0,
        }

    def check_invariants(self, extra_free: int = 0) -> None:
        """Assert the slot bookkeeping is consistent — the chaos-harness
        probe (tests/test_resilience.py, bench_serve.py --chaos) calls
        this after every iteration. `extra_free` exists for signature
        parity with PagedKVCache (a fault injector holding pages has no
        slot-layout analog)."""
        spec = self.spec
        assert self._active.isdisjoint(self._free)
        assert len(self._active) + len(self._free) == spec.max_seqs
        for s in self._free:
            assert self.lengths[s] == 0
        for s in self._active:
            assert 0 <= self.lengths[s] <= spec.max_len

    # -- construction from a compiled model ---------------------------------

    @staticmethod
    def from_model(
        model,
        max_seqs: int,
        max_len: int,
        dtype=None,
        buckets: Optional[Sequence[int]] = None,
    ) -> "KVCache":
        """Derive geometry + shardings from a compiled FFModel. When the
        model carries a `serving_placement` (compile_for_serving), the
        cache rides the SERVING mesh — slots on the data axis, heads on
        the model axis — instead of the training strategy's sharding."""
        import jax.numpy as jnp

        guids, heads, head_dim, head_axis, executor = _derive_geometry(model)
        spec = KVCacheSpec(
            layer_guids=tuple(guids),
            max_seqs=max_seqs,
            max_len=max_len,
            num_heads=heads,
            head_dim=head_dim,
            buckets=tuple(buckets) if buckets else default_buckets(max_len),
        )
        if dtype is None:
            dtype = jnp.float32
        placement = getattr(model, "serving_placement", None)
        if placement is not None:
            shardings = placement.kv_sharding()
        else:
            shardings = _heads_sharding(executor, head_axis)
        return KVCache(spec, dtype, shardings=shardings)


class PagedKVCache:
    """Block-paged pools + host-side page allocator and block tables.

    Device state: one `[num_pages, page_size, heads, head_dim]` K and V
    pool per layer (functional, swapped via `commit` like KVCache).
    Host state: the free-page stack, per-slot block tables (sentinel =
    `num_pages`, an out-of-bounds page id — OOB scatters drop and OOB
    gathers are masked by lengths, so sentinel entries are inert on
    device), per-slot lengths, and the reserve ledger that keeps
    admission preemption-free.

    Prefix sharing (`prefix_cache=True`): full pages whose token content
    (a chained blake2b over per-page tokens) matches a page a previous
    request registered are MAPPED into a new request's block table
    instead of recomputed — per-page refcounts track the aliasing, the
    sharer's table entries are flagged shared, and the first divergent
    write copies the page (copy-on-write inside `ensure_position`).
    Pages leave the pool only when their refcount hits zero, at which
    point their hash-index entry is invalidated too.

    int8 quantization (`spec.kv_dtype == "int8"`): the token pools hold
    int8 with one fp32 dequant scale per page per head in side pools
    (`k_scale`/`v_scale`, `[num_pages, num_heads]`). The FIRST write
    into a page fixes its scale (engine-side scatter-max); later rows
    reuse it (values beyond ±127·scale clip — the documented
    tolerance), so a page's bytes depend only on its token content and
    prefix-shared pages stay bit-identical across requests.
    """

    paged = True

    def __init__(
        self,
        spec: KVCacheSpec,
        dtype,
        shardings=None,
        prefix_cache=False,
        placement=None,
        prefix_evict: str = "none",
        swap_bytes_budget: int = 0,
        evict_pricer=None,
    ):
        import jax
        import jax.numpy as jnp

        if not spec.paged:
            raise ValueError("PagedKVCache needs a spec with page_size > 0")
        if prefix_evict not in ("none", "lru", "cost"):
            raise ValueError(
                f"prefix_evict must be 'none', 'lru', or 'cost', "
                f"got {prefix_evict!r}"
            )
        _validate_page_geometry(
            spec.max_seqs, spec.max_len, spec.page_size, spec.num_pages
        )
        self.quantized = spec.kv_dtype == "int8"
        if self.quantized:
            dtype = jnp.int8
        self.spec = dataclasses.replace(
            spec, itemsize=jnp.dtype(dtype).itemsize
        )
        spec = self.spec
        self.dtype = dtype
        self.prefix_cache = bool(prefix_cache)
        shape = (spec.num_pages, spec.page_size, spec.num_heads, spec.head_dim)
        self.k: Dict[int, object] = {}
        self.v: Dict[int, object] = {}
        # int8 side pools: fp32 scale per (page, head); scale == 0 marks
        # a page whose first write has not landed yet (engine scatter-max
        # claims it). Empty dicts under fp32 so the engine threads one
        # pytree shape through the jitted steps either way.
        self.k_scale: Dict[int, object] = {}
        self.v_scale: Dict[int, object] = {}
        scale_shardings = None
        if shardings is not None and self.quantized:
            from jax.sharding import NamedSharding, PartitionSpec

            # pools shard pages on dim 0 and heads on dim 2; the
            # [num_pages, heads] scale pools carry the same axes
            scale_shardings = NamedSharding(
                shardings.mesh,
                PartitionSpec(shardings.spec[0], shardings.spec[2]),
            )
        from flexflow_tpu.runtime import multihost

        for g in spec.layer_guids:
            k = jnp.zeros(shape, dtype)
            v = jnp.zeros(shape, dtype)
            if shardings is not None:
                k = multihost.place_array(k, shardings)
                v = multihost.place_array(v, shardings)
            self.k[g] = k
            self.v[g] = v
            if self.quantized:
                ks = jnp.zeros((spec.num_pages, spec.num_heads), jnp.float32)
                vs = jnp.zeros((spec.num_pages, spec.num_heads), jnp.float32)
                if scale_shardings is not None:
                    ks = multihost.place_array(ks, scale_shardings)
                    vs = multihost.place_array(vs, scale_shardings)
                self.k_scale[g] = ks
                self.v_scale[g] = vs
        self.lengths = np.zeros(spec.max_seqs, dtype=np.int32)
        self.block_tables = np.full(
            (spec.max_seqs, spec.max_pages_per_seq),
            spec.num_pages,
            dtype=np.int32,
        )
        # HOST partition (serving/distributed.py): host h owns the
        # contiguous slot block [h*spn, (h+1)*spn) and page block
        # [h*ppn, (h+1)*ppn) — coinciding with the device shard
        # boundaries of pool dim 0 on the serving mesh's data axis, so a
        # slot's pages live with its host's devices. Admission and page
        # claims run against PER-HOST free views; num_hosts == 1
        # degenerates to the single global heap (byte-identical pop
        # order to the pre-placement allocator).
        self.placement = placement
        self.num_hosts = placement.num_hosts if placement is not None else 1
        if spec.max_seqs % self.num_hosts or spec.num_pages % self.num_hosts:
            raise ValueError(
                f"host partition: max_seqs {spec.max_seqs} and num_pages "
                f"{spec.num_pages} must both divide by num_hosts "
                f"{self.num_hosts}"
            )
        self._slots_per_host = spec.max_seqs // self.num_hosts
        self._pages_per_host = spec.num_pages // self.num_hosts
        # min-heaps: alloc pops the lowest free slot/page id (deterministic
        # reuse order), release is O(log n) heappush instead of the old
        # append + full sort. One heap pair PER HOST; `_free_slots` /
        # `_free_pages` stay bound to host 0's heaps (the SAME list
        # objects, mutated in place, never rebound) so the single-host
        # fault injector and tests keep their direct handle on the pool.
        self._free_slots_h: List[List[int]] = [
            list(
                range(h * self._slots_per_host, (h + 1) * self._slots_per_host)
            )
            for h in range(self.num_hosts)
        ]
        self._free_pages_h: List[List[int]] = [
            list(
                range(h * self._pages_per_host, (h + 1) * self._pages_per_host)
            )
            for h in range(self.num_hosts)
        ]
        self._free_slots: List[int] = self._free_slots_h[0]
        self._active: set = set()
        self._free_pages: List[int] = self._free_pages_h[0]
        # preemption-free reserve: _max_pages[s] is slot s's worst-case
        # page need (fixed at admission), _held[s] what it holds now;
        # _reserved = Σ (max - held) over active RESERVE-admitted slots —
        # pages the free list must keep back for in-flight growth.
        # Optimistic slots (admitted beyond the reserve; preempted on
        # pool exhaustion) keep _max_pages pinned to _held and never
        # touch _reserved.
        self._held = np.zeros(spec.max_seqs, dtype=np.int64)
        self._max_pages = np.zeros(spec.max_seqs, dtype=np.int64)
        self._reserved_h: List[int] = [0] * self.num_hosts
        self._optimistic: set = set()
        # prefix sharing: per-page reference counts (re-derivable from
        # the block tables — check_invariants does exactly that), the
        # per-entry shared flag (True = this mapping aliases a page some
        # other request wrote; first write through it must COW), the
        # per-slot shared-mapping count, and the content-hash index
        # (chained page key -> page id, with its exact inverse).
        # "Owned" pages (_held - _shared) are what the reserve ledger
        # prices: a shared mapping costs the pool nothing until it COWs.
        self._refcounts = np.zeros(spec.num_pages, dtype=np.int32)
        self._entry_shared = np.zeros(
            (spec.max_seqs, spec.max_pages_per_seq), dtype=bool
        )
        self._shared = np.zeros(spec.max_seqs, dtype=np.int64)
        self._prefix_index: Dict[bytes, int] = {}
        self._page_keys: Dict[int, bytes] = {}
        self.prefix_hits = 0  # admissions that mapped >= 1 shared page
        self.cow_copies = 0  # divergent writes that copied a page
        # published-prefix eviction (prefix_evict="lru"): a published
        # page whose LAST table reference drops is RETAINED — refcount 0,
        # off the free heap, still advertised by the hash index — in
        # `_pub_only` (page -> (LRU stamp, wait-for window id)) instead
        # of released. Under pool pressure the least-recently-published
        # page is unpublished and returned to the free heap BEFORE any
        # live request is swapped or preempted; a new admission matching
        # it resurrects the mapping (refcount 0 -> 1) at zero pool cost.
        # The wait-window tag mirrors limbo's discipline: an in-flight
        # step dispatched before the release may still WRITE the page's
        # pool rows, so eviction (which hands the page to a new writer)
        # waits for that window to close; read-only resurrection is
        # always safe and is not gated.
        # prefix_evict="cost" replaces the LRU victim choice with the
        # page CHEAPEST to recompute: a published page covering tokens
        # [c, c+page_size) of its chain re-prefills as one chunk at
        # cursor c (CostModel.prefill_chunk_cost), and that cost grows
        # with c — so the cost policy reclaims shallow chain pages first
        # and keeps the deep (expensive) tails warm. `evict_pricer`
        # is the (cursor, chunk) -> seconds callable api.build_scheduler
        # wires from the compiled model's cost model; None degrades to
        # the cursor itself (the same monotone order, unpriced).
        # `_page_spans` records each published page's chain-start cursor
        # at registration time — pages only store hash keys otherwise.
        self.prefix_evict = prefix_evict
        self.evict_pricer = evict_pricer
        self._page_spans: Dict[int, int] = {}
        self._pub_only: Dict[int, Tuple[int, int]] = {}
        self._evict_tick = 0
        self.prefix_evictions = 0
        # KV swap-to-host (vLLM's swap alternative to recompute): a
        # victim's committed pages are device-gathered into host numpy
        # buffers keyed by a monotonic handle; re-admission scatters
        # them into freshly claimed pages — no re-prefill. The bytes
        # ledger enforces `swap_bytes_budget` (0 = unlimited) across
        # every outstanding handle.
        self.swap_bytes_budget = int(swap_bytes_budget)
        self._swapped: Dict[int, Dict[str, object]] = {}
        self._swap_seq = 0
        self._swap_bytes_held = 0
        self.swap_outs = 0
        self.swap_ins = 0
        self.swap_bytes_total = 0
        # host-failure drain: partitions marked lost refuse admission
        # (_pick_host / alloc_shared skip them) until marked up again
        self._hosts_down: set = set()
        # in-flight window (async dispatch): while a dispatched step's
        # deferred device reads may still reference the block tables it
        # was handed, pages released by free/truncate go to _limbo
        # instead of the free heap — handing them to a new sequence
        # would let its prefill race the in-flight step's stale write.
        # Windows open at dispatch and close at reconcile IN ORDER, and
        # the steady-state pipeline (dispatch N+1, then reconcile N)
        # keeps one window open at all times — so limbo entries are
        # tagged with the NEWEST window open at release time and drain
        # as soon as that window closes, not when the (never-idle)
        # depth hits zero.
        self._window_seq = 0  # id of the most recently opened window
        self._window_closed = 0  # window ids <= this have reconciled
        self._limbo: List[Tuple[int, int]] = []  # (page, wait-for window id)

    # -- in-flight window (async dispatch) -----------------------------------

    @property
    def _inflight_depth(self) -> int:
        return self._window_seq - self._window_closed

    def begin_inflight(self) -> None:
        """Open an in-flight window: a dispatched-but-not-reconciled
        step holds a snapshot of the block tables, so any page released
        while the window is open is PINNED (moved to the limbo list,
        not the free heap) until every step dispatched before the
        release has reconciled — optimistic preemption or an EOS retire
        during the window cannot hand an in-flight page to a new
        sequence."""
        self._window_seq += 1

    def end_inflight(self) -> None:
        """Close the oldest open window (steps reconcile in dispatch
        order); limbo pages waiting only on it return to the free
        heap."""
        if self._window_closed >= self._window_seq:
            raise RuntimeError("end_inflight without a matching begin_inflight")
        self._window_closed += 1
        if self._limbo:
            kept: List[Tuple[int, int]] = []
            for p, wid in self._limbo:
                if wid <= self._window_closed:
                    heapq.heappush(self._free_pages_h[self._page_home(p)], p)
                else:
                    kept.append((p, wid))
            self._limbo = kept

    @property
    def pinned_pages(self) -> int:
        """Pages released during an open in-flight window, unavailable
        until the steps that could reference them reconcile (the async
        scheduler drains the pipeline when a claim needs them back)."""
        return len(self._limbo)

    def _release_page(self, p: int) -> None:
        if self._window_seq > self._window_closed:
            self._limbo.append((p, self._window_seq))
        else:
            heapq.heappush(self._free_pages_h[self._page_home(p)], p)

    # -- host partition ------------------------------------------------------

    @property
    def _reserved(self) -> int:
        """Total growth reserve across host partitions (read-only view;
        writes go to the owning host's `_reserved_h` entry)."""
        return sum(self._reserved_h)

    def host_of_slot(self, slot: int) -> int:
        """Which host partition owns `slot` (contiguous blocks)."""
        return int(slot) // self._slots_per_host

    def _page_home(self, p: int) -> int:
        """Which host partition owns page `p` (contiguous blocks,
        aligned with the data-axis device shards of pool dim 0)."""
        return int(p) // self._pages_per_host

    def _host_avail(self, h: int) -> int:
        """Free pages plus evictable publication-only pages minus the
        growth reserve on host `h` — the admission headroom. A
        ONE-STEP-STALE view is safe by design: pages released during an
        open in-flight window sit in limbo (not the free heap), so this
        count only under-promises; it never hands out a page an
        in-flight step could still read. Counting evictable pages here
        is what makes prefix eviction happen BEFORE any live request is
        swapped or preempted: admission and page claims see the
        headroom, and `_pop_free_page` evicts lazily when the heap runs
        dry."""
        return (
            len(self._free_pages_h[h])
            + self._evictable_count(h)
            - self._reserved_h[h]
        )

    def mark_host_down(self, h: int) -> None:
        """Mark host partition `h` lost: `_pick_host` and `alloc_shared`
        refuse it until `mark_host_up`. The partition's ledgers stay
        intact (its pool content is gone with its devices, but the
        accounting still re-derives) — the scheduler drains its RUNNING
        requests to surviving hosts."""
        if not 0 <= h < self.num_hosts:
            raise ValueError(f"host {h} outside [0, {self.num_hosts})")
        self._hosts_down.add(h)

    def mark_host_up(self, h: int) -> None:
        """Re-join a recovered host partition into admission."""
        self._hosts_down.discard(h)

    @property
    def hosts_down(self) -> frozenset:
        return frozenset(self._hosts_down)

    def _pick_host(self, need: int) -> Optional[int]:
        """Choose the admission host: any alive host with a free slot
        whose free view covers `need` pages; most headroom wins, ties to
        the lowest host id (deterministic). None when no host can
        admit."""
        best = None
        best_avail = -1
        for h in range(self.num_hosts):
            if h in self._hosts_down or not self._free_slots_h[h]:
                continue
            avail = self._host_avail(h)
            if avail >= need and avail > best_avail:
                best, best_avail = h, avail
        return best

    def free_pages_by_host(self) -> List[int]:
        """Per-host free-heap depths (telemetry / scheduler views)."""
        return [len(hp) for hp in self._free_pages_h]

    # -- page/slot management (host side) ------------------------------------

    @property
    def num_active(self) -> int:
        return len(self._active)

    @property
    def num_free(self) -> int:
        return sum(len(hs) for hs in self._free_slots_h)

    @property
    def num_free_pages(self) -> int:
        return sum(len(hp) for hp in self._free_pages_h)

    @property
    def pages_in_use(self) -> int:
        return self.spec.num_pages - self.num_free_pages

    def active_slots(self) -> List[int]:
        return sorted(self._active)

    def _pages_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.spec.page_size)

    def can_admit(
        self,
        prompt_len: int = 1,
        total_len: int = 0,
        optimistic: bool = False,
    ) -> bool:
        """True when SOME host partition has a free slot AND its free
        view covers this request's page need on top of every in-flight
        reservation: the worst case (prompt + max_new_tokens) under the
        reserve policy, only the pages the prompt fills NOW under the
        optimistic one. Admission is per-host (a request's pages never
        straddle hosts), so a fragmented pod can refuse a request the
        global count would accept."""
        if optimistic:
            need = self._pages_for(prompt_len)
        else:
            need = self._pages_for(max(prompt_len, total_len))
        return self._pick_host(need) is not None

    def alloc(
        self,
        prompt_len: Optional[int] = None,
        total_len: Optional[int] = None,
        optimistic: bool = False,
    ) -> Optional[int]:
        """Admit a sequence: take a slot, allocate the pages its prompt
        fills now, and — under the default reserve policy — reserve
        (without allocating) the rest of its worst case. None when the
        policy refuses. `optimistic=True` reserves nothing beyond the
        prompt's pages (the slot may later hit PagePoolExhausted and be
        preempted). Omitted lengths reserve-and-fill a full max_len
        (slot-equivalent behavior for ad-hoc engine callers)."""
        spec = self.spec
        if prompt_len is None:
            prompt_len = spec.max_len
        total = max(prompt_len, total_len if total_len is not None else 0)
        if total > spec.max_len:
            raise ValueError(
                f"sequence of {total} tokens exceeds max_len {spec.max_len}"
            )
        need_now = self._pages_for(prompt_len)
        max_p = self._pages_for(total)
        h = self._pick_host(need_now if optimistic else max_p)
        if h is None:
            return None
        slot = heapq.heappop(self._free_slots_h[h])
        self._active.add(slot)
        for i in range(need_now):
            self._install_page(slot, i, self._pop_free_page(h))
        self._held[slot] = need_now
        if optimistic:
            # no growth reserve: _max_pages tracks _held so this slot
            # contributes zero to the reserve ledger, now and forever
            self._optimistic.add(slot)
            self._max_pages[slot] = need_now
        else:
            self._max_pages[slot] = max_p
            self._reserved_h[h] += max_p - need_now
        self.lengths[slot] = 0
        return slot

    # -- prefix sharing (hashed page cache + copy-on-write) ------------------

    def _owned(self, slot: int) -> int:
        """Pages this slot holds that came from the free pool (its
        shared mappings alias pages other requests own)."""
        return int(self._held[slot]) - int(self._shared[slot])

    def _install_page(self, slot: int, pi: int, page: int) -> None:
        """Map a freshly popped page into a table entry (refcount 1)."""
        self.block_tables[slot, pi] = page
        self._refcounts[page] = 1

    def _incref(self, slot: int, pi: int, page: int) -> None:
        """Map an already-live (or publication-only retained) page as a
        SHARED entry of `slot`. Resurrecting a retained page (refcount
        0 -> 1) removes it from the eviction candidates — it is live
        again and its sharers protect it."""
        self.block_tables[slot, pi] = page
        self._refcounts[page] += 1
        self._entry_shared[slot, pi] = True
        self._shared[slot] += 1
        if page in self._pub_only:
            del self._pub_only[page]

    def _decref_page(self, page: int) -> None:
        """Drop one reference. Under prefix_evict="lru" a PUBLISHED
        page whose last reference drops is retained as an eviction
        candidate (still matchable, resurrectable at zero pool cost)
        instead of released — closing the "last owner unpublishes" gap:
        publication alone now keeps a page warm until pool pressure
        actually needs it back. Otherwise the last owner unpublishes
        the page and releases it (through the in-flight limbo when a
        dispatched step may still read it)."""
        self._refcounts[page] -= 1
        assert self._refcounts[page] >= 0
        if self._refcounts[page] == 0:
            if self.prefix_evict != "none" and page in self._page_keys:
                self._evict_tick += 1
                self._pub_only[page] = (self._evict_tick, self._window_seq)
                return
            key = self._page_keys.pop(page, None)
            if key is not None and self._prefix_index.get(key) == page:
                del self._prefix_index[key]
            self._page_spans.pop(page, None)
            self._release_page(page)

    def _evictable_count(self, h: int) -> int:
        """Publication-only pages homed on host `h` whose wait window
        has closed — claimable via `_evict_prefix_page`. Pages retained
        while an in-flight window was open stay uncounted until that
        window reconciles (same discipline as limbo: an in-flight step
        may still write their rows)."""
        if not self._pub_only:
            return 0
        return sum(
            1
            for p, (_, wid) in self._pub_only.items()
            if wid <= self._window_closed and self._page_home(p) == h
        )

    def _evict_cost(self, page: int) -> float:
        """Seconds to recompute `page` if its prefix is wanted again:
        one chunk of page_size tokens appended at the page's chain-start
        cursor. Priced through `evict_pricer` when the compiled model
        wired one; otherwise the cursor itself — the same monotone
        order (attention cost grows with cursor), just unscaled. A
        raising pricer degrades to the proxy: eviction must never fail
        because pricing did."""
        cursor = self._page_spans.get(page, 0)
        if self.evict_pricer is not None:
            try:
                return float(self.evict_pricer(cursor, self.spec.page_size))
            except Exception:
                pass
        return float(cursor)

    def _evict_prefix_page(self, h: int) -> None:
        """Evict one publication-only page homed on host `h`: unpublish
        it from the hash index and push it straight onto the free heap
        (its wait window closed, so no in-flight step can touch it).
        Victim order is the policy: "lru" takes the least-recently-
        published page; "cost" takes the page cheapest to recompute
        (`_evict_cost`), stamp-then-page-id as the deterministic
        tiebreak."""
        cands = [
            (stamp, p)
            for p, (stamp, wid) in self._pub_only.items()
            if wid <= self._window_closed and self._page_home(p) == h
        ]
        if not cands:
            raise PagePoolExhausted(
                f"host {h}: no evictable publication-only page"
            )
        if self.prefix_evict == "cost":
            _, _, page = min(
                (self._evict_cost(p), stamp, p) for stamp, p in cands
            )
        else:
            _, page = min(cands)
        del self._pub_only[page]
        self._page_spans.pop(page, None)
        key = self._page_keys.pop(page, None)
        if key is not None and self._prefix_index.get(key) == page:
            del self._prefix_index[key]
        heapq.heappush(self._free_pages_h[h], page)
        self.prefix_evictions += 1

    def _pop_free_page(self, h: int) -> int:
        """The one pop path for host `h`'s free-page heap: when the
        heap is dry, evict a publication-only prefix page to refill it
        — live requests are ALWAYS served from published-but-idle
        capacity before anyone is swapped or preempted."""
        if not self._free_pages_h[h]:
            self._evict_prefix_page(h)
        return heapq.heappop(self._free_pages_h[h])

    def _decref_entry(self, slot: int, pi: int) -> None:
        """Clear one block-table entry: sentinel the mapping, settle the
        shared flag and held count, and decref the page."""
        page = int(self.block_tables[slot, pi])
        if page == self.spec.num_pages:
            return
        self.block_tables[slot, pi] = self.spec.num_pages
        self._held[slot] -= 1
        if self._entry_shared[slot, pi]:
            self._entry_shared[slot, pi] = False
            self._shared[slot] -= 1
        self._decref_page(page)

    @staticmethod
    def _chain_key(prev: bytes, tokens) -> bytes:
        """Key of a full page holding `tokens`, chained on the previous
        page's key — equal keys mean equal page content AND equal prefix
        up to this page, which is exactly what makes the page's KV rows
        (a pure function of the tokens at and before it) reusable."""
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(np.asarray(tokens, dtype=np.int64).tobytes())
        return h.digest()

    def match_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Longest run of registered pages covering a prefix of `tokens`
        (full pages only — partial pages are never shared). Read-only."""
        pages: List[int] = []
        if not self.prefix_cache:
            return pages
        ps = self.spec.page_size
        key = b""
        for i in range(len(tokens) // ps):
            key = self._chain_key(key, tokens[i * ps : (i + 1) * ps])
            page = self._prefix_index.get(key)
            if page is None:
                break
            pages.append(page)
        return pages

    def register_prefix(self, slot: int, tokens: Sequence[int], upto) -> None:
        """Publish `slot`'s full pages covering tokens[:upto] in the
        hash index so later admissions can map them. Idempotent; only
        pages whose content is fully written (upto capped at the slot's
        visible length) are published, and a content collision dedupes
        to the page already in the index."""
        if not self.prefix_cache:
            return
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        ps = self.spec.page_size
        upto = min(int(upto), len(tokens), int(self.lengths[slot]))
        key = b""
        for i in range(upto // ps):
            key = self._chain_key(key, tokens[i * ps : (i + 1) * ps])
            page = int(self.block_tables[slot, i])
            if page == self.spec.num_pages:
                break
            if key in self._prefix_index or page in self._page_keys:
                continue
            self._prefix_index[key] = page
            self._page_keys[page] = key
            # chain-start cursor: page i of the chain covers tokens
            # [i*ps, (i+1)*ps) — what the cost eviction policy prices
            self._page_spans[page] = i * ps

    def alloc_shared(
        self,
        tokens: Sequence[int],
        prompt_len: Optional[int] = None,
        total_len: Optional[int] = None,
        optimistic: bool = False,
    ) -> Optional[Tuple[int, int]]:
        """Admit a sequence with prefix sharing: registered pages whose
        chained content hash matches a prefix of `tokens` are MAPPED
        (refcounted) instead of allocated, and the caller receives
        `(slot, cursor)` — the cache cursor past the shared content, so
        prefill recomputes only tokens[cursor:]. At least one token is
        always left to recompute (the request needs sampling logits), so
        a whole-prompt match gets cursor len(tokens)-1 and its first
        write copy-on-writes the final shared page. `prompt_len` is the
        prompt span allocated eagerly (0 under token-budget chunking —
        chunks claim lazily); shared pages are mapped eagerly either
        way. Falls back to plain `alloc` semantics when the prefix cache
        is off (returns cursor 0). None when admission is refused."""
        spec = self.spec
        ntok = len(tokens)
        if prompt_len is None:
            prompt_len = ntok
        total = max(ntok, prompt_len, total_len if total_len is not None else 0)
        if total > spec.max_len:
            raise ValueError(
                f"sequence of {total} tokens exceeds max_len {spec.max_len}"
            )
        if not self.prefix_cache:
            slot = self.alloc(prompt_len, total, optimistic=optimistic)
            return None if slot is None else (slot, 0)
        ps = spec.page_size
        matched_all = self.match_prefix(tokens)
        # Host choice with page locality: a slot only maps shared pages
        # its OWN host's pool shard holds (the match truncates at the
        # first foreign page — cross-host prefix sharing would alias
        # pages onto another host's devices). Longest usable match wins,
        # then admission headroom, then lowest host id. Single-host runs
        # reduce to the full match and the old admission check exactly.
        best = None  # (m, avail, -h) ordering via explicit compare
        for h in range(self.num_hosts):
            if h in self._hosts_down or not self._free_slots_h[h]:
                continue
            m_h = 0
            for page in matched_all:
                if self._page_home(page) != h:
                    break
                m_h += 1
            cursor_h = min(m_h * ps, max(0, ntok - 1))
            fresh_h = max(0, self._pages_for(prompt_len) - m_h)
            max_p_h = self._pages_for(total) - (cursor_h // ps)
            need_h = fresh_h if optimistic else max_p_h
            # matched publication-only pages are about to be RESURRECTED
            # (mapped, not evicted), so the headroom they contribute as
            # eviction candidates is not really there for this admission
            avail = self._host_avail(h) - sum(
                1 for page in matched_all[:m_h] if page in self._pub_only
            )
            if avail < need_h:
                continue
            if best is None or (m_h, avail) > (best[0], best[1]):
                best = (m_h, avail, h)
        if best is None:
            return None
        m, _, h = best
        matched = matched_all[:m]
        cursor = min(m * ps, max(0, ntok - 1))
        # fresh pages popped now: the unshared remainder of the eager
        # prompt span; worst-case pool draws over the slot's lifetime:
        # every page from the cursor's page up to the total-length page
        # (the cursor page itself COWs when it is still shared — the
        # whole-prompt-match case)
        fresh_now = max(0, self._pages_for(prompt_len) - m)
        max_p = self._pages_for(total) - (cursor // ps)
        slot = heapq.heappop(self._free_slots_h[h])
        self._active.add(slot)
        for i, page in enumerate(matched):
            self._incref(slot, i, page)
        for i in range(m, m + fresh_now):
            self._install_page(slot, i, self._pop_free_page(h))
        self._held[slot] = m + fresh_now
        if optimistic:
            self._optimistic.add(slot)
            self._max_pages[slot] = fresh_now  # == owned
        else:
            self._max_pages[slot] = max_p
            self._reserved_h[h] += max_p - fresh_now
        self.lengths[slot] = cursor
        if m:
            self.prefix_hits += 1
        return slot, cursor

    def _cow_page(self, slot: int, pi: int) -> None:
        """First divergent write into a shared mapping: take the page
        over in place when this slot became its sole owner (unpublishing
        the now-divergent content), otherwise pop a fresh page, copy the
        shared page's rows (and int8 scales) across every layer pool,
        and swap the mapping — readers holding the old page see it
        untouched, and the functional pool threading orders the copy
        before any later step's reads."""
        page = int(self.block_tables[slot, pi])
        h = self.host_of_slot(slot)
        if self._refcounts[page] > 1:
            if slot in self._optimistic:
                if self._host_avail(h) < 1:
                    raise PagePoolExhausted(
                        f"free-page pool exhausted: optimistic slot {slot} "
                        f"needs a copy-on-write page but "
                        f"{len(self._free_pages_h[h])} free - "
                        f"{self._reserved_h[h]} "
                        "reserved leaves none"
                    )
            elif not self._free_pages_h[h] and not self._evictable_count(h):
                if self._limbo:
                    raise PagePoolExhausted(
                        f"free-page pool exhausted: {len(self._limbo)} pages "
                        "pinned by an in-flight step — reconcile the "
                        "pipeline to release them"
                    )
                raise PagePoolExhausted(
                    "free-page pool exhausted despite the admission reserve "
                    "— allocator invariant violated"
                )
            new = (
                heapq.heappop(self._free_pages_h[h])
                if self._free_pages_h[h]
                else self._pop_free_page(h)  # LRU-evict a retained page
            )
            # functional rebind (fresh dicts, whole-attribute swap), not
            # in-place entry mutation: any already-queued step read the
            # OLD array objects, which the .at[].set() copies leave
            # untouched — same discipline as commit()
            nk, nv = dict(self.k), dict(self.v)
            nks, nvs = dict(self.k_scale), dict(self.v_scale)
            for g in self.spec.layer_guids:
                nk[g] = nk[g].at[new].set(nk[g][page])
                nv[g] = nv[g].at[new].set(nv[g][page])
                if self.quantized:
                    nks[g] = nks[g].at[new].set(nks[g][page])
                    nvs[g] = nvs[g].at[new].set(nvs[g][page])
            self.k, self.v = nk, nv
            self.k_scale, self.v_scale = nks, nvs
            self.block_tables[slot, pi] = new
            self._refcounts[new] = 1
            self._refcounts[page] -= 1
            self.cow_copies += 1
        else:
            # sole owner now — the content is about to diverge, so the
            # index must stop advertising it
            key = self._page_keys.pop(page, None)
            if key is not None and self._prefix_index.get(key) == page:
                del self._prefix_index[key]
            self._page_spans.pop(page, None)
        self._entry_shared[slot, pi] = False
        self._shared[slot] -= 1
        if slot in self._optimistic:
            self._max_pages[slot] = self._owned(slot)
        elif self._owned(slot) <= self._max_pages[slot]:
            self._reserved_h[h] -= 1

    def ensure_position(self, slot: int, pos: int) -> None:
        """Make position `pos` of `slot` writable, claiming the next page
        from the free list when the sequence crosses a page boundary.
        For reserve-admitted slots the admission reserve guarantees the
        claim succeeds for any position inside the declared worst case;
        an optimistic slot's claim must additionally leave the reserve
        intact, and raises PagePoolExhausted when it cannot — the signal
        the scheduler answers with preemption-by-recompute. A position
        whose page is mapped but SHARED triggers the copy-on-write fork
        here — every dispatch path claims its write positions through
        this method, which is what makes it the single COW seam."""
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        pi = pos // self.spec.page_size
        if self.block_tables[slot, pi] != self.spec.num_pages:
            if self._entry_shared[slot, pi]:
                self._cow_page(slot, pi)
            return
        h = self.host_of_slot(slot)
        if slot in self._optimistic:
            if self._host_avail(h) < 1:
                raise PagePoolExhausted(
                    f"free-page pool exhausted: optimistic slot {slot} "
                    f"needs a page but {len(self._free_pages_h[h])} free - "
                    f"{self._reserved_h[h]} reserved leaves none"
                )
            self._install_page(slot, pi, self._pop_free_page(h))
            self._held[slot] += 1
            self._max_pages[slot] = self._owned(slot)
            return
        if not self._free_pages_h[h] and not self._evictable_count(h):
            if self._limbo:
                raise PagePoolExhausted(
                    f"free-page pool exhausted: {len(self._limbo)} pages "
                    "pinned by an in-flight step — reconcile the pipeline "
                    "to release them"
                )
            raise PagePoolExhausted(
                "free-page pool exhausted despite the admission reserve — "
                "allocator invariant violated"
            )
        self._install_page(slot, pi, self._pop_free_page(h))
        self._held[slot] += 1
        if self._owned(slot) <= self._max_pages[slot]:
            self._reserved_h[h] -= 1

    def truncate(
        self, slot: int, new_len: int, src_rows: Optional[Sequence[int]] = None
    ) -> None:
        """Roll the slot's visible length to `new_len` and return every
        page past ceil(new_len / page_size) to the free list — the
        speculative-decode rollback (verify claims pages for all k+1
        drafted rows; acceptance keeps a prefix). Returned pages go back
        under the slot's admission reserve (`_reserved` grows by exactly
        the pages released, capped at the slot's declared worst case), so
        the preemption-free accounting holds across rollback: a future
        re-growth of this slot re-claims from a pool that still covers
        every in-flight worst case. new_len may exceed the current
        length (verify commits accepted rows through this call) but
        never the pages the slot actually holds.

        src_rows (tree-verify commit): the accepted root-to-leaf rows'
        absolute positions, compacted into [new_len - len(src_rows),
        new_len) through the block table BEFORE the dead branches' pages
        are released — see KVCache.truncate for the contract. On int8
        pools the moved rows dequantize with their source page's scale
        and requantize under the destination page's; a destination page
        whose FIRST row is among the moves re-derives its scale from
        that row (the _quant_scatter claim rule), so the committed pool
        bytes match what a sequential decode of the accepted path would
        have produced up to the int8 round trip."""
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        if not 0 <= new_len <= self.spec.max_len:
            raise ValueError(
                f"new_len {new_len} outside [0, {self.spec.max_len}]"
            )
        keep = self._pages_for(new_len)
        if keep > self._held[slot]:
            raise ValueError(
                f"new_len {new_len} needs {keep} pages but slot {slot} "
                f"holds {int(self._held[slot])}"
            )
        if src_rows is not None and len(src_rows):
            self._compact_rows(slot, new_len, src_rows)
        old_resv = max(0, int(self._max_pages[slot]) - self._owned(slot))
        for pi in range(keep, self.spec.max_pages_per_seq):
            self._decref_entry(slot, pi)
        if slot in self._optimistic:
            # released pages return to the COMMON pool, not a reserve
            self._max_pages[slot] = self._owned(slot)
        else:
            self._reserved_h[self.host_of_slot(slot)] += (
                max(0, int(self._max_pages[slot]) - self._owned(slot))
                - old_resv
            )
        self.lengths[slot] = new_len

    def _compact_rows(
        self, slot: int, new_len: int, src_rows: Sequence[int]
    ) -> None:
        """Move the accepted tree rows into the contiguous tail of the
        committed prefix, resolving positions through the block table.
        Every touched page is exclusively owned: the verify claimed (and
        COW-forked where needed) each window page via ensure_position
        before writing it, so the row copies never leak into a shared
        prefix page. Functional rebind with gather-before-scatter, as in
        _cow_page/commit — queued steps keep reading the old pools."""
        import jax.numpy as jnp

        spec = self.spec
        ps = spec.page_size
        srcs = [int(p) for p in src_rows]
        dests = list(range(new_len - len(srcs), new_len))
        if dests[0] < 0:
            raise ValueError(
                f"{len(srcs)} compacted rows do not fit under new_len "
                f"{new_len}"
            )
        sentinel = spec.num_pages

        def flat(pos: int) -> int:
            page = int(self.block_tables[slot, pos // ps])
            if page >= sentinel:
                raise ValueError(
                    f"slot {slot} position {pos} has no mapped page"
                )
            return page * ps + pos % ps

        for s, d in zip(srcs, dests):
            if not d <= s < spec.max_len:
                raise ValueError(
                    f"source row {s} outside [{d}, {spec.max_len})"
                )
        if srcs == dests:
            return
        sf = np.asarray([flat(p) for p in srcs], dtype=np.int32)
        df = np.asarray([flat(p) for p in dests], dtype=np.int32)
        src_page = sf // ps
        dst_page = df // ps
        si = jnp.asarray(sf)
        di = jnp.asarray(df)
        nk, nv = dict(self.k), dict(self.v)
        if not self.quantized:
            for g in spec.layer_guids:
                kf = nk[g].reshape(-1, spec.num_heads, spec.head_dim)
                vf = nv[g].reshape(-1, spec.num_heads, spec.head_dim)
                nk[g] = kf.at[di].set(kf[si]).reshape(nk[g].shape)
                nv[g] = vf.at[di].set(vf[si]).reshape(nv[g].shape)
            self.k, self.v = nk, nv
            return
        # int8 pools: dequant with the source page's scale, requantize
        # under the destination page's. A destination page whose first
        # row moves re-derives its scale from that row — the same claim
        # rule _quant_scatter applies on sequential writes, so scales
        # (and bytes) come out as a linear decode of the path would
        first = (df % ps == 0)[:, None]  # [a, 1] page-initial dests
        spi = jnp.asarray(src_page)
        dpi = jnp.asarray(dst_page)
        firstj = jnp.asarray(first)
        nks, nvs = dict(self.k_scale), dict(self.v_scale)

        def requant(pool, scale):
            f = pool.reshape(-1, spec.num_heads, spec.head_dim)
            deq = f[si].astype(jnp.float32) * scale[spi][:, :, None]
            amax = jnp.max(jnp.abs(deq), axis=-1)  # [a, heads]
            cand = jnp.zeros_like(scale).at[dpi].max(
                jnp.where(firstj, amax / 127.0, 0.0)
            )
            claimed = jnp.zeros_like(scale).at[dpi].max(
                jnp.where(firstj, 1.0, 0.0)
            )
            new_scale = jnp.where(claimed > 0.0, cand, scale)
            s = new_scale[dpi]  # [a, heads]
            safe = jnp.where(s > 0.0, s, 1.0)
            q = jnp.clip(
                jnp.round(deq / safe[:, :, None]), -127, 127
            ).astype(pool.dtype)
            return f.at[di].set(q).reshape(pool.shape), new_scale

        for g in spec.layer_guids:
            nk[g], nks[g] = requant(nk[g], nks[g])
            nv[g], nvs[g] = requant(nv[g], nvs[g])
        self.k, self.v = nk, nv
        self.k_scale, self.v_scale = nks, nvs

    def free(self, slot: int) -> None:
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        self._active.remove(slot)
        owned_before = self._owned(slot)
        for pi in range(self.spec.max_pages_per_seq):
            self._decref_entry(slot, pi)
        if slot in self._optimistic:
            self._optimistic.discard(slot)
        else:
            self._reserved_h[self.host_of_slot(slot)] -= max(
                0, int(self._max_pages[slot]) - owned_before
            )
        self._held[slot] = 0
        self._max_pages[slot] = 0
        self.lengths[slot] = 0
        heapq.heappush(self._free_slots_h[self.host_of_slot(slot)], slot)

    # -- KV swap-to-host (swap vs recompute preemption) ----------------------

    def swap_bytes_for(self, slot: int) -> int:
        """Host bytes one swap-out of `slot` would stage: its held
        pages' K/V rows across every layer, plus the int8 fp32 scale
        slivers — the bytes_moved the cost model prices against one
        recompute prefill."""
        spec = self.spec
        per_page = (
            2 * spec.itemsize * spec.page_size * spec.num_heads * spec.head_dim
        )
        if self.quantized:
            per_page += 2 * 4 * spec.num_heads
        return int(self._held[slot]) * per_page * len(spec.layer_guids)

    @property
    def swapped_pages(self) -> int:
        """Pages' worth of KV currently staged in host swap buffers."""
        return sum(int(rec["pages"]) for rec in self._swapped.values())

    def swap_out(self, slot: int) -> Optional[int]:
        """Stage `slot`'s committed pages (K/V pools AND int8 scale
        slivers, in block-table order) into host buffers, free the slot,
        and return a swap handle `swap_in` restores from. Returns None —
        the caller degrades to recompute-preemption — when an in-flight
        step could still write the slot's pages (the scheduler drains
        the pipeline first, so this is a belt-and-braces refusal) or
        when `swap_bytes_budget` would be exceeded. The staged copy is
        the COMMITTED pool content, so a restore resumes decoding with
        value-identical KV rows — no re-prefill."""
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        if self._inflight_depth > 0:
            return None
        bytes_staged = self.swap_bytes_for(slot)
        if (
            self.swap_bytes_budget
            and self._swap_bytes_held + bytes_staged > self.swap_bytes_budget
        ):
            return None
        sentinel = self.spec.num_pages
        pages = [int(p) for p in self.block_tables[slot] if p != sentinel]
        idx = np.asarray(pages, dtype=np.int32)
        hk: Dict[int, np.ndarray] = {}
        hv: Dict[int, np.ndarray] = {}
        hks: Dict[int, np.ndarray] = {}
        hvs: Dict[int, np.ndarray] = {}
        for g in self.spec.layer_guids:
            kp, vp = self.k[g], self.v[g]
            hk[g] = np.asarray(kp[idx])
            hv[g] = np.asarray(vp[idx])
            if self.quantized:
                ksp, vsp = self.k_scale[g], self.v_scale[g]
                hks[g] = np.asarray(ksp[idx])
                hvs[g] = np.asarray(vsp[idx])
        handle = self._swap_seq
        self._swap_seq += 1
        self._swapped[handle] = {
            "k": hk,
            "v": hv,
            "k_scale": hks,
            "v_scale": hvs,
            "length": int(self.lengths[slot]),
            "pages": len(pages),
            "bytes": bytes_staged,
        }
        self._swap_bytes_held += bytes_staged
        self.swap_outs += 1
        self.swap_bytes_total += bytes_staged
        self.free(slot)
        return handle

    def snapshot_swap(self, slot: int) -> Optional[Dict[str, object]]:
        """Non-destructive sibling of `swap_out` for the write-ahead
        journal: gather `slot`'s committed pages (K/V and int8 scales,
        block-table order) into a host record shaped exactly like
        `export_swap`'s — fingerprint included, so a RESTARTED engine's
        `import_swap` can adopt it — WITHOUT freeing the slot, touching
        the `_swapped` ledger, or spending swap budget (the record's
        bytes live in the journal file, not in this cache's staging
        buffers — hence no FX106/FX107 ledger discipline applies).
        Returns None while an in-flight step could still write the
        slot's pages: a snapshot of half-written rows would restore a
        torn sequence."""
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        if self._inflight_depth > 0:
            return None
        sentinel = self.spec.num_pages
        pages = [int(p) for p in self.block_tables[slot] if p != sentinel]
        idx = np.asarray(pages, dtype=np.int32)
        hk: Dict[int, np.ndarray] = {}
        hv: Dict[int, np.ndarray] = {}
        hks: Dict[int, np.ndarray] = {}
        hvs: Dict[int, np.ndarray] = {}
        for g in self.spec.layer_guids:
            kp, vp = self.k[g], self.v[g]
            hk[g] = np.asarray(kp[idx])
            hv[g] = np.asarray(vp[idx])
            if self.quantized:
                ksp, vsp = self.k_scale[g], self.v_scale[g]
                hks[g] = np.asarray(ksp[idx])
                hvs[g] = np.asarray(vsp[idx])
        return {
            "k": hk,
            "v": hv,
            "k_scale": hks,
            "v_scale": hvs,
            "length": int(self.lengths[slot]),
            "pages": len(pages),
            "bytes": self.swap_bytes_for(slot),
            "fingerprint": self._swap_fingerprint(),
        }

    def swap_in(
        self,
        handle: int,
        total_len: Optional[int] = None,
        optimistic: bool = False,
    ) -> Optional[int]:
        """Restore a swapped-out sequence: claim a fresh slot and pages
        on any alive host, scatter the staged rows back into the pools
        (functional rebind, same discipline as `_cow_page`), and set the
        slot's length to the staged length — the stream resumes with a
        plain decode, token- and logit-identical to never-swapped.
        `total_len` sizes the growth reserve exactly like `alloc`'s;
        None means no host can admit (the handle stays valid for a
        later retry or `discard_swap`)."""
        rec = self._swapped.get(handle)
        if rec is None:
            raise KeyError(f"unknown swap handle {handle}")
        spec = self.spec
        n = int(rec["pages"])
        total = max(int(rec["length"]), total_len if total_len else 0)
        if total > spec.max_len:
            raise ValueError(
                f"sequence of {total} tokens exceeds max_len {spec.max_len}"
            )
        max_p = max(n, self._pages_for(total))
        h = self._pick_host(n if optimistic else max_p)
        if h is None:
            return None
        rec = self._swapped.pop(handle)
        self._swap_bytes_held -= int(rec["bytes"])
        slot = heapq.heappop(self._free_slots_h[h])
        self._active.add(slot)
        pages = [self._pop_free_page(h) for _ in range(n)]
        for i, page in enumerate(pages):
            self._install_page(slot, i, page)
        self._held[slot] = n
        if optimistic:
            self._optimistic.add(slot)
            self._max_pages[slot] = n
        else:
            self._max_pages[slot] = max_p
            self._reserved_h[h] += max_p - n
        self.lengths[slot] = int(rec["length"])
        if n:
            import jax.numpy as jnp

            idx = np.asarray(pages, dtype=np.int32)
            hk, hv = rec["k"], rec["v"]
            hks, hvs = rec["k_scale"], rec["v_scale"]
            nk, nv = dict(self.k), dict(self.v)
            nks, nvs = dict(self.k_scale), dict(self.v_scale)
            for g in spec.layer_guids:
                nk[g] = nk[g].at[idx].set(jnp.asarray(hk[g]))
                nv[g] = nv[g].at[idx].set(jnp.asarray(hv[g]))
                if self.quantized:
                    nks[g] = nks[g].at[idx].set(jnp.asarray(hks[g]))
                    nvs[g] = nvs[g].at[idx].set(jnp.asarray(hvs[g]))
            self.k, self.v = nk, nv
            self.k_scale, self.v_scale = nks, nvs
        self.swap_ins += 1
        self.swap_bytes_total += int(rec["bytes"])
        return slot

    def discard_swap(self, handle: int) -> None:
        """Drop a staged swap record (terminal request, or a swap-in
        degraded to recompute): its host bytes return to the budget.
        Unknown handles are ignored — discard races are expected."""
        rec = self._swapped.pop(handle, None)
        if rec is not None:
            self._swap_bytes_held -= int(rec["bytes"])

    # -- cross-engine handoff (prefill tier -> decode tier) ------------------

    def _swap_fingerprint(self) -> Tuple:
        """The geometry a staged record's rows are shaped by — two
        caches exchange swap records only when these agree (heads/dim/
        page_size fix the row shape, layer_guids the per-layer keys,
        kv_dtype the int8 scale slivers)."""
        spec = self.spec
        return (
            tuple(spec.layer_guids),
            spec.page_size,
            spec.num_heads,
            spec.head_dim,
            spec.kv_dtype,
        )

    def export_swap(self, handle: int) -> Dict[str, object]:
        """Surrender a staged swap record for restoration in ANOTHER
        engine's cache (the prefill->decode handoff): pops the record —
        the handle dies here, so a staged copy can be consumed exactly
        once (fxlint FX108's contract) — returns the staged bytes to
        this cache's budget, and stamps a geometry fingerprint
        `import_swap` validates. Raises KeyError on an unknown or
        already-consumed handle: double export IS the bug class."""
        rec = self._swapped.pop(handle)
        self._swap_bytes_held -= int(rec["bytes"])
        out = dict(rec)
        out["fingerprint"] = self._swap_fingerprint()
        return out

    def import_swap(self, record: Dict[str, object]) -> Optional[int]:
        """Adopt a record `export_swap` produced on a geometry-
        compatible cache: install it under a fresh LOCAL handle (the
        source handle died at export) against this cache's swap budget.
        Returns the new handle — `swap_in` then restores it exactly
        like a locally staged victim, bit-exact rows and int8 scales
        included — or None when the budget refuses (the record stays
        the caller's, to retry or degrade to recompute). Raises
        ValueError on a geometry mismatch: restoring rows shaped by a
        different page/head layout would scatter garbage."""
        rec = dict(record)
        fp = rec.pop("fingerprint", None)
        if fp is not None and tuple(fp) != self._swap_fingerprint():
            raise ValueError(
                f"import_swap: incompatible cache geometry {fp} vs "
                f"{self._swap_fingerprint()}"
            )
        bytes_staged = int(rec["bytes"])
        if (
            self.swap_bytes_budget
            and self._swap_bytes_held + bytes_staged > self.swap_bytes_budget
        ):
            return None
        handle = self._swap_seq
        self._swap_seq += 1
        self._swapped[handle] = rec
        self._swap_bytes_held += bytes_staged
        return handle

    def commit(
        self,
        new_k: Dict[int, object],
        new_v: Dict[int, object],
        new_k_scale: Optional[Dict[int, object]] = None,
        new_v_scale: Optional[Dict[int, object]] = None,
    ):
        """Swap in the pools a jitted step returned (and, under int8,
        the scale side pools the step's scatter-max may have claimed)."""
        self.k = dict(new_k)
        self.v = dict(new_v)
        if new_k_scale is not None:
            self.k_scale = dict(new_k_scale)
        if new_v_scale is not None:
            self.v_scale = dict(new_v_scale)

    def telemetry_gauges(self) -> Dict[str, float]:
        """Point-in-time allocator gauges for the telemetry sampler:
        UNIQUE pages live in block tables (refcount >= 1 — a shared
        mapping rides an already-live page, so it adds to
        `kv_prefix_pages_shared`, not to live), pages pinned in the
        in-flight limbo list, free-heap depth, the reserve ledger, and
        pool occupancy. These are the SAME ledgers `check_invariants`
        audits, so live + pinned + free (+ injector-stolen) always
        covers the pool — the conservation law the KV-gauge tests
        re-derive from the block tables themselves."""
        spec = self.spec
        live = int((self._refcounts > 0).sum())
        return {
            "kv_slots_active": len(self._active),
            "kv_slots_free": self.num_free,
            "kv_rows_used": int(self.lengths.sum()),
            "kv_occupancy": live / spec.num_pages if spec.num_pages else 0.0,
            "kv_pages_live": live,
            "kv_pages_pinned": len(self._limbo),
            "kv_free_heap_depth": self.num_free_pages,
            "kv_pages_reserved": int(self._reserved),
            "kv_inflight_depth": self._inflight_depth,
            "kv_prefix_pages_shared": int(self._shared.sum()),
            "kv_swapped_pages": self.swapped_pages,
            "kv_pages_pub_only": len(self._pub_only),
        }

    def telemetry_gauges_host(self, h: int) -> Dict[str, float]:
        """The per-host slice of the allocator gauges — sampled under a
        `host` label when the placement runs more than one host
        partition. Sums across hosts equal the unlabelled series."""
        lo, hi = h * self._pages_per_host, (h + 1) * self._pages_per_host
        live = int((self._refcounts[lo:hi] > 0).sum())
        return {
            "kv_slots_active": sum(
                1 for s in self._active if self.host_of_slot(s) == h
            ),
            "kv_slots_free": len(self._free_slots_h[h]),
            "kv_pages_live": live,
            "kv_pages_pinned": sum(
                1 for p, _ in self._limbo if self._page_home(p) == h
            ),
            "kv_free_heap_depth": len(self._free_pages_h[h]),
            "kv_pages_reserved": int(self._reserved_h[h]),
            "kv_pages_pub_only": sum(
                1 for p in self._pub_only if self._page_home(p) == h
            ),
        }

    def telemetry_counters(self) -> Dict[str, int]:
        """Monotonic allocator counters for the telemetry sampler."""
        return {
            "kv_prefix_hits_total": self.prefix_hits,
            "kv_cow_copies_total": self.cow_copies,
            "kv_swap_out_total": self.swap_outs,
            "kv_swap_in_total": self.swap_ins,
            "kv_swap_bytes_total": self.swap_bytes_total,
            "kv_prefix_evictions_total": self.prefix_evictions,
        }

    def check_invariants(self, extra_free: int = 0) -> None:
        """Assert the page allocator's full accounting is consistent —
        the chaos-harness probe (tests/test_resilience.py,
        bench_serve.py --chaos) calls this after every iteration.
        `extra_free` is pages a fault injector is deliberately holding
        outside the pool (faults.FaultInjector page-steal), which the
        conservation check must count."""
        spec = self.spec
        sentinel = spec.num_pages
        refs = np.zeros(spec.num_pages, dtype=np.int64)
        owners = np.zeros(spec.num_pages, dtype=np.int64)
        for s in range(spec.max_seqs):
            row = [int(p) for p in self.block_tables[s] if p != sentinel]
            for pi in range(spec.max_pages_per_seq):
                p = int(self.block_tables[s, pi])
                if p == sentinel:
                    # shared flags only mark real mappings
                    assert not self._entry_shared[s, pi]
                    continue
                refs[p] += 1
                if not self._entry_shared[s, pi]:
                    owners[p] += 1
            # per-slot ledgers match the table; free slots hold nothing
            assert len(row) == int(self._held[s])
            assert int(self._entry_shared[s].sum()) == int(self._shared[s])
            if s not in self._active:
                assert not row and self.lengths[s] == 0
            else:
                # visible length fits in the held pages
                assert int(self.lengths[s]) <= len(row) * spec.page_size
        # the refcount ledger re-derives exactly from the live block
        # tables, and a multiply-referenced page has at most one OWNING
        # (unshared) mapping — everyone else must COW before writing
        assert np.array_equal(refs, self._refcounts.astype(np.int64))
        assert (owners <= 1).all()
        live = {p for p in range(spec.num_pages) if refs[p] > 0}
        # publication-only retained pages: refcount 0 (no table maps
        # them), still published (matchable), off the free heap — a
        # fourth disjoint population the conservation law must count.
        # They exist only under an eviction policy.
        pub_only = set(self._pub_only)
        assert not pub_only or self.prefix_evict != "none"
        for p in pub_only:
            assert refs[p] == 0
            assert p in self._page_keys
        # conservation over UNIQUE pages: live + free + in-flight limbo
        # + publication-only retained (+ injector-held) is the whole
        # pool; free/limbo/retained pages carry no references
        limbo = [p for p, _ in self._limbo]
        free_all = [p for hp in self._free_pages_h for p in hp]
        assert len(limbo) == len(set(limbo))
        assert live.isdisjoint(free_all)
        assert live.isdisjoint(limbo)
        assert set(limbo).isdisjoint(free_all)
        assert pub_only.isdisjoint(free_all)
        assert pub_only.isdisjoint(limbo)
        assert len(live) + len(free_all) + len(limbo) + len(pub_only) + (
            extra_free
        ) == spec.num_pages
        # host-partition purity: every free heap holds only its own
        # host's pages, every slot heap its own host's slots, and every
        # mapped page lives on its slot's home host (alloc_shared
        # truncates prefix matches at the first foreign page to keep
        # this true) — the property that makes per-host free views a
        # sound admission signal. Per-host conservation pins the
        # injector's stolen pages (single-host harness) to host 0.
        for h in range(self.num_hosts):
            assert all(self._page_home(p) == h for p in self._free_pages_h[h])
            assert all(
                self.host_of_slot(s) == h for s in self._free_slots_h[h]
            )
            live_h = sum(1 for p in live if self._page_home(p) == h)
            limbo_h = sum(1 for p in limbo if self._page_home(p) == h)
            pub_h = sum(1 for p in pub_only if self._page_home(p) == h)
            assert live_h + len(self._free_pages_h[h]) + limbo_h + pub_h + (
                extra_free if h == 0 else 0
            ) == self._pages_per_host
        for s in self._active:
            hs = self.host_of_slot(s)
            for p in self.block_tables[s]:
                if int(p) != sentinel:
                    assert self._page_home(int(p)) == hs
        # the hash index only advertises live or publication-only
        # retained pages, bijectively with its reverse map
        assert len(self._prefix_index) == len(self._page_keys)
        for key, p in self._prefix_index.items():
            assert self._page_keys.get(p) == key
            assert refs[p] > 0 or p in pub_only
        # limbo pages only exist while an in-flight window is open
        assert self._inflight_depth >= 0
        if self._limbo:
            assert self._inflight_depth > 0
        # the reserve ledger re-derives from the per-slot worst cases
        # over OWNED pages (shared mappings cost the pool nothing until
        # they COW — and their COW page is part of the worst case),
        # counting only reserve-admitted slots, and never promises pages
        # the pool doesn't have (limbo pages still honor the promise —
        # they return to the heap before any claim that needs them, the
        # async scheduler's drain-before-preempt rule)
        for h in range(self.num_hosts):
            resv_h = sum(
                max(0, int(self._max_pages[s]) - self._owned(s))
                for s in self._active
                if s not in self._optimistic and self.host_of_slot(s) == h
            )
            assert resv_h == self._reserved_h[h]
            limbo_h = sum(1 for p in limbo if self._page_home(p) == h)
            pub_h = sum(1 for p in pub_only if self._page_home(p) == h)
            assert 0 <= self._reserved_h[h] <= (
                len(self._free_pages_h[h])
                + limbo_h
                + pub_h
                + (extra_free if h == 0 else 0)
            )
        # optimistic slots never carry a growth reserve
        for s in self._optimistic:
            assert s in self._active
            assert int(self._max_pages[s]) == self._owned(s)
        # slot bookkeeping
        free_slots_all = [s for hs in self._free_slots_h for s in hs]
        assert self._active.isdisjoint(free_slots_all)
        assert len(self._active) + len(free_slots_all) == spec.max_seqs
        # swap ledger: the host-bytes counter re-derives from the
        # outstanding records and never exceeds the budget
        assert self._swap_bytes_held == sum(
            int(rec["bytes"]) for rec in self._swapped.values()
        )
        if self.swap_bytes_budget:
            assert self._swap_bytes_held <= self.swap_bytes_budget
        for rec in self._swapped.values():
            assert 0 <= int(rec["length"]) <= int(rec["pages"]) * spec.page_size
        # downed hosts are a subset of the partition
        assert all(0 <= h < self.num_hosts for h in self._hosts_down)

    # -- construction from a compiled model ---------------------------------

    @staticmethod
    def from_model(
        model,
        max_seqs: int,
        max_len: int,
        dtype=None,
        buckets: Optional[Sequence[int]] = None,
        page_size: int = 0,
        num_pages: int = 0,
        kv_dtype: str = "fp32",
        prefix_cache: bool = False,
        prefix_evict: str = "none",
        swap_bytes_budget: int = 0,
        evict_pricer=None,
    ) -> "PagedKVCache":
        """Derive geometry + shardings from a compiled FFModel. Defaults
        (page_size 0 / num_pages 0) pick the vLLM-style block size and a
        pool with EXACTLY the slot layout's capacity
        (max_seqs * max_len rows), so existing callers see identical
        byte footprint and admission behavior. kv_dtype "int8" selects
        the quantized pool variant (the dtype argument is ignored);
        prefix_cache=True turns the hashed prefix-page index on."""
        import jax.numpy as jnp

        if kv_dtype not in ("fp32", "int8"):
            raise ValueError(
                f"kv_dtype must be 'fp32' or 'int8', got {kv_dtype!r}"
            )
        guids, heads, head_dim, head_axis, executor = _derive_geometry(model)
        if page_size <= 0:
            page_size = default_page_size(max_len)
        if max_len % page_size:
            raise ValueError(
                f"max_len {max_len} is not divisible by page_size {page_size}"
            )
        if num_pages <= 0:
            num_pages = max_seqs * max_len // page_size
        spec = KVCacheSpec(
            layer_guids=tuple(guids),
            max_seqs=max_seqs,
            max_len=max_len,
            num_heads=heads,
            head_dim=head_dim,
            buckets=tuple(buckets) if buckets else default_buckets(max_len),
            page_size=page_size,
            num_pages=num_pages,
            kv_dtype=kv_dtype,
        )
        if dtype is None:
            dtype = jnp.float32
        placement = getattr(model, "serving_placement", None)
        if placement is not None:
            placement.validate_geometry(max_seqs, num_pages)
            shardings = placement.kv_sharding()
        else:
            shardings = _heads_sharding(executor, head_axis)
        return PagedKVCache(
            spec,
            dtype,
            shardings=shardings,
            prefix_cache=prefix_cache,
            placement=placement,
            prefix_evict=prefix_evict,
            swap_bytes_budget=swap_bytes_budget,
            evict_pricer=evict_pricer,
        )
