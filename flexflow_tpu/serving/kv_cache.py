"""Preallocated, slot-addressed KV cache for the serving engine.

One pair of `[max_seqs, max_len, heads, head_dim]` arrays per attention
layer (the FlexFlow Serve / vLLM "static" layout — a fixed HBM footprint
the scheduler packs requests into, instead of per-request tensors that
fragment and force recompiles). A *slot* is one row of the leading dim:
admission allocates a slot, EOS/max-tokens frees it, and the decode step
always runs at the full `[max_seqs, 1]` shape so there is exactly ONE
compiled decode program regardless of how many requests are in flight.

Prompt lengths are *bucketed*: prefill pads each admission batch's
prompts up to the next bucket (powers of two by default), so the number
of compiled prefill programs is bounded by the bucket count, not by the
number of distinct prompt lengths the traffic happens to contain.

Sharding: the cache derives its specs from the compiled model's
ParallelTensor annotations — if the strategy shards attention heads (the
head-parallel replica-dim rewrite, ops/attention.py), the cache's heads
dim rides the same mesh axis, so TP-over-heads serving (the decode
search's batch-1 winner, search/auto.py optimize_serving) keeps each
chip's cache slice local.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flexflow_tpu.core.types import OperatorType


def default_buckets(max_len: int, smallest: int = 16) -> Tuple[int, ...]:
    """Powers of two from `smallest` up to (and including) max_len."""
    out = []
    b = smallest
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Static geometry of the cache, derived from the compiled model."""

    layer_guids: Tuple[int, ...]  # MHA node guids, topo order
    max_seqs: int
    max_len: int
    num_heads: int
    head_dim: int
    buckets: Tuple[int, ...]

    def bucket(self, length: int) -> int:
        """Smallest bucket >= length (prefill pad target)."""
        for b in self.buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt length {length} exceeds max_len {self.max_len}"
        )

    @property
    def bytes_per_layer(self) -> int:
        return 2 * 4 * self.max_seqs * self.max_len * self.num_heads * self.head_dim


class KVCache:
    """Device arrays + host-side slot bookkeeping.

    The arrays are functional (each engine step returns fresh ones;
    `commit` swaps them in); the slot free-list and per-slot lengths are
    plain host state the scheduler mutates between steps.
    """

    def __init__(self, spec: KVCacheSpec, dtype, shardings=None):
        import jax
        import jax.numpy as jnp

        self.spec = spec
        self.dtype = dtype
        shape = (spec.max_seqs, spec.max_len, spec.num_heads, spec.head_dim)
        self.k: Dict[int, object] = {}
        self.v: Dict[int, object] = {}
        for g in spec.layer_guids:
            k = jnp.zeros(shape, dtype)
            v = jnp.zeros(shape, dtype)
            if shardings is not None:
                k = jax.device_put(k, shardings)
                v = jax.device_put(v, shardings)
            self.k[g] = k
            self.v[g] = v
        # host bookkeeping: lengths[i] = tokens currently cached in slot i
        self.lengths = np.zeros(spec.max_seqs, dtype=np.int32)
        self._free: List[int] = list(range(spec.max_seqs - 1, -1, -1))
        self._active: set = set()

    # -- slot management (host side) ----------------------------------------

    @property
    def num_active(self) -> int:
        return len(self._active)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def active_slots(self) -> List[int]:
        return sorted(self._active)

    def alloc(self) -> Optional[int]:
        """Take a free slot (None when full). Lowest-index-last pop so slot
        ids stay dense and deterministic under a fixed request stream."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._active.add(slot)
        self.lengths[slot] = 0
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        self._active.remove(slot)
        self.lengths[slot] = 0
        self._free.append(slot)
        self._free.sort(reverse=True)

    def commit(self, new_k: Dict[int, object], new_v: Dict[int, object]):
        """Swap in the arrays a jitted step returned."""
        self.k = dict(new_k)
        self.v = dict(new_v)

    # -- construction from a compiled model ---------------------------------

    @staticmethod
    def from_model(
        model,
        max_seqs: int,
        max_len: int,
        dtype=None,
        buckets: Optional[Sequence[int]] = None,
    ) -> "KVCache":
        """Derive geometry + shardings from a compiled FFModel.

        Every MULTIHEAD_ATTENTION node must agree on (heads, head_dim)
        — one cache block size per model, like the reference serve stack.
        The sharding comes from the Wq weight's head dim: if the chosen
        strategy partitioned heads (parallel_idx -> mesh axis), the cache
        heads dim shards on that axis; otherwise the cache is replicated.
        """
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        if model.executor is None:
            raise RuntimeError("compile() the model before building a KVCache")
        graph = model.graph
        executor = model.executor
        guids = [
            g
            for g in executor.topo
            if graph.nodes[g].op_type == OperatorType.MULTIHEAD_ATTENTION
        ]
        if not guids:
            raise ValueError("model has no attention layers to cache")
        geom = set()
        head_axis = None
        for g in guids:
            node = graph.nodes[g]
            heads = int(node.params["num_heads"])
            head_dim = int(node.params["embed_dim"]) // heads
            geom.add((heads, head_dim))
            wq = node.weight_shapes[0] if node.weight_shapes else None
            if wq is not None and len(wq.dims) == 3:
                hd = wq.dims[1]
                if hd.degree > 1 and 0 <= hd.parallel_idx < len(
                    executor.mesh_config.axis_names
                ):
                    head_axis = executor.mesh_config.axis_names[hd.parallel_idx]
        if len(geom) != 1:
            raise ValueError(
                f"attention layers disagree on (heads, head_dim): {geom}"
            )
        heads, head_dim = geom.pop()
        spec = KVCacheSpec(
            layer_guids=tuple(guids),
            max_seqs=max_seqs,
            max_len=max_len,
            num_heads=heads,
            head_dim=head_dim,
            buckets=tuple(buckets) if buckets else default_buckets(max_len),
        )
        # always place the cache on the mesh (replicated when heads are
        # not sharded): uncommitted fresh zeros would give the first
        # engine step a different jit signature than every later step
        # (committed jit outputs) and buy a pointless recompile
        shardings = NamedSharding(
            executor.mesh, PartitionSpec(None, None, head_axis, None)
        )
        if dtype is None:
            dtype = jnp.float32
        return KVCache(spec, dtype, shardings=shardings)
